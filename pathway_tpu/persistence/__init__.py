"""pw.persistence — input snapshots + resume (reference:
python/pathway/persistence/__init__.py Backend:27-99, Config:116; engine side
src/persistence/input_snapshot.rs:286, backends/mod.rs:76).

Model: each named connector's parsed events append to a chunked log at every
commit, together with the subject's own cursor state (file mtimes, offsets).
On restart the log replays into the engine as the first batch and the
subject resumes from its cursor — the reference's input-snapshot mode.
Operator snapshots (differential arrangement state) are subsumed here by
deterministic replay of the compact input log.
"""

from __future__ import annotations

import os
import pickle
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from pathway_tpu.internals import faults, memtrack
from pathway_tpu.internals import sanitizer as _sanitizer


def _store_fault(key: str) -> None:
    """Fault-injection hook on every backend write (store_fail directive);
    one boolean read when the harness is disarmed."""
    if faults.ACTIVE:
        faults.store_put(key)


# Bump whenever the meaning of persisted state changes — key derivation
# schemes, delta encodings, snapshot layouts.  Restores from a different
# version fall back to full replay instead of silently mixing old keys
# with new derivation (v2: FlattenNode key finalizer changed).
SNAPSHOT_FORMAT_VERSION = 2


def graph_fingerprint(engine) -> List[Tuple[int, str, str, int]]:
    """Stable per-node identity: (position, class name, operator name,
    input arity) for every engine node.  Restoring pickled operator state
    by index is only safe when the whole sequence matches — a changed
    filter predicate or two reordered operators keep the node COUNT equal
    while shifting what each index means."""
    return [
        (idx, type(node).__name__, getattr(node, "name", ""), len(node.inputs))
        for idx, node in enumerate(engine.nodes)
    ]


def _unpicklable_path(obj: Any, prefix: str = "state", depth: int = 4) -> Optional[str]:
    """Best-effort dotted path to the first unpicklable leaf inside a
    node's snapshot state, so the skip diagnostics say WHICH attribute
    disabled the snapshot (`state['accum'].lock`), not just which node.
    Returns None when `obj` pickles fine."""
    try:
        pickle.dumps(obj)
        return None
    except Exception:  # noqa: BLE001 — any pickle failure counts
        pass
    if depth > 0:
        if isinstance(obj, dict):
            items = [(f"{prefix}[{k!r}]", v) for k, v in obj.items()]
        elif isinstance(obj, (list, tuple)):
            items = [(f"{prefix}[{i}]", v) for i, v in enumerate(obj)]
        else:
            d = getattr(obj, "__dict__", None)
            items = (
                [(f"{prefix}.{k}", v) for k, v in d.items()] if d else []
            )
        for path, v in items:
            found = _unpicklable_path(v, path, depth - 1)
            if found is not None:
                return found
    return prefix


class PersistenceBackend:
    """K/V store interface (reference: persistence/backends/mod.rs:76)."""

    def put_value(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get_value(self, key: str) -> bytes | None:
        raise NotImplementedError

    def append(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def read_appended(self, key: str) -> List[bytes]:
        raise NotImplementedError

    def list_keys(self) -> List[str]:
        raise NotImplementedError

    def truncate(self, key: str) -> None:
        """Drop an append log / value (log compaction after an operator
        snapshot bakes its events into operator state)."""
        raise NotImplementedError


class FilesystemBackend(PersistenceBackend):
    def __init__(self, path: str):
        self.root = path
        os.makedirs(path, exist_ok=True)
        self._locks: Dict[str, threading.Lock] = {}

    def _path(self, key: str) -> str:
        safe = key.replace("/", "__")
        return os.path.join(self.root, safe)

    def put_value(self, key: str, value: bytes) -> None:
        _store_fault(key)
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def get_value(self, key: str) -> bytes | None:
        path = self._path(key)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def append(self, key: str, value: bytes) -> None:
        _store_fault(key)
        with open(self._path(key), "ab") as f:
            f.write(len(value).to_bytes(8, "little"))
            f.write(value)

    def read_appended(self, key: str) -> List[bytes]:
        path = self._path(key)
        if not os.path.exists(path):
            return []
        out = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                n = int.from_bytes(header, "little")
                chunk = f.read(n)
                if len(chunk) < n:
                    break  # torn tail write from a crash — ignore
                out.append(chunk)
        return out

    def list_keys(self) -> List[str]:
        return os.listdir(self.root)

    def truncate(self, key: str) -> None:
        path = self._path(key)
        if os.path.exists(path):
            os.remove(path)


class MockBackend(PersistenceBackend):
    """In-memory backend for tests (reference: backends/mock.rs)."""

    def __init__(self, store: Dict[str, Any] | None = None):
        self.values: Dict[str, bytes] = (store or {}).setdefault("values", {}) if isinstance(store, dict) else {}
        self.logs: Dict[str, List[bytes]] = {}
        if isinstance(store, dict):
            self.logs = store.setdefault("logs", {})

    def put_value(self, key, value):
        _store_fault(key)
        self.values[key] = value

    def get_value(self, key):
        return self.values.get(key)

    def append(self, key, value):
        _store_fault(key)
        self.logs.setdefault(key, []).append(value)

    def read_appended(self, key):
        return list(self.logs.get(key, []))

    def list_keys(self):
        return list(set(self.values) | set(self.logs))

    def truncate(self, key):
        self.values.pop(key, None)
        self.logs.pop(key, None)


class ObjectStoreBackend(PersistenceBackend):
    """Persistence over any object store with put/get/delete/list
    (reference: persistence/backends/s3.rs, azure.rs — a K/V trait over
    immutable objects).

    Objects are immutable, so `append` is emulated with numbered chunk
    objects under `<key>/log.<n>`; `read_appended` lists and sorts them.
    The client interface is minimal and injectable for tests:
    put(key, bytes), get(key) -> bytes|None, delete(key), list(prefix) ->
    [key]."""

    def __init__(self, client, prefix: str = ""):
        self.client = client
        self.prefix = prefix.strip("/")
        self._counters: Dict[str, int] = {}

    def _full(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put_value(self, key, value):
        _store_fault(key)
        self.client.put(self._full(key), value)

    def get_value(self, key):
        return self.client.get(self._full(key))

    def append(self, key, value):
        _store_fault(key)
        n = self._counters.get(key)
        if n is None:
            existing = self.client.list(self._full(key) + "/log.")
            n = len(existing)
        self.client.put(self._full(key) + f"/log.{n:08d}", value)
        self._counters[key] = n + 1

    def read_appended(self, key):
        names = sorted(self.client.list(self._full(key) + "/log."))
        out = []
        for name in names:
            blob = self.client.get(name)
            if blob is not None:
                out.append(blob)
        return out

    def list_keys(self):
        skip = len(self.prefix) + 1 if self.prefix else 0
        return [k[skip:] for k in self.client.list(self.prefix)]

    def truncate(self, key):
        for name in self.client.list(self._full(key) + "/log."):
            self.client.delete(name)
        self.client.delete(self._full(key))
        self._counters.pop(key, None)


class _Boto3ObjectClient:
    """S3 client adapter (gated on boto3; injectable fake in tests)."""

    def __init__(self, bucket: str, **kwargs):
        import boto3  # type: ignore

        self.bucket = bucket
        self.client = boto3.client("s3", **kwargs)

    def put(self, key, value):
        self.client.put_object(Bucket=self.bucket, Key=key, Body=value)

    def get(self, key):
        try:
            resp = self.client.get_object(Bucket=self.bucket, Key=key)
        except Exception:  # noqa: BLE001 — NoSuchKey and friends
            return None
        return resp["Body"].read()

    def delete(self, key):
        self.client.delete_object(Bucket=self.bucket, Key=key)

    def list(self, prefix):
        out = []
        paginator = self.client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                out.append(obj["Key"])
        return out


class _AzureBlobClient:
    """Azure Blob adapter (gated on azure-storage-blob)."""

    def __init__(self, container: str, connection_string: str | None = None, **kwargs):
        from azure.storage.blob import BlobServiceClient  # type: ignore

        if connection_string is not None:
            service = BlobServiceClient.from_connection_string(
                connection_string, **kwargs
            )
        else:
            service = BlobServiceClient(**kwargs)
        self.container = service.get_container_client(container)

    def put(self, key, value):
        self.container.upload_blob(key, value, overwrite=True)

    def get(self, key):
        try:
            return self.container.download_blob(key).readall()
        except Exception:  # noqa: BLE001
            return None

    def delete(self, key):
        try:
            self.container.delete_blob(key)
        except Exception:  # noqa: BLE001
            pass

    def list(self, prefix):
        return [
            b.name for b in self.container.list_blobs(name_starts_with=prefix)
        ]


class Backend:
    """Factory namespace (reference: persistence/__init__.py Backend:27)."""

    def __init__(self, engine_backend: PersistenceBackend):
        self._backend = engine_backend

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(FilesystemBackend(path))

    @classmethod
    def mock(cls, events: Dict | None = None) -> "Backend":
        return cls(MockBackend(events))

    @classmethod
    def s3(
        cls,
        root_path: str,
        bucket_settings=None,
        *,
        _client=None,
        **client_kwargs,
    ) -> "Backend":
        """root_path: s3://bucket/prefix (reference: backends/s3.rs).
        Tests inject `_client`; production uses boto3 credentials from the
        standard chain or `bucket_settings`."""
        bucket, _, prefix = root_path.removeprefix("s3://").partition("/")
        if _client is None:
            if isinstance(bucket_settings, dict):
                client_kwargs.update(bucket_settings)
            _client = _Boto3ObjectClient(bucket, **client_kwargs)
        return cls(ObjectStoreBackend(_client, prefix))

    @classmethod
    def azure(
        cls,
        root_path: str,
        *,
        account=None,
        password=None,
        connection_string=None,
        _client=None,
        **client_kwargs,
    ) -> "Backend":
        """root_path: az://container/prefix (reference: backends/azure.rs)."""
        container, _, prefix = root_path.removeprefix("az://").partition("/")
        if _client is None:
            _client = _AzureBlobClient(
                container, connection_string=connection_string, **client_kwargs
            )
        return cls(ObjectStoreBackend(_client, prefix))


class Config:
    """reference: persistence/__init__.py Config:116."""

    def __init__(
        self,
        backend: Backend,
        *,
        snapshot_interval_ms: int = 0,
        snapshot_access=None,
        persistence_mode=None,
        continue_after_replay: bool = True,
    ):
        self.backend = backend
        self.snapshot_interval_ms = snapshot_interval_ms
        self.persistence_mode = persistence_mode
        self.continue_after_replay = continue_after_replay

    # legacy alias used by reference code: Config.simple_config
    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend, **kwargs)

    # run-lifecycle hooks (reference: persistence Config.on_before_run /
    # on_after_run — env setup/teardown for cloud backends); the backend
    # gets first refusal so e.g. an S3 backend can stage credentials
    def on_before_run(self) -> None:
        hook = getattr(self.backend, "on_before_run", None)
        if hook is not None:
            hook()

    def on_after_run(self) -> None:
        hook = getattr(self.backend, "on_after_run", None)
        if hook is not None:
            hook()


class OperatorSnapshotManager:
    """Checkpoint operator state keyed by frontier + compact input logs
    (reference: src/persistence/operator_snapshot.rs:231 snapshot
    writer/merger, tracker.rs:51 frontier commit, dataflow/persist.rs).

    At a quiescent frontier (all node queues drained after `process_time`),
    every stateful node's `snapshot_state()` is pickled under an
    epoch-versioned key `opsnap/<worker>/<epoch>/<node-idx>`; the manifest
    is written LAST and names the epoch, so a crash mid-save leaves the old
    manifest pointing at the old epoch's intact blobs (commit-last
    atomicity, like the reference's snapshot writer). Before the event logs
    are truncated, their deltas merge into a *consolidated base log* per
    source — so even if a later restart cannot restore operator state (the
    graph changed, a blob is missing), full replay of base + tail loses
    nothing. Restore is two-phase: `load_states` reads and unpickles
    without mutating (multi-worker agreement can veto), `apply_states`
    commits. A node whose state fails to pickle is skipped (warn-once)
    and recorded in the manifest as `skipped_nodes`; such a snapshot
    still compacts the logs but restore refuses it (full replay of the
    consolidated base loses nothing). A backend write failure aborts the
    save entirely — the previous manifest and the event logs stay intact
    and the job continues."""

    def __init__(self, backend: PersistenceBackend, worker_id: int = 0):
        self.backend = backend
        self.worker_id = worker_id
        self.manifest_key = f"opsnap/{worker_id}/manifest"

    def _base_key(self, name: str, epoch: int) -> str:
        return f"snapshot/{self.worker_id}/{name}/base.{epoch:016d}"

    def _list_base_epochs(self, name: str) -> List[int]:
        marker = f"snapshot/{self.worker_id}/{name}/base.".replace("/", "__")
        out = []
        for key in self.backend.list_keys():
            flat = key.replace("/", "__")
            if flat.startswith(marker):
                try:
                    out.append(int(flat[len(marker):][:16]))
                except ValueError:
                    continue
        return sorted(set(out))

    def save(
        self, engine, time: int, writers: Dict[str, "InputSnapshotWriter"]
    ) -> bool:
        """Crash-safe ordering: (1) seal log segments, (2) stage the new
        consolidated bases and state blobs under epoch-versioned keys,
        (3) write the manifest — the single commit point, (4) clean up old
        segments/bases/blobs. A crash before (3) leaves the previous
        manifest + its intact epoch; a crash after (3) only leaves garbage
        that the next save deletes. Replay never double-applies because the
        manifest records `folded_through` per source and the restore path
        replays only later segments."""
        import logging

        states: List[Tuple[int, bytes]] = []
        skipped: List[int] = []
        for idx, node in enumerate(engine.nodes):
            state = node.snapshot_state()
            if state is None:
                continue
            try:
                states.append((idx, pickle.dumps(state)))
            except Exception as exc:  # noqa: BLE001 — unpicklable state
                # skip only this node: the manifest records it so restore
                # refuses the partial snapshot and full-replays instead
                skipped.append(idx)
                path = _unpicklable_path(state) or "state"
                warn_once = getattr(engine, "warn_once", None)
                msg = (
                    "operator snapshot skips node %d (%s): state does not "
                    "pickle at %s: %s"
                )
                if warn_once is not None:
                    warn_once(f"snapshot-unpicklable-{idx}", msg, idx,
                              node.name, path, exc)
                else:
                    logging.getLogger("pathway_tpu").warning(
                        msg, idx, node.name, path, exc
                    )
                # structured twin of the warn-once: a flight event naming
                # the offending attribute path (the static PWT904 finding
                # points at the same capture before the run ever starts)
                m = getattr(engine, "metrics", None)
                if m is not None:
                    m.recorder.record(
                        "snapshot_skip",
                        time=time,
                        node=idx,
                        name=f"{node.name}: unpicklable at {path}",
                        errors=1,
                    )

        if memtrack.ENABLED:
            # host-RAM staging footprint of this save (pickled state
            # blobs held until the manifest commits); the entry persists
            # as "what the last snapshot staged" and dies with the manager
            memtrack.tracker().register(
                "snapshot_staging",
                self,
                sum(len(blob) for _, blob in states),
                tier="host",
                nodes=len(states),
            )
        try:
            return self._save_committed(engine, time, writers, states, skipped)
        except Exception as exc:  # noqa: BLE001 — backend write failed
            logging.getLogger("pathway_tpu").warning(
                "operator snapshot at frontier %s failed (%s: %s); job "
                "continues, previous snapshot and event logs kept",
                time,
                type(exc).__name__,
                exc,
            )
            return False

    def _save_committed(
        self,
        engine,
        time: int,
        writers: Dict[str, "InputSnapshotWriter"],
        states: List[Tuple[int, bytes]],
        skipped: List[int],
    ) -> bool:
        from pathway_tpu.engine.stream import consolidate

        epoch = time
        folded_through: Dict[str, int] = {}
        for name, writer in writers.items():
            sealed = writer.start_new_segment()
            folded_through[name] = sealed
            prev_deltas, prev_seg = self.read_base(name)
            # fold sealed segments the previous base has not folded yet
            tail = [
                d
                for seg in writer.list_segments()
                if prev_seg < seg <= sealed
                for d in writer.read_segment(seg)
            ]
            merged = consolidate(prev_deltas + tail)
            self.backend.put_value(
                self._base_key(name, epoch),
                pickle.dumps(
                    {"folded_through": sealed, "deltas": merged}
                ),
            )
        for idx, blob in states:
            self.backend.put_value(
                f"opsnap/{self.worker_id}/{epoch}/{idx}", blob
            )
        prev = self.load_manifest()
        # commit point
        self.backend.put_value(
            self.manifest_key,
            pickle.dumps(
                {
                    "time": time,
                    "epoch": epoch,
                    "format_version": SNAPSHOT_FORMAT_VERSION,
                    "node_count": len(engine.nodes),
                    "graph_fingerprint": graph_fingerprint(engine),
                    "state_nodes": [idx for idx, _ in states],
                    "skipped_nodes": skipped,
                    "folded_through": folded_through,
                    # replay-divergence baselines (PATHWAY_SANITIZE):
                    # per-UDF [rows, hash] as of this snapshot's frontier
                    "udf_hashes": (
                        _sanitizer.tracker().hashes_for_manifest()
                        if _sanitizer.ACTIVE
                        and _sanitizer.tracker().hashing
                        else None
                    ),
                }
            ),
        )
        # cleanup: sealed segments are folded; older epochs superseded
        for name, writer in writers.items():
            writer.drop_segments_through(folded_through[name])
            for e in self._list_base_epochs(name):
                if e != epoch:
                    self.backend.truncate(self._base_key(name, e))
        if prev is not None and prev.get("epoch") not in (None, epoch):
            for idx in prev.get("state_nodes", []):
                self.backend.truncate(
                    f"opsnap/{self.worker_id}/{prev['epoch']}/{idx}"
                )
        return True

    def load_manifest(self) -> dict | None:
        blob = self.backend.get_value(self.manifest_key)
        if blob is None:
            return None
        try:
            return pickle.loads(blob)
        except Exception:  # noqa: BLE001
            return None

    def load_states(self, engine, manifest: dict) -> Dict[int, dict] | None:
        """Phase 1: read + unpickle every state blob WITHOUT touching the
        engine. None = unusable (graph changed / blob missing / corrupt)."""
        # a snapshot written under another format version (or before
        # versioning existed) may encode keys/state the current code
        # derives differently — full replay is the only safe restore
        if manifest.get("format_version") != SNAPSHOT_FORMAT_VERSION:
            return None
        if manifest.get("node_count") != len(engine.nodes):
            return None
        # same node COUNT is not the same GRAPH: a changed predicate or a
        # reordered pair of operators would restore state into the wrong
        # nodes by index.  Refuse on any per-node fingerprint mismatch so
        # the caller falls back to consolidated-base full replay (the
        # reference keys snapshots by stable persistent operator ids).
        if manifest.get("graph_fingerprint") != graph_fingerprint(engine):
            return None
        # a snapshot that skipped unpicklable nodes is incomplete by
        # construction — replaying the consolidated base rebuilds every
        # node's state, restoring the others by index would not
        if manifest.get("skipped_nodes"):
            return None
        epoch = manifest.get("epoch", manifest.get("time"))
        states: Dict[int, dict] = {}
        for idx in manifest.get("state_nodes", []):
            blob = self.backend.get_value(
                f"opsnap/{self.worker_id}/{epoch}/{idx}"
            )
            if blob is None:
                return None
            try:
                states[idx] = pickle.loads(blob)
            except Exception:  # noqa: BLE001
                return None
        return states

    def apply_states(self, engine, states: Dict[int, dict]) -> None:
        """Phase 2: commit (after any multi-worker agreement)."""
        for idx, state in states.items():
            engine.nodes[idx].restore_state(state)

    def read_base(self, name: str) -> Tuple[List, int]:
        """Latest readable consolidated base: (deltas, folded_through).
        (-1 = nothing folded; replay every segment.)"""
        for epoch in reversed(self._list_base_epochs(name)):
            blob = self.backend.get_value(self._base_key(name, epoch))
            if blob is None:
                continue
            try:
                data = pickle.loads(blob)
                return data["deltas"], data["folded_through"]
            except Exception:  # noqa: BLE001
                continue
        return [], -1


class InputSnapshotWriter:
    """Segmented event log per source per worker (reference:
    input_snapshot.rs:286 chunked event logs).

    Events append to `snapshot/<worker>/<name>/events.<segment>`; a
    snapshot rolls the writer onto a fresh segment so compaction folds only
    sealed segments (no read/truncate race with ongoing appends), and the
    worker scoping makes each log single-writer — the contract
    `PersistenceBackend.append` requires."""

    def __init__(
        self, backend: PersistenceBackend, source_name: str, worker_id: int = 0
    ):
        self.backend = backend
        self.prefix = f"snapshot/{worker_id}/{source_name}"
        self.state_key = f"{self.prefix}/state"
        self.segptr_key = f"{self.prefix}/segptr"
        segs = self.list_segments()
        # the segment pointer survives compaction deleting every segment
        # file: without it a restart would reuse a sealed segment number
        # and the replay cursor (folded_through) would skip its events
        ptr = 0
        blob = self.backend.get_value(self.segptr_key)
        if blob is not None:
            try:
                ptr = int(blob.decode())
            except ValueError:
                ptr = 0
        self.active_segment = max(segs[-1] if segs else 0, ptr)

    def _segment_key(self, seg: int) -> str:
        return f"{self.prefix}/events.{seg:08d}"

    _SEGMENT_RE = re.compile(r"events\.(\d{8})(?:$|/)")

    def list_segments(self) -> List[int]:
        # Extract the segment id from the `events.<seg>` path component
        # itself.  ObjectStoreBackend emulates append by storing chunks
        # under `<key>/log.<n>`, so the final dot-suffix of a listed key is
        # the CHUNK number, not the segment number — splitting on the last
        # '.' would invent phantom segments there.
        out = []
        marker = self.prefix.replace("/", "__") + "__events."
        for key in self.backend.list_keys():
            if marker in key.replace("/", "__"):
                m = self._SEGMENT_RE.search(key)
                if m:
                    out.append(int(m.group(1)))
        return sorted(set(out))

    def start_new_segment(self) -> int:
        """Seal the active segment; returns the sealed segment number."""
        sealed = self.active_segment
        self.active_segment = sealed + 1
        self.backend.put_value(
            self.segptr_key, str(self.active_segment).encode()
        )
        return sealed

    def write_batch(self, deltas, subject_state=None) -> None:
        if deltas:
            self.backend.append(
                self._segment_key(self.active_segment), pickle.dumps(deltas)
            )
        if subject_state is not None:
            self.backend.put_value(self.state_key, pickle.dumps(subject_state))

    def read_segment(self, seg: int) -> List:
        out = []
        for chunk in self.backend.read_appended(self._segment_key(seg)):
            try:
                out.extend(pickle.loads(chunk))
            except Exception:  # noqa: BLE001 — torn chunk at crash point
                break
        return out

    def read_events(self, after_segment: int = -1) -> List:
        out: List = []
        for seg in self.list_segments():
            if seg > after_segment:
                out.extend(self.read_segment(seg))
        return out

    def drop_segments_through(self, seg: int) -> None:
        for s in self.list_segments():
            if s <= seg:
                self.backend.truncate(self._segment_key(s))

    def read_state(self):
        blob = self.backend.get_value(self.state_key)
        if blob is None:
            return None
        try:
            return pickle.loads(blob)
        except Exception:  # noqa: BLE001
            return None


class SinkCommitLog:
    """Durable per-(worker, sink) commit metadata for exactly-once output.

    Output written for epoch T only becomes durable when the operator
    snapshot frontier reaches >= T — everything newer is provisional and
    rolled back on recovery, then regenerated by replay.  The commit log
    carries that protocol: one atomically-replaced marker record

        {"frontier": F,          # highest finalized commit frontier
         "offsets": {F: bytes},  # file length per frontier (truncate
                                 # recovery for append-style sinks)
         "staged": [F...]}       # staged-payload frontiers awaiting
                                 # finalize (buffered sinks: postgres/mq)

    plus one staged-payload blob per prepared frontier.  Atomicity comes
    from the ordering against the operator-snapshot manifest, the run's
    single commit point:

      prepare(F):  record_offset / stage — BEFORE the manifest, so the
                   restore frontier M always has its entry;
      commit(F):   mark_committed / apply staged — AFTER the manifest,
                   idempotent, re-runnable by recover(M) after a crash.
    """

    _KEEP_OFFSETS = 8

    def __init__(
        self, backend: PersistenceBackend, name: str, worker_id: int = 0
    ):
        self.backend = backend
        self.prefix = f"sinkcommit/{worker_id}/{name}"
        self._marker_key = f"{self.prefix}/marker"
        self._rec = self._load()

    def _load(self) -> Dict[str, Any]:
        blob = self.backend.get_value(self._marker_key)
        if blob is not None:
            try:
                rec = pickle.loads(blob)
                if isinstance(rec, dict):
                    rec.setdefault("frontier", -1)
                    rec.setdefault("offsets", {})
                    rec.setdefault("staged", [])
                    return rec
            except Exception:  # noqa: BLE001 — torn write
                pass
        return {"frontier": -1, "offsets": {}, "staged": []}

    def _write(self) -> None:
        self.backend.put_value(self._marker_key, pickle.dumps(self._rec))

    def _stage_key(self, frontier: int) -> str:
        return f"{self.prefix}/stage.{frontier:016d}"

    def committed_frontier(self) -> int:
        return self._rec["frontier"]

    # -- file-offset protocol (append-style sinks: jsonlines/csv) --------

    def record_offset(self, frontier: int, offset: int) -> None:
        offsets = self._rec["offsets"]
        offsets[frontier] = offset
        for f in sorted(offsets)[: -self._KEEP_OFFSETS]:
            del offsets[f]
        self._write()

    def offset_for(self, frontier: int) -> Optional[int]:
        return self._rec["offsets"].get(frontier)

    # -- staged-payload protocol (buffered sinks: postgres/kafka) --------

    def stage(self, frontier: int, payload: bytes) -> None:
        self.backend.put_value(self._stage_key(frontier), payload)
        if frontier not in self._rec["staged"]:
            self._rec["staged"].append(frontier)
            self._rec["staged"].sort()
        self._write()

    def read_staged(
        self, lo_exclusive: int, hi_inclusive: int
    ) -> List[Tuple[int, bytes]]:
        out: List[Tuple[int, bytes]] = []
        for f in self._rec["staged"]:
            if lo_exclusive < f <= hi_inclusive:
                blob = self.backend.get_value(self._stage_key(f))
                if blob is not None:
                    out.append((f, blob))
        return out

    def rollback_to(self, frontier: int) -> None:
        """Recovery: drop staged payloads and offsets recorded past the
        restore frontier.  Post-restore epochs renumber from the restore
        frontier, so a stale staged blob at a colliding frontier number
        would later be applied as if it were regenerated output."""
        keep = []
        for f in self._rec["staged"]:
            if f > frontier:
                self.backend.truncate(self._stage_key(f))
            else:
                keep.append(f)
        self._rec["staged"] = keep
        offsets = self._rec["offsets"]
        for f in [f for f in offsets if f > frontier]:
            del offsets[f]
        self._write()

    def mark_committed(self, frontier: int) -> None:
        """Finalize: advance the marker and prune staged payloads the
        sink has durably applied."""
        self._rec["frontier"] = max(self._rec["frontier"], frontier)
        keep = []
        for f in self._rec["staged"]:
            if f <= self._rec["frontier"]:
                self.backend.truncate(self._stage_key(f))
            else:
                keep.append(f)
        self._rec["staged"] = keep
        self._write()


class CachedObjectStorage:
    """Persistence-backed cache of downloaded source objects (reference:
    src/persistence/cached_object_storage.rs — 833 LoC of exactly this
    contract): bytes fetched from slow external sources (GDrive,
    SharePoint) are stored under (object id, version) so a restarted
    pipeline re-serves them from the persistent store instead of
    re-downloading and re-parsing.

    Keys are hashed into the backend namespace; the object id and version
    live inside the blob, so listing works on plain key enumeration."""

    def __init__(self, backend: PersistenceBackend, scope: str):
        import hashlib as _hashlib

        self.backend = backend
        self.scope = scope
        self._h = lambda s: _hashlib.blake2b(
            s.encode(), digest_size=12
        ).hexdigest()

    def _key(self, object_id: str) -> str:
        return f"objcache/{self._h(self.scope)}/{self._h(object_id)}"

    def get(self, object_id: str, version: Any) -> Optional[bytes]:
        """Cached bytes for this exact (id, version); None on miss."""
        blob = self.backend.get_value(self._key(object_id))
        if blob is None:
            return None
        try:
            entry = pickle.loads(blob)
        except Exception:  # noqa: BLE001 — torn write
            return None
        if entry.get("version") != version:
            return None
        return entry.get("payload")

    def put(
        self,
        object_id: str,
        version: Any,
        payload: bytes,
        metadata: Any = None,
    ) -> None:
        self.backend.put_value(
            self._key(object_id),
            pickle.dumps(
                {
                    "object_id": object_id,
                    "version": version,
                    "payload": payload,
                    "metadata": metadata,
                }
            ),
        )

    def evict(self, object_id: str) -> None:
        self.backend.truncate(self._key(object_id))

    def list_objects(self) -> Dict[str, Any]:
        """object_id -> version for every cached object in this scope."""
        prefix_raw = f"objcache/{self._h(self.scope)}/"
        prefix_flat = prefix_raw.replace("/", "__")
        out: Dict[str, Any] = {}
        for key in self.backend.list_keys():
            if not (
                key.startswith(prefix_raw) or key.startswith(prefix_flat)
            ):
                continue
            blob = self.backend.get_value(key)
            if blob is None:
                continue
            try:
                entry = pickle.loads(blob)
            except Exception:  # noqa: BLE001
                continue
            out[entry["object_id"]] = entry["version"]
        return out


from contextlib import contextmanager as _contextmanager

from pathway_tpu.io.s3 import AwsS3Settings  # noqa: E402 — parity re-export


@_contextmanager
def get_persistence_engine_config(persistence_config):
    """Context manager yielding the engine-facing persistence config with
    the run-lifecycle hooks bracketed (reference: persistence/__init__.py
    get_persistence_engine_config:193 — on_before_run before the run,
    on_after_run guaranteed after it). The runner enters this around
    every persistent run."""
    if persistence_config is None:
        yield None
        return
    persistence_config.on_before_run()
    try:
        yield persistence_config
    finally:
        persistence_config.on_after_run()
