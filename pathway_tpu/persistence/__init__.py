"""pw.persistence — input snapshots + resume (reference:
python/pathway/persistence/__init__.py Backend:27-99, Config:116; engine side
src/persistence/input_snapshot.rs:286, backends/mod.rs:76).

Model: each named connector's parsed events append to a chunked log at every
commit, together with the subject's own cursor state (file mtimes, offsets).
On restart the log replays into the engine as the first batch and the
subject resumes from its cursor — the reference's input-snapshot mode.
Operator snapshots (differential arrangement state) are subsumed here by
deterministic replay of the compact input log.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple


class PersistenceBackend:
    """K/V store interface (reference: persistence/backends/mod.rs:76)."""

    def put_value(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get_value(self, key: str) -> bytes | None:
        raise NotImplementedError

    def append(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def read_appended(self, key: str) -> List[bytes]:
        raise NotImplementedError

    def list_keys(self) -> List[str]:
        raise NotImplementedError

    def truncate(self, key: str) -> None:
        """Drop an append log / value (log compaction after an operator
        snapshot bakes its events into operator state)."""
        raise NotImplementedError


class FilesystemBackend(PersistenceBackend):
    def __init__(self, path: str):
        self.root = path
        os.makedirs(path, exist_ok=True)
        self._locks: Dict[str, threading.Lock] = {}

    def _path(self, key: str) -> str:
        safe = key.replace("/", "__")
        return os.path.join(self.root, safe)

    def put_value(self, key: str, value: bytes) -> None:
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def get_value(self, key: str) -> bytes | None:
        path = self._path(key)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def append(self, key: str, value: bytes) -> None:
        with open(self._path(key), "ab") as f:
            f.write(len(value).to_bytes(8, "little"))
            f.write(value)

    def read_appended(self, key: str) -> List[bytes]:
        path = self._path(key)
        if not os.path.exists(path):
            return []
        out = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                n = int.from_bytes(header, "little")
                chunk = f.read(n)
                if len(chunk) < n:
                    break  # torn tail write from a crash — ignore
                out.append(chunk)
        return out

    def list_keys(self) -> List[str]:
        return os.listdir(self.root)

    def truncate(self, key: str) -> None:
        path = self._path(key)
        if os.path.exists(path):
            os.remove(path)


class MockBackend(PersistenceBackend):
    """In-memory backend for tests (reference: backends/mock.rs)."""

    def __init__(self, store: Dict[str, Any] | None = None):
        self.values: Dict[str, bytes] = (store or {}).setdefault("values", {}) if isinstance(store, dict) else {}
        self.logs: Dict[str, List[bytes]] = {}
        if isinstance(store, dict):
            self.logs = store.setdefault("logs", {})

    def put_value(self, key, value):
        self.values[key] = value

    def get_value(self, key):
        return self.values.get(key)

    def append(self, key, value):
        self.logs.setdefault(key, []).append(value)

    def read_appended(self, key):
        return list(self.logs.get(key, []))

    def list_keys(self):
        return list(set(self.values) | set(self.logs))

    def truncate(self, key):
        self.values.pop(key, None)
        self.logs.pop(key, None)


class ObjectStoreBackend(PersistenceBackend):
    """Persistence over any object store with put/get/delete/list
    (reference: persistence/backends/s3.rs, azure.rs — a K/V trait over
    immutable objects).

    Objects are immutable, so `append` is emulated with numbered chunk
    objects under `<key>/log.<n>`; `read_appended` lists and sorts them.
    The client interface is minimal and injectable for tests:
    put(key, bytes), get(key) -> bytes|None, delete(key), list(prefix) ->
    [key]."""

    def __init__(self, client, prefix: str = ""):
        self.client = client
        self.prefix = prefix.strip("/")
        self._counters: Dict[str, int] = {}

    def _full(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put_value(self, key, value):
        self.client.put(self._full(key), value)

    def get_value(self, key):
        return self.client.get(self._full(key))

    def append(self, key, value):
        n = self._counters.get(key)
        if n is None:
            existing = self.client.list(self._full(key) + "/log.")
            n = len(existing)
        self.client.put(self._full(key) + f"/log.{n:08d}", value)
        self._counters[key] = n + 1

    def read_appended(self, key):
        names = sorted(self.client.list(self._full(key) + "/log."))
        out = []
        for name in names:
            blob = self.client.get(name)
            if blob is not None:
                out.append(blob)
        return out

    def list_keys(self):
        skip = len(self.prefix) + 1 if self.prefix else 0
        return [k[skip:] for k in self.client.list(self.prefix)]

    def truncate(self, key):
        for name in self.client.list(self._full(key) + "/log."):
            self.client.delete(name)
        self.client.delete(self._full(key))
        self._counters.pop(key, None)


class _Boto3ObjectClient:
    """S3 client adapter (gated on boto3; injectable fake in tests)."""

    def __init__(self, bucket: str, **kwargs):
        import boto3  # type: ignore

        self.bucket = bucket
        self.client = boto3.client("s3", **kwargs)

    def put(self, key, value):
        self.client.put_object(Bucket=self.bucket, Key=key, Body=value)

    def get(self, key):
        try:
            resp = self.client.get_object(Bucket=self.bucket, Key=key)
        except Exception:  # noqa: BLE001 — NoSuchKey and friends
            return None
        return resp["Body"].read()

    def delete(self, key):
        self.client.delete_object(Bucket=self.bucket, Key=key)

    def list(self, prefix):
        out = []
        paginator = self.client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                out.append(obj["Key"])
        return out


class _AzureBlobClient:
    """Azure Blob adapter (gated on azure-storage-blob)."""

    def __init__(self, container: str, connection_string: str | None = None, **kwargs):
        from azure.storage.blob import BlobServiceClient  # type: ignore

        if connection_string is not None:
            service = BlobServiceClient.from_connection_string(
                connection_string, **kwargs
            )
        else:
            service = BlobServiceClient(**kwargs)
        self.container = service.get_container_client(container)

    def put(self, key, value):
        self.container.upload_blob(key, value, overwrite=True)

    def get(self, key):
        try:
            return self.container.download_blob(key).readall()
        except Exception:  # noqa: BLE001
            return None

    def delete(self, key):
        try:
            self.container.delete_blob(key)
        except Exception:  # noqa: BLE001
            pass

    def list(self, prefix):
        return [
            b.name for b in self.container.list_blobs(name_starts_with=prefix)
        ]


class Backend:
    """Factory namespace (reference: persistence/__init__.py Backend:27)."""

    def __init__(self, engine_backend: PersistenceBackend):
        self._backend = engine_backend

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(FilesystemBackend(path))

    @classmethod
    def mock(cls, events: Dict | None = None) -> "Backend":
        return cls(MockBackend(events))

    @classmethod
    def s3(
        cls,
        root_path: str,
        bucket_settings=None,
        *,
        _client=None,
        **client_kwargs,
    ) -> "Backend":
        """root_path: s3://bucket/prefix (reference: backends/s3.rs).
        Tests inject `_client`; production uses boto3 credentials from the
        standard chain or `bucket_settings`."""
        bucket, _, prefix = root_path.removeprefix("s3://").partition("/")
        if _client is None:
            if isinstance(bucket_settings, dict):
                client_kwargs.update(bucket_settings)
            _client = _Boto3ObjectClient(bucket, **client_kwargs)
        return cls(ObjectStoreBackend(_client, prefix))

    @classmethod
    def azure(
        cls,
        root_path: str,
        *,
        account=None,
        password=None,
        connection_string=None,
        _client=None,
        **client_kwargs,
    ) -> "Backend":
        """root_path: az://container/prefix (reference: backends/azure.rs)."""
        container, _, prefix = root_path.removeprefix("az://").partition("/")
        if _client is None:
            _client = _AzureBlobClient(
                container, connection_string=connection_string, **client_kwargs
            )
        return cls(ObjectStoreBackend(_client, prefix))


class Config:
    """reference: persistence/__init__.py Config:116."""

    def __init__(
        self,
        backend: Backend,
        *,
        snapshot_interval_ms: int = 0,
        snapshot_access=None,
        persistence_mode=None,
        continue_after_replay: bool = True,
    ):
        self.backend = backend
        self.snapshot_interval_ms = snapshot_interval_ms
        self.persistence_mode = persistence_mode
        self.continue_after_replay = continue_after_replay

    # legacy alias used by reference code: Config.simple_config
    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend, **kwargs)


class OperatorSnapshotManager:
    """Checkpoint operator state keyed by frontier + compact input logs
    (reference: src/persistence/operator_snapshot.rs:231 snapshot
    writer/merger, tracker.rs:51 frontier commit, dataflow/persist.rs).

    At a quiescent frontier (all node queues drained after `process_time`),
    every stateful node's `snapshot_state()` is pickled under an
    epoch-versioned key `opsnap/<worker>/<epoch>/<node-idx>`; the manifest
    is written LAST and names the epoch, so a crash mid-save leaves the old
    manifest pointing at the old epoch's intact blobs (commit-last
    atomicity, like the reference's snapshot writer). Before the event logs
    are truncated, their deltas merge into a *consolidated base log* per
    source — so even if a later restart cannot restore operator state (the
    graph changed, a blob is missing), full replay of base + tail loses
    nothing. Restore is two-phase: `load_states` reads and unpickles
    without mutating (multi-worker agreement can veto), `apply_states`
    commits. If any node's state fails to pickle, the whole snapshot
    aborts and the logs are kept."""

    def __init__(self, backend: PersistenceBackend, worker_id: int = 0):
        self.backend = backend
        self.worker_id = worker_id
        self.manifest_key = f"opsnap/{worker_id}/manifest"

    def _events_key(self, name: str) -> str:
        return f"snapshot/{name}/events"

    def _base_key(self, name: str) -> str:
        return f"snapshot/{name}/base"

    def save(self, engine, time: int, source_names: List[str]) -> bool:
        states: List[Tuple[int, bytes]] = []
        try:
            for idx, node in enumerate(engine.nodes):
                state = node.snapshot_state()
                if state is not None:
                    states.append((idx, pickle.dumps(state)))
        except Exception:  # noqa: BLE001 — unpicklable operator state
            return False
        # compaction step 1: fold the event-log tail into the consolidated
        # base (bounded by live rows, not history) BEFORE truncation — the
        # full-replay fallback stays complete no matter what happens later
        from pathway_tpu.engine.stream import consolidate

        for name in source_names:
            tail: List = []
            for chunk in self.backend.read_appended(self._events_key(name)):
                try:
                    tail.extend(pickle.loads(chunk))
                except Exception:  # noqa: BLE001 — torn crash-point chunk
                    break
            if not tail:
                continue
            base_blob = self.backend.get_value(self._base_key(name))
            base: List = []
            if base_blob is not None:
                try:
                    base = pickle.loads(base_blob)
                except Exception:  # noqa: BLE001
                    base = []
            merged = consolidate(base + tail)
            self.backend.put_value(self._base_key(name), pickle.dumps(merged))
            self.backend.truncate(self._events_key(name))

        prev = self.load_manifest()
        epoch = time
        for idx, blob in states:
            self.backend.put_value(
                f"opsnap/{self.worker_id}/{epoch}/{idx}", blob
            )
        # commit point: the manifest flips to the new epoch atomically
        self.backend.put_value(
            self.manifest_key,
            pickle.dumps(
                {
                    "time": time,
                    "epoch": epoch,
                    "node_count": len(engine.nodes),
                    "state_nodes": [idx for idx, _ in states],
                }
            ),
        )
        if prev is not None and prev.get("epoch") not in (None, epoch):
            for idx in prev.get("state_nodes", []):
                self.backend.truncate(
                    f"opsnap/{self.worker_id}/{prev['epoch']}/{idx}"
                )
        return True

    def load_manifest(self) -> dict | None:
        blob = self.backend.get_value(self.manifest_key)
        if blob is None:
            return None
        try:
            return pickle.loads(blob)
        except Exception:  # noqa: BLE001
            return None

    def load_states(self, engine, manifest: dict) -> Dict[int, dict] | None:
        """Phase 1: read + unpickle every state blob WITHOUT touching the
        engine. None = unusable (graph changed / blob missing / corrupt)."""
        if manifest.get("node_count") != len(engine.nodes):
            return None
        epoch = manifest.get("epoch", manifest.get("time"))
        states: Dict[int, dict] = {}
        for idx in manifest.get("state_nodes", []):
            blob = self.backend.get_value(
                f"opsnap/{self.worker_id}/{epoch}/{idx}"
            )
            if blob is None:
                return None
            try:
                states[idx] = pickle.loads(blob)
            except Exception:  # noqa: BLE001
                return None
        return states

    def apply_states(self, engine, states: Dict[int, dict]) -> None:
        """Phase 2: commit (after any multi-worker agreement)."""
        for idx, state in states.items():
            engine.nodes[idx].restore_state(state)

    def read_base(self, name: str) -> List:
        blob = self.backend.get_value(self._base_key(name))
        if blob is None:
            return []
        try:
            return pickle.loads(blob)
        except Exception:  # noqa: BLE001
            return []


class InputSnapshotWriter:
    """Append parsed events per source (reference: input_snapshot.rs:286)."""

    def __init__(self, backend: PersistenceBackend, source_name: str):
        self.backend = backend
        self.key = f"snapshot/{source_name}/events"
        self.state_key = f"snapshot/{source_name}/state"

    def write_batch(self, deltas, subject_state=None) -> None:
        if deltas:
            self.backend.append(self.key, pickle.dumps(deltas))
        if subject_state is not None:
            self.backend.put_value(self.state_key, pickle.dumps(subject_state))

    def read_events(self):
        out = []
        for chunk in self.backend.read_appended(self.key):
            try:
                out.extend(pickle.loads(chunk))
            except Exception:  # noqa: BLE001 — torn chunk at crash point
                break
        return out

    def read_state(self):
        blob = self.backend.get_value(self.state_key)
        if blob is None:
            return None
        try:
            return pickle.loads(blob)
        except Exception:  # noqa: BLE001
            return None
