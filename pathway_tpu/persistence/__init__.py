"""pw.persistence — input snapshots + resume (reference:
python/pathway/persistence/__init__.py Backend:27-99, Config:116; engine side
src/persistence/input_snapshot.rs:286, backends/mod.rs:76).

Model: each named connector's parsed events append to a chunked log at every
commit, together with the subject's own cursor state (file mtimes, offsets).
On restart the log replays into the engine as the first batch and the
subject resumes from its cursor — the reference's input-snapshot mode.
Operator snapshots (differential arrangement state) are subsumed here by
deterministic replay of the compact input log.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple


class PersistenceBackend:
    """K/V store interface (reference: persistence/backends/mod.rs:76)."""

    def put_value(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get_value(self, key: str) -> bytes | None:
        raise NotImplementedError

    def append(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def read_appended(self, key: str) -> List[bytes]:
        raise NotImplementedError

    def list_keys(self) -> List[str]:
        raise NotImplementedError


class FilesystemBackend(PersistenceBackend):
    def __init__(self, path: str):
        self.root = path
        os.makedirs(path, exist_ok=True)
        self._locks: Dict[str, threading.Lock] = {}

    def _path(self, key: str) -> str:
        safe = key.replace("/", "__")
        return os.path.join(self.root, safe)

    def put_value(self, key: str, value: bytes) -> None:
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def get_value(self, key: str) -> bytes | None:
        path = self._path(key)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def append(self, key: str, value: bytes) -> None:
        with open(self._path(key), "ab") as f:
            f.write(len(value).to_bytes(8, "little"))
            f.write(value)

    def read_appended(self, key: str) -> List[bytes]:
        path = self._path(key)
        if not os.path.exists(path):
            return []
        out = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                n = int.from_bytes(header, "little")
                chunk = f.read(n)
                if len(chunk) < n:
                    break  # torn tail write from a crash — ignore
                out.append(chunk)
        return out

    def list_keys(self) -> List[str]:
        return os.listdir(self.root)


class MockBackend(PersistenceBackend):
    """In-memory backend for tests (reference: backends/mock.rs)."""

    def __init__(self, store: Dict[str, Any] | None = None):
        self.values: Dict[str, bytes] = (store or {}).setdefault("values", {}) if isinstance(store, dict) else {}
        self.logs: Dict[str, List[bytes]] = {}
        if isinstance(store, dict):
            self.logs = store.setdefault("logs", {})

    def put_value(self, key, value):
        self.values[key] = value

    def get_value(self, key):
        return self.values.get(key)

    def append(self, key, value):
        self.logs.setdefault(key, []).append(value)

    def read_appended(self, key):
        return list(self.logs.get(key, []))

    def list_keys(self):
        return list(set(self.values) | set(self.logs))


class Backend:
    """Factory namespace (reference: persistence/__init__.py Backend:27)."""

    def __init__(self, engine_backend: PersistenceBackend):
        self._backend = engine_backend

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls(FilesystemBackend(path))

    @classmethod
    def mock(cls, events: Dict | None = None) -> "Backend":
        return cls(MockBackend(events))

    @classmethod
    def s3(cls, root_path: str, bucket_settings=None) -> "Backend":
        raise NotImplementedError(
            "S3 persistence backend requires object-store credentials; "
            "use Backend.filesystem on a mounted bucket"
        )

    azure = s3


class Config:
    """reference: persistence/__init__.py Config:116."""

    def __init__(
        self,
        backend: Backend,
        *,
        snapshot_interval_ms: int = 0,
        snapshot_access=None,
        persistence_mode=None,
        continue_after_replay: bool = True,
    ):
        self.backend = backend
        self.snapshot_interval_ms = snapshot_interval_ms
        self.persistence_mode = persistence_mode
        self.continue_after_replay = continue_after_replay

    # legacy alias used by reference code: Config.simple_config
    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend, **kwargs)


class InputSnapshotWriter:
    """Append parsed events per source (reference: input_snapshot.rs:286)."""

    def __init__(self, backend: PersistenceBackend, source_name: str):
        self.backend = backend
        self.key = f"snapshot/{source_name}/events"
        self.state_key = f"snapshot/{source_name}/state"

    def write_batch(self, deltas, subject_state=None) -> None:
        if deltas:
            self.backend.append(self.key, pickle.dumps(deltas))
        if subject_state is not None:
            self.backend.put_value(self.state_key, pickle.dumps(subject_state))

    def read_events(self):
        out = []
        for chunk in self.backend.read_appended(self.key):
            try:
                out.extend(pickle.loads(chunk))
            except Exception:  # noqa: BLE001 — torn chunk at crash point
                break
        return out

    def read_state(self):
        blob = self.backend.get_value(self.state_key)
        if blob is None:
            return None
        try:
            return pickle.loads(blob)
        except Exception:  # noqa: BLE001
            return None
