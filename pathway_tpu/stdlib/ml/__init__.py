"""pw.ml (reference: python/pathway/stdlib/ml/)."""

from pathway_tpu.stdlib.ml import (
    classifiers,
    datasets,
    hmm,
    index,
    smart_table_ops,
    utils,
)
from pathway_tpu.stdlib.ml.hmm import create_hmm_reducer
from pathway_tpu.stdlib.ml.utils import classifier_accuracy

__all__ = [
    "classifiers",
    "classifier_accuracy",
    "create_hmm_reducer",
    "datasets",
    "hmm",
    "index",
    "smart_table_ops",
    "utils",
]
