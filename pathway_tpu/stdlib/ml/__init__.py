"""pw.ml (reference: python/pathway/stdlib/ml/). Populated progressively:
index (legacy KNNIndex), classifiers, smart_table_ops."""

from pathway_tpu.stdlib.ml import index

__all__ = ["index"]
