"""pw.ml (reference: python/pathway/stdlib/ml/). Populated progressively:
index (legacy KNNIndex), classifiers, smart_table_ops."""

from pathway_tpu.stdlib.ml import classifiers, index, smart_table_ops

__all__ = ["classifiers", "index", "smart_table_ops"]
