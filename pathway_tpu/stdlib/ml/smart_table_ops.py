"""Fuzzy join (reference:
python/pathway/stdlib/ml/smart_table_ops/_fuzzy_join.py). Matches rows of
two tables by shared features with normalized weights, one-to-one greedy
assignment."""

from __future__ import annotations

import re
from typing import Any, Callable

import pathway_tpu.internals.reducers as red
from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals.table import Table


class FuzzyJoinFeatureGeneration:
    AUTO = "auto"
    TOKENIZE = "tokenize"
    LETTERS = "letters"


def _tokenize(text: str) -> list:
    return [t.lower() for t in re.findall(r"[A-Za-z0-9]+", text or "")]


def fuzzy_match_tables(
    left: Table,
    right: Table,
    *,
    by_hand_match=None,
    feature_generation: str = FuzzyJoinFeatureGeneration.AUTO,
    left_projection: dict | None = None,
    right_projection: dict | None = None,
):
    """Match rows across tables by token overlap (reference:
    _fuzzy_join.py fuzzy_match_tables). Returns (left_id, right_id, weight).
    """
    left_cols = list(left.column_names())
    right_cols = list(right.column_names())

    def features_of(*values) -> tuple:
        feats = []
        for v in values:
            if isinstance(v, str):
                feats.extend(_tokenize(v))
            elif v is not None:
                feats.append(repr(v))
        return tuple(feats)

    from pathway_tpu.internals.expression import IdReference

    lf = left.select(
        feats=pw_api.apply_with_type(
            features_of, tuple, *(left[c] for c in left_cols)
        ),
        orig=IdReference(left),
    )
    rf = right.select(
        feats=pw_api.apply_with_type(
            features_of, tuple, *(right[c] for c in right_cols)
        ),
        orig=IdReference(right),
    )
    lflat = lf.flatten(lf.feats).rename_by_dict({"feats": "feature"})
    rflat = rf.flatten(rf.feats).rename_by_dict({"feats": "feature"})
    # feature weight ~ 1/frequency across both sides
    all_feats = lflat.concat_reindex(rflat)
    freq = all_feats.groupby(all_feats.feature).reduce(
        feature=all_feats.feature, n=red.count()
    )
    import pathway_tpu as pw

    pairs = lflat.join(rflat, lflat.feature == rflat.feature)
    freq_keyed = freq.with_id_from(freq.feature)
    paired = pairs.select(
        left_id=lflat.orig,
        right_id=rflat.orig,
        feature=lflat.feature,
    )
    with_w = paired.select(
        left_id=paired.left_id,
        right_id=paired.right_id,
        w=1.0
        / freq_keyed.ix(
            freq_keyed.pointer_from(paired.feature), optional=True
        ).n,
    )
    scores = with_w.groupby(with_w.left_id, with_w.right_id).reduce(
        left=with_w.left_id,
        right=with_w.right_id,
        weight=red.sum_(with_w.w),
    )
    return scores


def smart_fuzzy_join(left: Table, right: Table, **kwargs):
    return fuzzy_match_tables(left, right, **kwargs)
