"""Legacy KNNIndex API (reference: python/pathway/stdlib/ml/index.py:9 —
LSH-based; here backed by the XLA brute-force kernel).

>>> import numpy as np
>>> import pathway_tpu as pw
>>> from pathway_tpu.stdlib.ml.index import KNNIndex
>>> data = pw.debug.table_from_rows(
...     pw.schema_from_types(doc=str, emb=np.ndarray),
...     [("apple", np.array([1.0, 0.0])), ("pear", np.array([0.9, 0.1]))],
... )
>>> index = KNNIndex(data.emb, data, n_dimensions=2)
>>> qs = pw.debug.table_from_rows(
...     pw.schema_from_types(qemb=np.ndarray), [(np.array([1.0, 0.05]),)]
... )
>>> r = index.get_nearest_items(qs.qemb, k=1).select(pw.this.doc)
>>> pw.debug.compute_and_print(r, include_id=False)
doc
('apple',)
"""

from __future__ import annotations

from typing import Any, Optional

from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnMetricKind,
)


class KNNIndex:
    """reference: ml/index.py KNNIndex — thin wrapper over DataIndex."""

    def __init__(
        self,
        data_embedding,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata=None,
    ):
        metric = (
            BruteForceKnnMetricKind.COS
            if distance_type == "cosine"
            else BruteForceKnnMetricKind.L2SQ
        )
        inner = BruteForceKnn(
            data_embedding,
            metadata,
            dimensions=n_dimensions,
            metric=metric,
        )
        self._index = DataIndex(data, inner)
        self._data = data

    def get_nearest_items(
        self,
        query_embedding,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter=None,
    ) -> Table:
        return self._index.query(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )

    def get_nearest_items_asof_now(
        self,
        query_embedding,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter=None,
    ) -> Table:
        return self._index.query_as_of_now(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
