"""ML helpers (reference: python/pathway/stdlib/ml/utils.py)."""

from __future__ import annotations

from pathway_tpu.internals import thisclass
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


def classifier_accuracy(predicted_labels: Table, exact_labels: Table) -> Table:
    """Counts of matching/mismatching predictions (reference:
    ml/utils.py classifier_accuracy:13). `predicted_labels` has
    `predicted_label`, `exact_labels` (same keys) has `label`."""
    comparative = predicted_labels.select(
        predicted_label=predicted_labels.predicted_label,
        label=exact_labels.restrict(predicted_labels).label,
    )
    comparative = comparative.select(
        thisclass.this.predicted_label,
        thisclass.this.label,
        match=comparative.label == comparative.predicted_label,
    )
    return comparative.groupby(comparative.match).reduce(
        cnt=reducers.count(),
        value=comparative.match,
    )
