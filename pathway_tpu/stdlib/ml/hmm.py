"""Hidden-Markov-Model decoding as a custom reducer (reference:
python/pathway/stdlib/ml/hmm.py create_hmm_reducer:11 — Viterbi over a
networkx DiGraph, folded observation-by-observation inside a
BaseCustomAccumulator).

The graph contract matches the reference:
  * `graph.graph["start_nodes"]`: iterable of start states;
  * each node carries `idx` (dense int) and `calc_emission_log_ppb(obs)`;
  * each edge carries `log_transition_ppb`.
"""

from __future__ import annotations

from collections import deque
from typing import Any

import numpy as np

from pathway_tpu.internals.reducers import BaseCustomAccumulator, udf_reducer


def create_hmm_reducer(
    graph,
    beam_size: int | None = None,
    num_results_kept: int | None = None,
):
    """Returns a reducer decoding the most likely state path for the
    observations aggregated (in order) into each group."""
    idx_to_node = {graph.nodes[n]["idx"]: n for n in graph.nodes}
    n_nodes = graph.number_of_nodes()
    effective_beam = beam_size if beam_size is not None else n_nodes + 1

    class HmmAccumulator(BaseCustomAccumulator):
        def __init__(self, observation):
            self.cnt = 1
            self.observation = observation
            self.ppb = np.full(n_nodes, -np.inf)
            self.backpointers: deque[np.ndarray] = deque()
            self.trimmed_nodes_idx = []
            for start_node in graph.graph["start_nodes"]:
                idx = graph.nodes[start_node]["idx"]
                self.ppb[idx] = graph.nodes[start_node][
                    "calc_emission_log_ppb"
                ](observation)
                self.trimmed_nodes_idx.append(idx)
            self.path_states = (idx_to_node[int(self.ppb.argmax())],)

        @classmethod
        def from_row(cls, row):
            (observation,) = row
            return cls(observation)

        def update(self, other) -> None:
            assert other.cnt == 1, "HMM accumulator folds one row at a time"
            self.cnt += 1
            observation = other.observation
            new_ppb = np.full(n_nodes, -np.inf)
            new_backpointers = np.zeros(n_nodes, dtype=int)
            reachable: dict = {}
            for start_idx in self.trimmed_nodes_idx:
                start_node = idx_to_node[start_idx]
                cost = self.ppb[start_idx]
                for node in graph.successors(start_node):
                    step = cost + graph.get_edge_data(start_node, node)[
                        "log_transition_ppb"
                    ]
                    reachable.setdefault(node, []).append((step, start_idx))
            trimmed = []
            for node, candidates in reachable.items():
                emission = graph.nodes[node]["calc_emission_log_ppb"](
                    observation
                )
                best_cost, best_from = max(candidates)
                idx = graph.nodes[node]["idx"]
                new_ppb[idx] = emission + best_cost
                new_backpointers[idx] = best_from
                trimmed.append(idx)
            if len(trimmed) > effective_beam:
                trimmed.sort(key=lambda i: -new_ppb[i])
                kept = set(trimmed[:effective_beam])
                for i in trimmed[effective_beam:]:
                    new_ppb[i] = -np.inf
                trimmed = [i for i in trimmed if i in kept]
            self.ppb = new_ppb
            self.backpointers.append(new_backpointers)
            self.trimmed_nodes_idx = trimmed
            # decode best path via backpointers
            best = int(self.ppb.argmax())
            path = [best]
            for bp in reversed(self.backpointers):
                path.append(int(bp[path[-1]]))
            path.reverse()
            states = tuple(idx_to_node[i] for i in path)
            if num_results_kept is not None:
                states = states[-num_results_kept:]
            self.path_states = states

        def compute_result(self) -> tuple:
            return self.path_states

    return udf_reducer(HmmAccumulator)
