"""Example datasets as Pathway tables (reference:
python/pathway/stdlib/ml/datasets/classification/__init__.py
load_mnist_sample — fetch_openml MNIST split into train/test tables).

Zero-egress environments fall back to scikit-learn's bundled digits
dataset (8x8 images, shipped with sklearn, no network) with the same
return shape: (X_train, y_train, X_test, y_test) tables holding `data`
(np.ndarray) and `label` columns."""

from __future__ import annotations

import numpy as np


def _tables_from_arrays(X_train, y_train, X_test, y_test):
    import pandas as pd

    from pathway_tpu.debug import table_from_pandas

    X_train_table = table_from_pandas(
        pd.DataFrame({"data": [np.asarray(x) for x in X_train]})
    )
    y_train_table = table_from_pandas(
        pd.DataFrame({"label": [str(y) for y in y_train]})
    )
    X_test_table = table_from_pandas(
        pd.DataFrame({"data": [np.asarray(x) for x in X_test]})
    )
    y_test_table = table_from_pandas(
        pd.DataFrame({"label": [str(y) for y in y_test]})
    )
    return X_train_table, y_train_table, X_test_table, y_test_table


def load_mnist_sample(sample_size: int = 70000):
    """reference: datasets/classification load_mnist_sample. Requires
    network for the real MNIST via openml; offline it raises."""
    from sklearn.datasets import fetch_openml

    X, y = fetch_openml(
        "mnist_784", version=1, return_X_y=True, as_frame=False
    )
    X = X / 255.0
    train_size = int(sample_size * 6 / 7)
    test_size = int(sample_size / 7)
    return _tables_from_arrays(
        X[:60000][:train_size],
        y[:60000][:train_size],
        X[60000:70000][:test_size],
        y[60000:70000][:test_size],
    )


def load_digits_sample(sample_size: int = 1797, train_fraction: float = 6 / 7):
    """Offline-friendly variant over sklearn's bundled 8x8 digits."""
    from sklearn.datasets import load_digits

    X, y = load_digits(return_X_y=True)
    X = X / 16.0
    X, y = X[:sample_size], y[:sample_size]
    split = int(len(X) * train_fraction)
    return _tables_from_arrays(X[:split], y[:split], X[split:], y[split:])
