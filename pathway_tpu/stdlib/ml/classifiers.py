"""ML classifiers (reference: python/pathway/stdlib/ml/classifiers/
_knn_lsh.py — LSH-based KNN classifier; backed here by the XLA KNN)."""

from __future__ import annotations

from typing import Any

import pathway_tpu.internals.reducers as red
from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.ml.index import KNNIndex


def knn_lsh_classifier_train(
    data: Table,
    L: int = 20,
    type: str = "euclidean",
    **kwargs,
):
    """Train: build the index over (data, label) rows; returns a classify
    function (reference: _knn_lsh.py knn_lsh_classifier_train)."""
    d = kwargs.get("d")
    if d is None:
        raise ValueError("provide d= (embedding dimensionality)")
    index = KNNIndex(
        data.data, data, n_dimensions=d, distance_type=type
    )

    def classify(queries: Table, k: int = 3) -> Table:
        matches = index.get_nearest_items(queries.data, k=k)
        # majority vote over neighbor labels
        def majority(labels):
            from collections import Counter

            votes = Counter(l for l in (labels or ()) if l is not None)
            if not votes:
                return None
            return votes.most_common(1)[0][0]

        return matches.select(
            predicted_label=pw_api.apply_with_type(
                majority, Any, matches.label
            )
        )

    return classify


knn_classifier_train = knn_lsh_classifier_train
