"""pw.statistical (reference:
python/pathway/stdlib/statistical/_interpolate.py)."""

from __future__ import annotations

import enum

from pathway_tpu.internals import thisclass
from pathway_tpu.internals.api import apply_with_type, coalesce, if_else
from pathway_tpu.internals.desugaring import desugar


class InterpolateMode(enum.Enum):
    LINEAR = "linear"


def _linear_interpolate(t, prev_t, prev_v, next_t, next_v):
    if prev_v is None and next_v is None:
        return None
    if prev_v is None:
        return float(next_v)
    if next_v is None:
        return float(prev_v)
    if next_t == prev_t:
        return float(prev_v)
    w = (t - prev_t) / (next_t - prev_t)
    return float(prev_v) + w * (float(next_v) - float(prev_v))


def interpolate(table, timestamp, *values, mode: InterpolateMode = InterpolateMode.LINEAR):
    """Linear interpolation of missing values over time order (reference:
    stdlib/statistical/_interpolate.py).

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... t | v
    ... 0 | 0
    ... 2 |
    ... 4 | 4
    ... ''')
    >>> res = t.interpolate(pw.this.t, pw.this.v)
    >>> pw.debug.compute_and_print(
    ...     res.select(v=pw.this.v), include_id=False
    ... )
    v
    4
    0
    2.0
    """
    if mode is not InterpolateMode.LINEAR:
        raise ValueError("only linear interpolation is supported")
    mapping = {thisclass.this: table}
    ts = desugar(timestamp, mapping)
    sorted_t = table.sort(key=ts)
    prev_rows = table.ix(sorted_t.prev, optional=True)
    next_rows = table.ix(sorted_t.next, optional=True)
    cols = {ts.name: ts} if hasattr(ts, "name") else {}
    for v in values:
        ref = desugar(v, mapping)
        # walk to neighbors; a full interpolation to farther rows requires
        # iterate; single-step interpolation covers the common case
        cols[ref.name] = coalesce(
            ref,
            apply_with_type(
                _linear_interpolate,
                float | None,
                ts,
                prev_rows[ts.name] if hasattr(ts, "name") else None,
                prev_rows[ref.name],
                next_rows[ts.name] if hasattr(ts, "name") else None,
                next_rows[ref.name],
            ),
        )
    return table.select(**cols)


__all__ = ["interpolate", "InterpolateMode"]
