"""Standard library (reference: python/pathway/stdlib)."""

from pathway_tpu.stdlib import (
    indexing,
    ml,
    ordered,
    stateful,
    statistical,
    temporal,
    utils,
)

__all__ = [
    "indexing",
    "ml",
    "ordered",
    "stateful",
    "statistical",
    "temporal",
    "utils",
]
