"""Standard library (reference: python/pathway/stdlib)."""

from pathway_tpu.stdlib import (
    graphs,
    indexing,
    ml,
    ordered,
    stateful,
    statistical,
    temporal,
    utils,
)

__all__ = [
    "graphs",
    "indexing",
    "ml",
    "ordered",
    "stateful",
    "statistical",
    "temporal",
    "utils",
]
