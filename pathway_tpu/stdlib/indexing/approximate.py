"""Approximate KNN structures: LSH and IVF-flat (reference:
src/external_integration/usearch_integration.rs — usearch HNSW approximate
index; python/pathway/stdlib/indexing/nearest_neighbors.py LshKnn:262).

Two sub-linear indexes with exact rerank of the candidate set:

* `LshIndex` — sign-random-projection LSH for cosine/IP, p-stable
  (floor((a.x + b) / bucket_length)) for euclidean; `n_or` hash tables of
  `n_and` concatenated bits each, the reference LshKnn's parameters with
  the same meaning.
* `IvfIndex` — inverted-file flat index: k-means centroids over the
  corpus, queries probe the `n_probes` nearest lists. This is the
  TPU-shaped replacement for HNSW: centroid scoring is one [Q, C] matmul
  and the probed lists rerank exactly — graph walks (usearch) do not map
  onto the MXU, coarse quantization does.

Candidate rerank is exact, so recall degrades gracefully and never
produces phantom neighbors."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _scores(metric: str, vectors: np.ndarray, queries: np.ndarray):
    """similarity (higher better) between [N,d] and [Q,d] -> [Q,N]."""
    if metric == "cos":
        v = vectors / (np.linalg.norm(vectors, axis=1, keepdims=True) + 1e-30)
        q = queries / (np.linalg.norm(queries, axis=1, keepdims=True) + 1e-30)
        return q @ v.T
    if metric == "ip":
        return queries @ vectors.T
    if metric == "l2sq":
        sq_v = (vectors * vectors).sum(axis=1)
        sq_q = (queries * queries).sum(axis=1, keepdims=True)
        return 2.0 * (queries @ vectors.T) - sq_v[None, :] - sq_q
    raise ValueError(f"unknown metric {metric!r}")


class _BaseApproxIndex:
    def __init__(self, dimensions: int, metric: str):
        self.d = dimensions
        self.metric = metric
        self.vectors: Dict[Any, np.ndarray] = {}

    def __len__(self) -> int:
        return len(self.vectors)

    def add(self, key, vector) -> None:
        vector = np.asarray(vector, dtype=np.float32).reshape(-1)
        if vector.shape[0] != self.d:
            raise ValueError(
                f"vector dim {vector.shape[0]} != index dim {self.d}"
            )
        if key in self.vectors:
            self.remove(key)
        self.vectors[key] = vector
        self._insert(key, vector)

    def remove(self, key) -> None:
        vector = self.vectors.pop(key, None)
        if vector is not None:
            self._evict(key, vector)

    def _insert(self, key, vector) -> None:
        raise NotImplementedError

    def _evict(self, key, vector) -> None:
        raise NotImplementedError

    def _candidates(self, query: np.ndarray) -> List[Any]:
        raise NotImplementedError

    def search_many(
        self, queries: np.ndarray, k: int
    ) -> List[List[Tuple[Any, float]]]:
        queries = np.asarray(queries, dtype=np.float32)
        out: List[List[Tuple[Any, float]]] = []
        for q in queries:
            cand = self._candidates(q)
            if not cand:
                out.append([])
                continue
            mat = np.stack([self.vectors[c] for c in cand])
            scores = _scores(self.metric, mat, q[None, :])[0]
            top = np.argsort(-scores)[:k]
            out.append([(cand[i], float(scores[i])) for i in top])
        return out


class LshIndex(_BaseApproxIndex):
    def __init__(
        self,
        dimensions: int,
        *,
        metric: str = "cos",
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        seed: int = 0,
    ):
        super().__init__(dimensions, metric)
        self.n_or = n_or
        self.n_and = n_and
        self.bucket_length = float(bucket_length)
        rng = np.random.default_rng(seed)
        # [n_or, n_and, d] projection directions
        self.planes = rng.standard_normal(
            (n_or, n_and, dimensions)
        ).astype(np.float32)
        if metric == "l2sq":
            self.offsets = rng.uniform(
                0.0, self.bucket_length, size=(n_or, n_and)
            ).astype(np.float32)
        self.tables: List[Dict[tuple, set]] = [dict() for _ in range(n_or)]

    def _hashes(self, vector: np.ndarray) -> List[tuple]:
        proj = self.planes @ vector  # [n_or, n_and]
        if self.metric == "l2sq":
            buckets = np.floor(
                (proj + self.offsets) / self.bucket_length
            ).astype(np.int64)
            return [tuple(row) for row in buckets]
        return [tuple((row > 0).astype(np.int8)) for row in proj]

    def _insert(self, key, vector) -> None:
        for table, h in zip(self.tables, self._hashes(vector)):
            table.setdefault(h, set()).add(key)

    def _evict(self, key, vector) -> None:
        for table, h in zip(self.tables, self._hashes(vector)):
            bucket = table.get(h)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del table[h]

    def _candidates(self, query: np.ndarray) -> List[Any]:
        seen: set = set()
        for table, h in zip(self.tables, self._hashes(query)):
            seen |= table.get(h, set())
        return list(seen)


class IvfIndex(_BaseApproxIndex):
    def __init__(
        self,
        dimensions: int,
        *,
        metric: str = "cos",
        n_probes: int = 4,
        retrain_every: int = 1024,
        max_centroids: int = 256,
        seed: int = 0,
    ):
        super().__init__(dimensions, metric)
        self.n_probes = n_probes
        self.retrain_every = retrain_every
        self.max_centroids = max_centroids
        self.seed = seed
        self.centroids: Optional[np.ndarray] = None
        self.lists: List[set] = []
        self.assignment: Dict[Any, int] = {}
        self._since_train = 0

    def _n_centroids(self) -> int:
        return int(min(self.max_centroids, max(1, np.sqrt(len(self.vectors)))))

    def _retrain(self) -> None:
        if not self.vectors:
            self.centroids, self.lists, self.assignment = None, [], {}
            return
        keys = list(self.vectors.keys())
        data = np.stack([self.vectors[k] for k in keys])
        n_c = self._n_centroids()
        if len(keys) <= n_c:
            self.centroids = data.copy()
        else:
            rng = np.random.default_rng(self.seed)
            centroids = data[rng.choice(len(keys), n_c, replace=False)]
            for _ in range(8):  # lloyd iterations; one matmul each on TPU
                assign = np.argmax(_scores(self.metric, centroids, data), 1)
                for c in range(n_c):
                    members = data[assign == c]
                    if len(members):
                        centroids[c] = members.mean(axis=0)
            self.centroids = centroids
        assign = np.argmax(_scores(self.metric, self.centroids, data), axis=1)
        self.lists = [set() for _ in range(len(self.centroids))]
        self.assignment = {}
        for key, c in zip(keys, assign):
            self.lists[int(c)].add(key)
            self.assignment[key] = int(c)
        self._since_train = 0

    def _insert(self, key, vector) -> None:
        self._since_train += 1
        if self.centroids is None or self._since_train >= self.retrain_every:
            self._retrain()
            return
        c = int(
            np.argmax(_scores(self.metric, self.centroids, vector[None, :]))
        )
        self.lists[c].add(key)
        self.assignment[key] = c

    def _evict(self, key, vector) -> None:
        c = self.assignment.pop(key, None)
        if c is not None and c < len(self.lists):
            self.lists[c].discard(key)

    def _candidates(self, query: np.ndarray) -> List[Any]:
        if self.centroids is None:
            return []
        scores = _scores(self.metric, self.centroids, query[None, :])[0]
        order = np.argsort(-scores)[: self.n_probes]
        cand: set = set()
        for c in order:
            cand |= self.lists[int(c)]
        return list(cand)
