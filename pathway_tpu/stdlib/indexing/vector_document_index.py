"""Prebuilt document indexes (reference:
python/pathway/stdlib/indexing/vector_document_index.py:12-196)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    LshKnnFactory,
    UsearchKnnFactory,
)


def default_vector_document_index(
    data_column,
    data_table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column=None,
) -> DataIndex:
    return default_brute_force_knn_document_index(
        data_column,
        data_table,
        embedder=embedder,
        dimensions=dimensions,
        metadata_column=metadata_column,
    )


def default_brute_force_knn_document_index(
    data_column,
    data_table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column=None,
) -> DataIndex:
    factory = BruteForceKnnFactory(
        dimensions=dimensions,
        metric=BruteForceKnnMetricKind.COS,
        embedder=embedder,
    )
    return factory.build_index(data_column, data_table, metadata_column)


def default_usearch_knn_document_index(
    data_column,
    data_table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column=None,
) -> DataIndex:
    factory = UsearchKnnFactory(dimensions=dimensions, embedder=embedder)
    return factory.build_index(data_column, data_table, metadata_column)


def default_lsh_knn_document_index(
    data_column,
    data_table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column=None,
) -> DataIndex:
    factory = LshKnnFactory(dimensions=dimensions, embedder=embedder)
    return factory.build_index(data_column, data_table, metadata_column)
