"""DataIndex / InnerIndex — the retrieval API (reference:
python/pathway/stdlib/indexing/data_index.py: InnerIndex:206, DataIndex:278,
result repacking :294)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    collect_tables,
    smart_wrap,
)
from pathway_tpu.internals.parse_graph import record_op
from pathway_tpu.internals.schema import (
    ColumnSchema,
    Schema,
    schema_from_columns,
)
from pathway_tpu.internals.table import Table, _compile_on
from pathway_tpu.internals.universe import Universe


class IdScoreSchema(Schema):
    _pw_index_reply_id: Any
    _pw_index_reply_score: float


class InnerIndex:
    """Index over a data column (reference: data_index.py InnerIndex:206).

    Subclasses provide `_make_impl()` returning an engine IndexImpl."""

    def __init__(self, data_column: ColumnReference, metadata_column=None):
        self.data_column = data_column
        self.metadata_column = metadata_column
        tables = list(collect_tables(data_column, set()))
        if len(tables) != 1:
            raise ValueError("index data column must reference one table")
        self.data_table: Table = tables[0]

    def _make_impl(self):
        raise NotImplementedError

    def _query_preprocess(self, query_column: ColumnExpression):
        """Hook: e.g. embed query text before KNN search."""
        return query_column

    def _data_preprocess(self, data_column: ColumnExpression):
        return data_column


class DataIndex:
    """A data table + an inner index; answers query tables (reference:
    data_index.py DataIndex:278)."""

    def __init__(
        self,
        data_table: Table,
        inner_index: InnerIndex,
    ):
        self.data_table = data_table
        self.inner = inner_index

    def query_as_of_now(
        self,
        query_column: ColumnExpression,
        *,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        return self._query(
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
            as_of_now=True,
        )

    def query(
        self,
        query_column: ColumnExpression,
        *,
        number_of_matches: Any = 3,
        collapse_rows: bool = True,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        return self._query(
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
            as_of_now=False,
        )

    def _query(
        self,
        query_column: ColumnExpression,
        *,
        number_of_matches,
        collapse_rows,
        metadata_filter,
        as_of_now,
    ) -> Table:
        query_column = self.inner._query_preprocess(smart_wrap(query_column))
        q_tables = list(collect_tables(query_column, set()))
        if len(q_tables) != 1:
            raise ValueError("query column must reference one table")
        query_table = q_tables[0]
        data_table = self.data_table
        inner = self.inner
        data_value_expr = inner._data_preprocess(inner.data_column)
        k_expr = smart_wrap(number_of_matches)
        filter_expr = (
            smart_wrap(metadata_filter) if metadata_filter is not None else None
        )

        def build(ctx):
            from pathway_tpu.engine.exchange import exchange_by_key
            from pathway_tpu.engine.index_node import ExternalIndexNode

            data_node = ctx.node(data_table)
            query_node = ctx.node(query_table)
            return exchange_by_key(ctx.engine, ExternalIndexNode(
                ctx.engine,
                data_node,
                query_node,
                inner._make_impl(),
                _compile_on(ctx, [data_table], data_value_expr),
                (
                    _compile_on(ctx, [data_table], inner.metadata_column)
                    if inner.metadata_column is not None
                    else None
                ),
                _compile_on(ctx, [query_table], query_column),
                _compile_on(ctx, [query_table], k_expr),
                (
                    _compile_on(ctx, [query_table], filter_expr)
                    if filter_expr is not None
                    else None
                ),
                data_width=len(data_table.column_names()),
                as_of_now=as_of_now,
            ))

        cols: dict = {
            "_pw_index_reply_id": ColumnSchema(
                name="_pw_index_reply_id", dtype=dt.ListDType(dt.POINTER)
            ),
            "_pw_index_reply_score": ColumnSchema(
                name="_pw_index_reply_score", dtype=dt.ListDType(dt.FLOAT)
            ),
        }
        for name, c in data_table._schema.columns().items():
            cols[name] = ColumnSchema(
                name=name, dtype=dt.ListDType(dt.Optionalize(c.dtype))
            )
        reply = Table(
            schema=schema_from_columns(cols),
            universe=query_table._universe,
            build=build,
        )
        # capacity annotation for the PWT6xx pass (analysis/capacity.py):
        # the analyzer predicts the device footprint of this index from
        # the same numbers the runtime will allocate with
        record_op(
            reply,
            "external_index",
            (query_table, data_table),
            index=type(inner).__name__,
            dimensions=getattr(inner, "dimensions", None),
            reserved_space=getattr(inner, "reserved_space", None),
            metric=_metric_name(inner),
            encoder=_encoder_info(getattr(inner, "embedder", None)),
        )
        if collapse_rows:
            # zip query columns alongside (same universe)
            out_cols = {}
            for name in query_table.column_names():
                out_cols[name] = query_table[name]
            for name in reply.column_names():
                if name not in out_cols:
                    out_cols[name] = reply[name]
            return reply._select_impl(out_cols)
        # one row per match
        paired = reply._select_impl(
            {
                **{name: query_table[name] for name in query_table.column_names()},
                "_pw_pairs": _zip_pairs_expr(reply),
            }
        )
        flat = paired.flatten(paired._pw_pairs)
        out_cols = {}
        for name in query_table.column_names():
            out_cols[name] = flat[name]
        out_cols["_pw_index_reply_id"] = flat._pw_pairs.get(0)
        out_cols["_pw_index_reply_score"] = flat._pw_pairs.get(1)
        data_names = self.data_table.column_names()
        for i, name in enumerate(data_names):
            out_cols[name] = flat._pw_pairs.get(2 + i)
        return flat._select_impl(out_cols)


def _metric_name(inner: InnerIndex) -> Optional[str]:
    m = getattr(inner, "metric", None)
    return getattr(m, "value", m) if m is not None else None


def _encoder_info(embedder: Any) -> Optional[dict]:
    """Geometry of a local JAX encoder (the fused-path criterion in
    stdlib/indexing/nearest_neighbors._local_jax_encoder), as a plain
    dict the analyzer can price with costmodel.encoder_param_count.
    API-backed embedders (no device-resident params) return None."""
    encoder = getattr(embedder, "encoder", None)
    if encoder is None or not hasattr(encoder, "lm"):
        return None
    cfg = getattr(encoder, "config", None)
    if cfg is None:
        return None
    return {
        "vocab_size": int(getattr(cfg, "vocab_size", 30522)),
        "hidden": int(getattr(cfg, "hidden", 0)),
        "layers": int(getattr(cfg, "layers", 0)),
        "mlp_dim": int(getattr(cfg, "mlp_dim", 0)),
        "max_len": int(getattr(cfg, "max_len", 512)),
    }


def _zip_pairs_expr(reply: Table):
    from pathway_tpu.internals.api import apply_with_type

    data_cols = [
        c
        for c in reply.column_names()
        if c not in ("_pw_index_reply_id", "_pw_index_reply_score")
    ]

    def zipper(ids, scores, *cols):
        return tuple(
            (i, s, *(col[j] for col in cols))
            for j, (i, s) in enumerate(zip(ids, scores))
        )

    return apply_with_type(
        zipper,
        tuple,
        reply._pw_index_reply_id,
        reply._pw_index_reply_score,
        *(reply[c] for c in data_cols),
    )
