"""Full-text document index (reference:
python/pathway/stdlib/indexing/full_text_document_index.py)."""

from __future__ import annotations

from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25Factory
from pathway_tpu.stdlib.indexing.data_index import DataIndex


def default_full_text_document_index(
    data_column,
    data_table,
    *,
    metadata_column=None,
) -> DataIndex:
    factory = TantivyBM25Factory()
    return factory.build_index(data_column, data_table, metadata_column)
