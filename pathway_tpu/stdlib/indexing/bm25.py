"""BM25 full-text index (reference: python/pathway/stdlib/indexing/bm25.py
TantivyBM25:41; backend src/external_integration/tantivy_integration.rs).

A pure-python incremental BM25 (Okapi) replaces the tantivy crate; scoring is
vectorized with numpy over the candidate postings.

>>> import pathway_tpu as pw
>>> from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25Factory
>>> docs = pw.debug.table_from_rows(
...     pw.schema_from_types(text=str),
...     [("the quick brown fox",), ("lazy dogs sleep",)],
... )
>>> index = TantivyBM25Factory().build_index(docs.text, docs)
>>> q = pw.debug.table_from_rows(pw.schema_from_types(q=str), [("fox",)])
>>> r = index.query_as_of_now(q.q, number_of_matches=1)
>>> sorted(r.column_names())
['_pw_index_reply_id', '_pw_index_reply_score', 'q', 'text']
"""

from __future__ import annotations

import math
import re
from collections import Counter
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from pathway_tpu.engine.index_node import IndexImpl
from pathway_tpu.stdlib.indexing._filters import evaluate_filter
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    AbstractRetrieverFactory,
)
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex

_TOKEN_RE = re.compile(r"[A-Za-z0-9_]+")


def _tokenize(text: str) -> List[str]:
    return [t.lower() for t in _TOKEN_RE.findall(text or "")]


class BM25IndexImpl(IndexImpl):
    K1 = 1.2
    B = 0.75

    def __init__(self):
        self.docs: Dict[Any, Counter] = {}
        self.doc_len: Dict[Any, int] = {}
        self.postings: Dict[str, Dict[Any, int]] = {}
        self.metadata: Dict[Any, Any] = {}
        self.total_len = 0

    def add(self, key, value, metadata) -> None:
        if key in self.docs:
            self.remove(key)
        tokens = Counter(_tokenize(value))
        self.docs[key] = tokens
        length = sum(tokens.values())
        self.doc_len[key] = length
        self.total_len += length
        for term, tf in tokens.items():
            self.postings.setdefault(term, {})[key] = tf
        if metadata is not None:
            self.metadata[key] = metadata

    def remove(self, key) -> None:
        tokens = self.docs.pop(key, None)
        if tokens is None:
            return
        self.total_len -= self.doc_len.pop(key, 0)
        for term in tokens:
            bucket = self.postings.get(term)
            if bucket is not None:
                bucket.pop(key, None)
                if not bucket:
                    del self.postings[term]
        self.metadata.pop(key, None)

    def search(self, value, k, metadata_filter):
        n = len(self.docs)
        if n == 0:
            return []
        avg_len = self.total_len / n
        scores: Dict[Any, float] = {}
        for term in _tokenize(value):
            bucket = self.postings.get(term)
            if not bucket:
                continue
            df = len(bucket)
            idf = math.log(1.0 + (n - df + 0.5) / (df + 0.5))
            for key, tf in bucket.items():
                dl = self.doc_len[key]
                denom = tf + self.K1 * (1 - self.B + self.B * dl / avg_len)
                scores[key] = scores.get(key, 0.0) + idf * tf * (self.K1 + 1) / denom
        ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
        if metadata_filter:
            ranked = [
                (key, s)
                for key, s in ranked
                if evaluate_filter(metadata_filter, self.metadata.get(key))
            ]
        return ranked[:k]


class TantivyBM25(InnerIndex):
    """reference: bm25.py TantivyBM25:41 (name kept for parity; backend is
    the in-tree BM25, not tantivy)."""

    def __init__(
        self,
        data_column,
        metadata_column=None,
        *,
        ram_budget: int = 50_000_000,
        in_memory_index: bool = True,
    ):
        super().__init__(data_column, metadata_column)

    def _make_impl(self) -> IndexImpl:
        return BM25IndexImpl()


@dataclass(kw_only=True)
class TantivyBM25Factory(AbstractRetrieverFactory):
    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        return TantivyBM25(
            data_column,
            metadata_column,
            ram_budget=self.ram_budget,
            in_memory_index=self.in_memory_index,
        )

