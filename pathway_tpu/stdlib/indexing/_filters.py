"""Metadata filtering for index queries.

The reference filters candidates with JMESPath expressions
(src/external_integration/mod.rs IndexDerivedImpl — jmespath crate). We use
the python `jmespath` package when available and otherwise fall back to a
small evaluator covering the subset the LLM xpack emits
(`field == 'value'`, `contains(path, 'x')`, &&/||, globmatch)."""

from __future__ import annotations

import fnmatch
import re
from typing import Any

try:
    import jmespath as _jmespath
except ImportError:  # pragma: no cover
    _jmespath = None


def evaluate_filter(filter_expr: str, metadata: Any) -> bool:
    from pathway_tpu.engine.value import Json

    if filter_expr is None:
        return True
    if isinstance(metadata, Json):
        metadata = metadata.value
    if metadata is None:
        metadata = {}
    if _jmespath is not None:
        try:
            return bool(_jmespath.search(filter_expr, metadata))
        except Exception:  # noqa: BLE001
            return False
    return bool(_mini_eval(filter_expr, metadata))


_TOKEN = re.compile(
    r"\s*(&&|\|\||==|!=|>=|<=|>|<|\(|\)|`[^`]*`|'[^']*'|\"[^\"]*\""
    r"|[A-Za-z_][A-Za-z0-9_.]*\([^()]*\)|[A-Za-z_][A-Za-z0-9_.]*|-?\d+\.?\d*)"
)


def _mini_eval(expr: str, metadata: dict) -> Any:
    tokens = _TOKEN.findall(expr)
    pos = [0]

    def parse_or():
        left = parse_and()
        while pos[0] < len(tokens) and tokens[pos[0]] == "||":
            pos[0] += 1
            right = parse_and()
            left = bool(left) or bool(right)
        return left

    def parse_and():
        left = parse_cmp()
        while pos[0] < len(tokens) and tokens[pos[0]] == "&&":
            pos[0] += 1
            right = parse_cmp()
            left = bool(left) and bool(right)
        return left

    def parse_cmp():
        left = parse_atom()
        if pos[0] < len(tokens) and tokens[pos[0]] in (
            "==",
            "!=",
            ">",
            "<",
            ">=",
            "<=",
        ):
            op = tokens[pos[0]]
            pos[0] += 1
            right = parse_atom()
            try:
                if op == "==":
                    return left == right
                if op == "!=":
                    return left != right
                if op == ">":
                    return left > right
                if op == "<":
                    return left < right
                if op == ">=":
                    return left >= right
                if op == "<=":
                    return left <= right
            except TypeError:
                return False
        return left

    def parse_atom():
        tok = tokens[pos[0]]
        pos[0] += 1
        if tok == "(":
            v = parse_or()
            if pos[0] < len(tokens) and tokens[pos[0]] == ")":
                pos[0] += 1
            return v
        if tok.startswith(("`", "'", '"')):
            inner = tok[1:-1]
            try:
                import json

                return json.loads(inner)
            except Exception:  # noqa: BLE001
                return inner
        if re.fullmatch(r"-?\d+", tok):
            return int(tok)
        if re.fullmatch(r"-?\d+\.\d*", tok):
            return float(tok)
        call = re.fullmatch(r"([A-Za-z_][A-Za-z0-9_.]*)\((.*)\)", tok)
        if call:
            fname, argstr = call.group(1), call.group(2)
            args = [a.strip() for a in argstr.split(",")] if argstr else []
            vals = [_atom_value(a, metadata) for a in args]
            if fname == "contains" and len(vals) == 2:
                try:
                    return vals[1] in vals[0]
                except TypeError:
                    return False
            if fname == "globmatch" and len(vals) == 2:
                return fnmatch.fnmatch(str(vals[1]), str(vals[0]))
            if fname == "to_string" and len(vals) == 1:
                return str(vals[0])
            return False
        return _lookup_path(tok, metadata)

    try:
        return parse_or()
    except (IndexError, ValueError):
        return False


def _atom_value(text: str, metadata: dict):
    text = text.strip()
    if text.startswith(("`", "'", '"')) and len(text) >= 2:
        inner = text[1:-1]
        try:
            import json

            return json.loads(inner)
        except Exception:  # noqa: BLE001
            return inner
    if re.fullmatch(r"-?\d+", text):
        return int(text)
    if re.fullmatch(r"-?\d+\.\d*", text):
        return float(text)
    return _lookup_path(text, metadata)


def _lookup_path(path: str, metadata: Any):
    cur = metadata
    for part in path.split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    return cur
