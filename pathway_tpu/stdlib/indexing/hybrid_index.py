"""Hybrid retrieval via reciprocal rank fusion (reference:
python/pathway/stdlib/indexing/hybrid_index.py HybridIndex:14, RRF :35-120)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

from pathway_tpu.engine.index_node import IndexImpl
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    AbstractRetrieverFactory,
)
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex


class _HybridImpl(IndexImpl):
    def __init__(self, impls: List[IndexImpl], k_const: float):
        self.impls = impls
        self.k_const = k_const

    def add(self, key, value, metadata) -> None:
        # value is a tuple: one entry per inner index
        for impl, v in zip(self.impls, value):
            impl.add(key, v, metadata)

    def remove(self, key) -> None:
        for impl in self.impls:
            impl.remove(key)

    def search(self, value, k, metadata_filter):
        fused: Dict[Any, float] = {}
        for impl, v in zip(self.impls, value):
            results = impl.search(v, k, metadata_filter)
            for rank, (key, _score) in enumerate(results):
                fused[key] = fused.get(key, 0.0) + 1.0 / (
                    self.k_const + rank + 1
                )
        ranked = sorted(fused.items(), key=lambda kv: (-kv[1], kv[0]))
        return ranked[:k]


class HybridIndex(InnerIndex):
    """Fuses rankings of several inner indexes over the same data table."""

    def __init__(self, inner_indexes: List[InnerIndex], *, k: float = 60.0):
        self.inner_indexes = inner_indexes
        self.k_const = k
        first = inner_indexes[0]
        from pathway_tpu.internals.api import make_tuple

        data_cols = [
            idx._data_preprocess(idx.data_column) for idx in inner_indexes
        ]
        self.data_column = make_tuple(*data_cols)
        self.metadata_column = first.metadata_column
        self.data_table = first.data_table

    def _make_impl(self) -> IndexImpl:
        return _HybridImpl(
            [idx._make_impl() for idx in self.inner_indexes], self.k_const
        )

    def _query_preprocess(self, query_column):
        from pathway_tpu.internals.api import make_tuple

        return make_tuple(
            *(idx._query_preprocess(query_column) for idx in self.inner_indexes)
        )

    def _data_preprocess(self, data_column):
        return self.data_column


@dataclass
class HybridIndexFactory(AbstractRetrieverFactory):
    retriever_factories: List[Any]
    k: float = 60.0

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        inner = [
            f.build_inner_index(data_column, metadata_column)
            for f in self.retriever_factories
        ]
        return HybridIndex(inner, k=self.k)

