"""pw.indexing — KNN / BM25 / hybrid retrieval (reference:
python/pathway/stdlib/indexing/). Filled by the TPU data plane:
BruteForceKnn runs as a sharded XLA matmul+top_k (see pathway_tpu/ops/knn.py).
"""

from pathway_tpu.stdlib.indexing.data_index import (
    DataIndex,
    InnerIndex,
    IdScoreSchema,
)
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    AbstractRetrieverFactory,
    BruteForceKnn,
    DefaultKnnFactory,
    LshKnnFactory,
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    LshKnn,
    USearchKnn,
    UsearchKnnFactory,
    USearchMetricKind,
)
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25, TantivyBM25Factory
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex, HybridIndexFactory
from pathway_tpu.stdlib.indexing.vector_document_index import (
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)
from pathway_tpu.stdlib.indexing.full_text_document_index import (
    default_full_text_document_index,
)

__all__ = [
    "AbstractRetrieverFactory",
    "DefaultKnnFactory",
    "LshKnnFactory",
    "DataIndex",
    "InnerIndex",
    "IdScoreSchema",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "BruteForceKnnMetricKind",
    "USearchKnn",
    "UsearchKnnFactory",
    "USearchMetricKind",
    "LshKnn",
    "TantivyBM25",
    "TantivyBM25Factory",
    "HybridIndex",
    "HybridIndexFactory",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_lsh_knn_document_index",
    "default_usearch_knn_document_index",
    "default_full_text_document_index",
]
