"""KNN inner indexes (reference:
python/pathway/stdlib/indexing/nearest_neighbors.py: BruteForceKnn:170,
USearchKnn:65, LshKnn:262, factories :407-580).

All variants run on the XLA brute-force kernel (ops/knn.py) — the TPU-native
equivalent of usearch-HNSW at these index sizes is a batched matmul+top_k on
the MXU; the classes keep API parity with the reference so user code ports
unchanged."""

from __future__ import annotations

import logging
import os
import enum
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

from pathway_tpu.engine.index_node import IndexImpl
from pathway_tpu.internals import serving as _serving
from pathway_tpu.ops.knn import DeviceKnnIndex
from pathway_tpu.stdlib.indexing._filters import evaluate_filter
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex


class BruteForceKnnMetricKind(enum.Enum):
    COS = "cos"
    L2SQ = "l2sq"
    IP = "ip"


class USearchMetricKind(enum.Enum):
    COS = "cos"
    L2SQ = "l2sq"
    IP = "ip"


def _ingest_backend():
    """The process-wide mesh execution backend, when it can shard the
    bucketed ingest/index axes (power-of-two dp). Impls are built at
    engine-build time — inside pw.run(mesh=...) — so this is where an
    explicit `mesh=None` factory picks up the run's mesh."""
    from pathway_tpu.internals.mesh_backend import active_backend

    backend = active_backend()
    if backend is not None and backend.can_shard_ingest():
        return backend
    return None


class _KnnIndexImpl(IndexImpl):
    """Device KNN with a degradation host path.

    ``DeviceKnnIndex.add``/``remove`` only mutate host-side staging (the
    device scatter happens lazily inside ``search``), so while the device
    monitor reports DEGRADED this impl serves searches from a numpy
    brute-force pass over a host mirror of the vectors and never issues a
    device dispatch — a dead tunnel would hang one indefinitely.  On
    re-promotion the next device search flushes everything staged in the
    interim.  The mirror costs one float32 copy per live vector."""

    # every mutation flows through DeviceKnnIndex.add/remove, whose
    # serving generation hooks invalidate cached results — so the
    # serving result cache may front search_many (engine/index_node.py)
    supports_result_cache = True

    def __init__(self, dimensions: int, metric: str, reserved_space: int, mesh=None):
        if mesh is None:
            backend = _ingest_backend()
            if backend is not None:
                mesh = backend.mesh
        self.knn = DeviceKnnIndex(
            dimensions, metric=metric, reserved_space=reserved_space, mesh=mesh
        )
        self.metric = metric
        self.metadata: dict = {}
        self._host_vecs: dict = {}

    def add(self, key, value, metadata) -> None:
        vec = np.asarray(value, dtype=np.float32)
        self.knn.add(key, vec)
        self._host_vecs[key] = vec.reshape(-1)
        if metadata is not None:
            self.metadata[key] = metadata

    def remove(self, key) -> None:
        self.knn.remove(key)
        self._host_vecs.pop(key, None)
        self.metadata.pop(key, None)

    def _host_search(self, queries: np.ndarray, fetch: int) -> list:
        """Numpy brute force over the host mirror; same (key, score) row
        shape as DeviceKnnIndex.search_keys, higher-is-better scores."""
        keys = list(self._host_vecs.keys())
        mat = np.stack([self._host_vecs[k] for k in keys])
        if self.metric == "cos":
            qn = queries / (
                np.linalg.norm(queries, axis=1, keepdims=True) + 1e-30
            )
            mn = mat / (np.linalg.norm(mat, axis=1, keepdims=True) + 1e-30)
            scores = qn @ mn.T
        elif self.metric == "ip":
            scores = queries @ mat.T
        else:  # l2sq: negated squared distance so higher is better
            scores = -(
                (queries**2).sum(axis=1, keepdims=True)
                - 2.0 * queries @ mat.T
                + (mat**2).sum(axis=1)[None, :]
            )
        fetch = min(fetch, len(keys))
        order = np.argsort(-scores, axis=1)[:, :fetch]
        return [
            [(keys[j], float(scores[i, j])) for j in row]
            for i, row in enumerate(order)
        ]

    def search(self, value, k, metadata_filter):
        return self.search_many([value], [k], [metadata_filter])[0]

    def search_many(self, values, ks, filters):
        from pathway_tpu.internals.device_probe import device_degraded

        if not values:
            return []
        if not self._host_vecs:
            return [[] for _ in values]
        k_max = max(ks) if ks else 3
        # over-fetch when filtering so post-filter top-k stays full
        fetch = min(
            len(self._host_vecs),
            max(k_max, k_max * 4 if any(f for f in filters) else k_max),
        )
        queries = np.stack([np.asarray(v, dtype=np.float32) for v in values])
        if device_degraded():
            rows = self._host_search(queries, fetch)
        else:
            rows = self.knn.search_keys(queries, fetch)
        out = []
        for row, k, filt in zip(rows, ks, filters):
            if filt:
                row = [
                    (key, s)
                    for key, s in row
                    if evaluate_filter(filt, self.metadata.get(key))
                ]
            out.append(row[:k])
        return out


class _FusedKnnIndexImpl(IndexImpl):
    """Embed+search fused into one device dispatch per batch.

    When the embedder is a local JAX sentence encoder, documents and queries
    arrive as raw text and the impl runs tokenize → encoder → similarity →
    top_k as a single jit call (ops/knn.py FusedEmbedSearch). Document
    embeddings are computed and scattered into the device index without ever
    leaving HBM. This is the framework wiring of SURVEY §3.4's hot path."""

    # adds (sync or pipelined) and removes all land in DeviceKnnIndex,
    # whose serving generation hooks keep the result cache sound
    supports_result_cache = True

    def __init__(self, encoder, metric: str, reserved_space: int, mesh=None):
        from pathway_tpu.ops.knn import DeviceKnnIndex, FusedEmbedSearch

        backend = None
        if mesh is None:
            # adopt the run's mesh backend: dp-sharded index + dp-grouped
            # packed ingest + tp-sharded encoder (ops/knn.FusedEmbedSearch)
            backend = _ingest_backend()
            if backend is not None:
                mesh = backend.mesh
        self._backend = backend
        self.knn = DeviceKnnIndex(
            encoder.dimension, metric=metric, reserved_space=reserved_space,
            mesh=mesh,
        )
        self.fused = FusedEmbedSearch(encoder, self.knn, backend=backend)
        self.metadata: dict = {}
        self._pipeline = None
        self._pipeline_broken = False

    def add(self, key, value, metadata) -> None:
        self.add_many([key], [value], [metadata])

    @staticmethod
    def _ingest_chunk() -> int:
        """Ingest chunking trades host/device overlap against per-dispatch
        round trips.  Behind a high-RTT tunneled chip every extra dispatch
        costs a round trip, so the default is one monolithic dispatch
        (measured: 9.9k vs 7.3k docs/s at ~100 ms RTT); on a local chip
        set PATHWAY_INGEST_CHUNK=4096 to overlap tokenization with the
        MXU (measured ~1.8x on the bare ops path).  Read per call so the
        knob works after import; invalid/negative values mean 'off'."""
        try:
            return max(0, int(os.environ.get("PATHWAY_INGEST_CHUNK", "0")))
        except ValueError:
            return 0

    # -- async device pipeline wiring --------------------------------------

    def _use_pipeline(self) -> bool:
        from pathway_tpu.internals.device_pipeline import pipeline_enabled
        from pathway_tpu.internals.device_probe import device_degraded

        # a factory-attached mesh keeps the classic dispatch (sharded
        # inputs would need per-shard donation bookkeeping); the mesh
        # BACKEND path pipelines — its dp-grouped slabs dispatch as one
        # SPMD program, one in-flight window per dp replica.  DEGRADED
        # devices bypass the pipeline so in-flight work drains and new
        # batches take the synchronous path the monitor already guards
        return (
            pipeline_enabled()
            and not self._pipeline_broken
            and (self.knn.mesh is None or self._backend is not None)
            and not device_degraded()
        )

    def _ensure_pipeline(self):
        if self._pipeline is None:
            from pathway_tpu.internals.device_pipeline import DevicePipeline

            self._pipeline = DevicePipeline(
                prepare=lambda item: self.fused.prepare_batch(*item),
                dispatch=self.fused.dispatch_batch,
                quiesce=self._quiesce_device,
                name="knn-ingest",
                replicas=self._backend.dp if self._backend else 1,
            )
        return self._pipeline

    def _quiesce_device(self) -> None:
        # scalar readback on the index buffer: completion of this sum
        # implies completion of every scatter in the donated-buffer chain
        import jax.numpy as jnp

        self.knn._flush()
        buf = getattr(self.knn, "_buffer", None)
        if buf is not None:
            np.asarray(jnp.sum(buf[:1, :4].astype(jnp.float32)))

    def _pipeline_step(self, n: int) -> int:
        # finer chunks than the monolithic sync default: prepare of chunk
        # i+1 overlaps device execution of chunk i (the whole point);
        # PATHWAY_INGEST_CHUNK still wins when set
        return self._ingest_chunk() or min(max(n, 1), 1024)

    def _disable_pipeline(self, exc) -> None:
        """Per-batch fallback, columnar-exchange style: disable the
        pipeline for this impl and replay every parked batch on the
        classic synchronous path (exactly once — parked batches never
        reached the device)."""
        self._pipeline_broken = True
        failed = self._pipeline.take_failed() if self._pipeline else []
        logging.getLogger(__name__).warning(
            "device pipeline disabled after %s: %s; replaying %d "
            "batch(es) synchronously",
            type(getattr(exc, "__cause__", None) or exc).__name__,
            exc,
            len(failed),
        )
        for keys_c, texts_c in failed:
            self.fused.embed_and_add(keys_c, texts_c)

    def _sync_pipeline(self, *, full: bool = False) -> None:
        """barrier (dispatched) or full drain (executed) of the ingest
        pipeline; pipeline failures downgrade to the sync replay path."""
        from pathway_tpu.internals.device_pipeline import DevicePipelineError

        pipe = self._pipeline
        if pipe is None:
            return
        try:
            if full:
                pipe.drain()
            else:
                pipe.barrier()
        except DevicePipelineError as exc:
            self._disable_pipeline(exc)

    def drain(self) -> None:
        """Complete all in-flight pipeline batches and quiesce the device
        — the snapshot / rollback / failover / finish contract."""
        self._sync_pipeline(full=True)

    def take_aux_spans(self):
        if self._pipeline is None:
            return []
        return self._pipeline.take_aux_spans()

    def add_many(self, keys, values, metas) -> None:
        from pathway_tpu.internals.device_pipeline import DevicePipelineError

        texts = [v if isinstance(v, str) else str(v) for v in values]
        keys = list(keys)
        if _serving.ENABLED and keys:
            # the pipelined path defers the DeviceKnnIndex scatter (and
            # its generation hook) until dispatch; bump at SUBMIT so a
            # cache consult racing the pipeline can only over-invalidate,
            # never serve a result that predates this delta
            _serving.note_index_add(len(keys))
        if texts and self._use_pipeline():
            pipe = self._ensure_pipeline()
            step = self._pipeline_step(len(texts))
            chunks = [
                (keys[s : s + step], texts[s : s + step])
                for s in range(0, len(texts), step)
            ]
            for i, chunk in enumerate(chunks):
                try:
                    pipe.submit(chunk)
                except DevicePipelineError as exc:
                    self._disable_pipeline(exc)
                    for keys_c, texts_c in chunks[i:]:
                        self.fused.embed_and_add(keys_c, texts_c)
                    break
        elif texts:
            # classic synchronous path (PATHWAY_DEVICE_PIPELINE=0, mesh,
            # degraded device, or prior pipeline failure); finish any
            # still-pipelined work first so delta order is preserved
            self._sync_pipeline(full=True)
            step = self._ingest_chunk() or len(texts) or 1
            for s in range(0, len(texts), step):
                self.fused.embed_and_add(
                    keys[s : s + step], texts[s : s + step]
                )
        for key, meta in zip(keys, metas):
            if meta is not None:
                self.metadata[key] = meta

    def remove(self, key) -> None:
        # removes mutate the slot maps the dispatcher also writes — order
        # behind everything already submitted
        self._sync_pipeline()
        self.knn.remove(key)
        self.metadata.pop(key, None)

    def search(self, value, k, metadata_filter):
        return self.search_many([value], [k], [metadata_filter])[0]

    def search_many(self, values, ks, filters):
        # searches read the device buffer: a dispatch barrier suffices —
        # XLA's data dependency on the scatter chain orders the rest
        self._sync_pipeline()
        if not values:
            return []
        if len(self.knn) == 0:
            return [[] for _ in values]
        k_max = max(int(k) for k in ks) if ks else 3
        fetch = min(
            len(self.knn),
            k_max * 4 if any(f for f in filters) else k_max,
        )
        texts = [v if isinstance(v, str) else str(v) for v in values]
        rows = self.fused.search_texts(texts, fetch)
        out = []
        for row, k, filt in zip(rows, ks, filters):
            if filt:
                row = [
                    (key, s)
                    for key, s in row
                    if evaluate_filter(filt, self.metadata.get(key))
                ]
            out.append(row[: int(k)])
        return out


def _local_jax_encoder(embedder):
    """The fused path needs a device-resident encoder: a
    SentenceTransformerEmbedder-style object exposing `.encoder` with
    tokenizer/params. API-backed embedders (OpenAI etc.) return None and
    keep the UDF pre-embedding path."""
    encoder = getattr(embedder, "encoder", None)
    if encoder is not None and hasattr(encoder, "lm") and hasattr(
        encoder, "tokenizer"
    ):
        return encoder
    return None


class BruteForceKnn(InnerIndex):
    """Exact KNN on the TPU mesh (reference: nearest_neighbors.py
    BruteForceKnn:170; kernel: brute_force_knn_integration.rs → ops/knn.py)."""

    def __init__(
        self,
        data_column,
        metadata_column=None,
        *,
        dimensions: int,
        reserved_space: int = 512,
        metric: BruteForceKnnMetricKind = BruteForceKnnMetricKind.COS,
        embedder=None,
        mesh=None,
    ):
        super().__init__(data_column, metadata_column)
        self.dimensions = dimensions
        self.reserved_space = reserved_space
        self.metric = metric
        self.embedder = embedder
        # mesh: shard the device index over the mesh's first axis
        # (sharded_knn_search); None = single-device buffer
        self.mesh = mesh

    def _make_impl(self) -> IndexImpl:
        encoder = _local_jax_encoder(self.embedder)
        if encoder is not None:
            return _FusedKnnIndexImpl(
                encoder, self.metric.value, self.reserved_space,
                mesh=self.mesh,
            )
        return _KnnIndexImpl(
            self.dimensions, self.metric.value, self.reserved_space,
            mesh=self.mesh,
        )

    def _query_preprocess(self, query_column):
        if self.embedder is not None and _local_jax_encoder(self.embedder) is None:
            return self.embedder(query_column)
        return query_column

    def _data_preprocess(self, data_column):
        if self.embedder is not None and _local_jax_encoder(self.embedder) is None:
            return self.embedder(data_column)
        return data_column


class _ApproxIndexImpl(IndexImpl):
    """IndexImpl over an approximate structure (LSH / IVF) with exact
    candidate rerank + metadata filtering."""

    def __init__(self, inner):
        self.inner = inner
        self.metadata: dict = {}

    def add(self, key, value, metadata) -> None:
        self.inner.add(key, np.asarray(value, dtype=np.float32))
        if metadata is not None:
            self.metadata[key] = metadata

    def remove(self, key) -> None:
        self.inner.remove(key)
        self.metadata.pop(key, None)

    def search(self, value, k, metadata_filter):
        return self.search_many([value], [k], [metadata_filter])[0]

    def search_many(self, values, ks, filters):
        if not values:
            return []
        if len(self.inner) == 0:
            return [[] for _ in values]
        k_max = max(int(k) for k in ks) if ks else 3
        fetch = k_max * 4 if any(f for f in filters) else k_max
        queries = np.stack([np.asarray(v, dtype=np.float32) for v in values])
        rows = self.inner.search_many(queries, fetch)
        out = []
        for row, k, filt in zip(rows, ks, filters):
            if filt:
                row = [
                    (key, s)
                    for key, s in row
                    if evaluate_filter(filt, self.metadata.get(key))
                ]
            out.append(row[: int(k)])
        return out


class USearchKnn(BruteForceKnn):
    """Approximate KNN in the reference's USearchKnn slot
    (nearest_neighbors.py USearchKnn:65, usearch_integration.rs:20).

    TPU-native departure: instead of an HNSW graph walk (which does not map
    onto the MXU), this is an IVF-flat index — k-means centroid probing
    (one [Q, C] matmul) + exact rerank of the probed lists. Parameter
    mapping: `expansion_search` bounds the probed-list count,
    `connectivity` the centroid budget."""

    def __init__(
        self,
        data_column,
        metadata_column=None,
        *,
        dimensions: int,
        reserved_space: int = 512,
        metric: USearchMetricKind = USearchMetricKind.COS,
        connectivity: int = 16,
        expansion_add: int = 128,
        expansion_search: int = 64,
        embedder=None,
    ):
        m = BruteForceKnnMetricKind(metric.value)
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=reserved_space,
            metric=m,
            embedder=embedder,
        )
        self.connectivity = connectivity
        self.expansion_add = expansion_add
        self.expansion_search = expansion_search

    def _make_impl(self) -> IndexImpl:
        from pathway_tpu.stdlib.indexing.approximate import IvfIndex

        return _ApproxIndexImpl(
            IvfIndex(
                self.dimensions,
                metric=self.metric.value,
                n_probes=max(1, self.expansion_search // 16),
                max_centroids=max(16, self.connectivity * 16),
                retrain_every=max(128, self.expansion_add * 8),
            )
        )

    def _query_preprocess(self, query_column):
        if self.embedder is not None:
            return self.embedder(query_column)
        return query_column

    _data_preprocess = _query_preprocess


class LshKnn(BruteForceKnn):
    """Locality-sensitive-hashing KNN (reference: nearest_neighbors.py
    LshKnn:262). n_or hash tables of n_and projections each; euclidean
    uses p-stable hashing with `bucket_length`, cosine sign-random
    projections. Candidates rerank exactly."""

    def __init__(
        self,
        data_column,
        metadata_column=None,
        *,
        dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        embedder=None,
        reserved_space: int = 512,
    ):
        metric = (
            BruteForceKnnMetricKind.COS
            if distance_type == "cosine"
            else BruteForceKnnMetricKind.L2SQ
        )
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=reserved_space,
            metric=metric,
            embedder=embedder,
        )
        self.n_or = n_or
        self.n_and = n_and
        self.bucket_length = bucket_length

    def _make_impl(self) -> IndexImpl:
        from pathway_tpu.stdlib.indexing.approximate import LshIndex

        return _ApproxIndexImpl(
            LshIndex(
                self.dimensions,
                metric=self.metric.value,
                n_or=self.n_or,
                n_and=self.n_and,
                bucket_length=self.bucket_length,
            )
        )

    def _query_preprocess(self, query_column):
        if self.embedder is not None:
            return self.embedder(query_column)
        return query_column

    _data_preprocess = _query_preprocess


class AbstractRetrieverFactory:
    """Base for index factories (reference: indexing/retrievers.py
    AbstractRetrieverFactory:7): subclasses provide build_inner_index;
    build_index wraps it in a DataIndex."""

    def build_inner_index(self, data_column, metadata_column=None):
        raise NotImplementedError

    def build_index(self, data_column, data_table, metadata_column=None):
        from pathway_tpu.stdlib.indexing.data_index import DataIndex

        return DataIndex(
            data_table, self.build_inner_index(data_column, metadata_column)
        )


@dataclass(kw_only=True)
class BruteForceKnnFactory(AbstractRetrieverFactory):
    """reference: nearest_neighbors.py BruteForceKnnFactory:407."""

    dimensions: int | None = None
    reserved_space: int = 512
    metric: BruteForceKnnMetricKind = BruteForceKnnMetricKind.COS
    embedder: Any = None
    mesh: Any = None

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        dimensions = self.dimensions
        if dimensions is None and self.embedder is not None:
            dimensions = self.embedder.get_embedding_dimension()
        return BruteForceKnn(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            embedder=self.embedder,
            mesh=self.mesh,
        )



@dataclass(kw_only=True)
class UsearchKnnFactory(AbstractRetrieverFactory):
    """reference: nearest_neighbors.py UsearchKnnFactory."""

    dimensions: int | None = None
    reserved_space: int = 512
    metric: USearchMetricKind = USearchMetricKind.COS
    connectivity: int = 16
    expansion_add: int = 128
    expansion_search: int = 64
    embedder: Any = None

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        dimensions = self.dimensions
        if dimensions is None and self.embedder is not None:
            dimensions = self.embedder.get_embedding_dimension()
        return USearchKnn(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            connectivity=self.connectivity,
            expansion_add=self.expansion_add,
            expansion_search=self.expansion_search,
            embedder=self.embedder,
        )



@dataclass(kw_only=True)
class LshKnnFactory(AbstractRetrieverFactory):
    dimensions: int | None = None
    n_or: int = 20
    n_and: int = 10
    bucket_length: float = 10.0
    distance_type: str = "euclidean"
    embedder: Any = None

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        return LshKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            n_or=self.n_or,
            n_and=self.n_and,
            bucket_length=self.bucket_length,
            distance_type=self.distance_type,
            embedder=self.embedder,
        )




@dataclass(kw_only=True)
class DefaultKnnFactory(BruteForceKnnFactory):
    """The default KNN factory — brute force on the device (reference:
    nearest_neighbors.py DefaultKnnFactory:574, which also defaults to
    BruteForceKnn)."""
