"""interval_join — join rows whose time difference falls in an interval
(reference: python/pathway/stdlib/temporal/_interval_join.py:577).

`left.t + lower <= right.t <= left.t + upper`, optionally with extra equality
conditions. Implemented as a dedicated engine node that buckets both sides by
the equality key and recomputes affected buckets per batch."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set

from pathway_tpu.engine.engine import Engine, Node
from pathway_tpu.engine.operators import _DiffCache, _freeze
from pathway_tpu.engine.value import Pointer, ref_scalar
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import desugar
from pathway_tpu.internals.expression import (
    BinaryOpExpression,
    MakeTupleExpression,
    collect_tables,
)
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import Table, _compile_on


@dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    if upper_bound < lower_bound:
        # reference: temporal/utils raises on an empty interval spec
        raise ValueError(
            f"interval(): lower_bound {lower_bound!r} exceeds "
            f"upper_bound {upper_bound!r}"
        )
    return Interval(lower_bound, upper_bound)


class IntervalJoinNode(Node):
    """Bucketed interval join with optional outer sides."""

    name = "interval_join"
    snapshot_attrs = ('left_index', 'right_index', 'cache')

    def __init__(
        self,
        engine: Engine,
        left: Node,
        right: Node,
        left_time_prog,
        right_time_prog,
        left_key_prog,
        right_key_prog,
        lower,
        upper,
        *,
        left_width: int,
        right_width: int,
        left_outer: bool,
        right_outer: bool,
    ):
        # multi-worker: co-locate rows by join key (empty key = one worker)
        from pathway_tpu.engine.exchange import exchange_by_value

        left = exchange_by_value(engine, left, left_key_prog)
        right = exchange_by_value(engine, right, right_key_prog)
        super().__init__(engine, [left, right])
        self.left_time_prog = left_time_prog
        self.right_time_prog = right_time_prog
        self.left_key_prog = left_key_prog
        self.right_key_prog = right_key_prog
        self.lower = lower
        self.upper = upper
        self.left_width = left_width
        self.right_width = right_width
        self.left_outer = left_outer
        self.right_outer = right_outer
        # bucket -> {key: (time, row)}
        self.left_index: Dict[Any, Dict] = {}
        self.right_index: Dict[Any, Dict] = {}
        self.cache = _DiffCache()

    def _apply(self, index, deltas, time_prog, key_prog, affected: Set):
        if not deltas:
            return
        keys = [d[0] for d in deltas]
        rows = ([d[1] for d in deltas],)
        tvs = time_prog(keys, rows)
        jvs = key_prog(keys, rows)
        for (key, values, diff), tv, jv in zip(deltas, tvs, jvs):
            jv = _freeze(jv)
            affected.add(jv)
            bucket = index.setdefault(jv, {})
            if diff > 0:
                bucket[key] = (tv, values)
            else:
                bucket.pop(key, None)
                if not bucket:
                    del index[jv]

    def process(self, time: int) -> None:
        left_deltas = self.take(0)
        right_deltas = self.take(1)
        if not left_deltas and not right_deltas:
            return
        affected: Set = set()
        self._apply(
            self.left_index, left_deltas, self.left_time_prog, self.left_key_prog, affected
        )
        self._apply(
            self.right_index,
            right_deltas,
            self.right_time_prog,
            self.right_key_prog,
            affected,
        )
        out = []
        l_nones = (None,) * self.left_width
        r_nones = (None,) * self.right_width
        for jv in affected:
            lefts = self.left_index.get(jv, {})
            rights = self.right_index.get(jv, {})
            new_rows: Dict[Pointer, tuple] = {}
            matched_left: Set = set()
            matched_right: Set = set()
            for lk, (lt, lrow) in lefts.items():
                for rk, (rt, rrow) in rights.items():
                    if lt + self.lower <= rt <= lt + self.upper:
                        matched_left.add(lk)
                        matched_right.add(rk)
                        new_rows[ref_scalar(lk, rk)] = (lk, rk, *lrow, *rrow)
            if self.left_outer:
                for lk, (lt, lrow) in lefts.items():
                    if lk not in matched_left:
                        new_rows[ref_scalar(lk, None)] = (lk, None, *lrow, *r_nones)
            if self.right_outer:
                for rk, (rt, rrow) in rights.items():
                    if rk not in matched_right:
                        new_rows[ref_scalar(None, rk)] = (None, rk, *l_nones, *rrow)
            self.cache.diff(jv, new_rows, out)
        self.emit(time, out)


class IntervalJoinResult(JoinResult):
    """JoinResult flavor whose engine node is an IntervalJoinNode."""

    def __init__(
        self,
        left: Table,
        right: Table,
        left_time_expr,
        right_time_expr,
        interval_: Interval,
        on: tuple,
        mode: JoinMode,
        remap=None,
    ):
        super().__init__(left, right, on, mode=mode, remap=remap)
        # each side's time expression resolves pw.this against ITS OWN
        # table (reference semantics)
        self._left_time = desugar(
            left_time_expr,
            {thisclass.left: left, thisclass.right: right,
             thisclass.this: left},
        )
        self._right_time = desugar(
            right_time_expr,
            {thisclass.left: left, thisclass.right: right,
             thisclass.this: right},
        )
        self._interval = interval_

    def _join_node(self, ctx):
        cached = ctx.join_nodes.get(id(self))
        if cached is not None:
            return cached
        left_node = ctx.node(self._left)
        right_node = ctx.node(self._right)
        node = IntervalJoinNode(
            ctx.engine,
            left_node,
            right_node,
            _compile_on(ctx, [self._left], self._left_time),
            _compile_on(ctx, [self._right], self._right_time),
            _compile_on(ctx, [self._left], MakeTupleExpression(*self._on_left)),
            _compile_on(ctx, [self._right], MakeTupleExpression(*self._on_right)),
            self._interval.lower_bound,
            self._interval.upper_bound,
            left_width=len(self._left.column_names()),
            right_width=len(self._right.column_names()),
            left_outer=self._mode in (JoinMode.LEFT, JoinMode.OUTER),
            right_outer=self._mode in (JoinMode.RIGHT, JoinMode.OUTER),
        )
        from pathway_tpu.engine.exchange import exchange_by_key

        node = exchange_by_key(ctx.engine, node)
        ctx.join_nodes[id(self)] = node
        return node


def interval_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    interval: Interval,
    *on,
    behavior=None,
    how: JoinMode = JoinMode.INNER,
) -> IntervalJoinResult:
    """Join rows whose time difference falls inside `interval` (reference:
    stdlib/temporal/_interval_join.py interval_join:577).

    >>> import pathway_tpu as pw
    >>> left = pw.debug.table_from_markdown('''
    ... t | a
    ... 1 | 1
    ... 5 | 2
    ... ''')
    >>> right = pw.debug.table_from_markdown('''
    ... t | b
    ... 2 | 10
    ... 9 | 20
    ... ''')
    >>> res = left.interval_join(
    ...     right, left.t, right.t, pw.temporal.interval(-2, 2)
    ... ).select(a=pw.left.a, b=pw.right.b)
    >>> pw.debug.compute_and_print(res, include_id=False)
    a | b
    1 | 10
    """
    from pathway_tpu.internals.parse_graph import record_marker

    record_marker("interval_join", has_behavior=behavior is not None)
    if isinstance(how, str):
        how = JoinMode[how.upper()]
    remap = None
    if behavior is not None:
        # behaviors gate the join's INPUT sides (reference: interval
        # joins apply cutoff/forgetting on each side's time column).
        # User expressions keep referencing the ORIGINAL tables; the
        # JoinResult remap machinery rebinds them onto the gated copies.
        from pathway_tpu.stdlib.temporal._window import (
            _apply_behavior_on_time,
            _remap_by_name,
        )

        lt = desugar(
            self_time,
            {thisclass.left: self, thisclass.right: other,
             thisclass.this: self},
        )
        rt = desugar(
            other_time,
            {thisclass.left: self, thisclass.right: other,
             thisclass.this: other},
        )
        new_left = _apply_behavior_on_time(self, lt, behavior)
        new_right = _apply_behavior_on_time(other, rt, behavior)
        # right entries first: on a SELF-join (self is other) the left
        # side wins the collision, matching the no-behavior resolver's
        # left-first precedence
        remap = {}
        for c in other.column_names():
            remap[(id(other), c)] = new_right[c]
        for c in self.column_names():
            remap[(id(self), c)] = new_left[c]
        self_time = _remap_by_name(lt, new_left)
        other_time = _remap_by_name(rt, new_right)
        self, other = new_left, new_right
    return IntervalJoinResult(
        self, other, self_time, other_time, interval, on, how,
        remap=remap,
    )


def interval_join_inner(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(
        self, other, self_time, other_time, interval, *on,
        how=JoinMode.INNER, **kw,
    )


def interval_join_left(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(
        self, other, self_time, other_time, interval, *on,
        how=JoinMode.LEFT, **kw,
    )


def interval_join_right(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(
        self, other, self_time, other_time, interval, *on,
        how=JoinMode.RIGHT, **kw,
    )


def interval_join_outer(self, other, self_time, other_time, interval, *on, **kw):
    return interval_join(
        self, other, self_time, other_time, interval, *on,
        how=JoinMode.OUTER, **kw,
    )
