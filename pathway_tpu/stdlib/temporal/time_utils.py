"""Time utilities (reference: python/pathway/stdlib/temporal/time_utils.py:
utc_now:37, inactivity_detection:64)."""

from __future__ import annotations

import datetime
import time as time_mod

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class _NowSubject(ConnectorSubjectBase):
    def __init__(self, refresh_rate: datetime.timedelta):
        super().__init__()
        self.refresh_rate = refresh_rate.total_seconds()

    def run(self) -> None:
        last_key = None
        while True:
            now = datetime.datetime.now(tz=datetime.timezone.utc)
            if last_key is not None:
                self._remove({"timestamp_utc": last_key})
            self.next(timestamp_utc=now)
            last_key = now
            self.commit()
            time_mod.sleep(self.refresh_rate)


def utc_now(refresh_rate: datetime.timedelta | None = None):
    """A 1-row table holding the current UTC time, refreshed periodically
    (reference: time_utils.py utc_now:37)."""
    refresh_rate = refresh_rate or datetime.timedelta(seconds=60)
    schema = schema_from_columns(
        {
            "timestamp_utc": ColumnSchema(
                name="timestamp_utc", dtype=dt.DATE_TIME_UTC
            )
        },
        name="UtcNowSchema",
    )
    return connector_table(
        schema, lambda: _NowSubject(refresh_rate), mode="streaming"
    )


def inactivity_detection(
    event_time_column,
    allowed_inactivity_period: datetime.timedelta,
    refresh_rate: datetime.timedelta | None = None,
    instance=None,
):
    """Detect inactivity periods: emits (inactive since, resumed at) alerts
    (reference: time_utils.py inactivity_detection:64)."""
    from pathway_tpu.internals import reducers as red
    from pathway_tpu.internals.expression import collect_tables

    tables = list(collect_tables(event_time_column, set()))
    if len(tables) != 1:
        raise ValueError("event_time_column must reference one table")
    table = tables[0]
    latest = table.reduce(latest_t=red.max_(event_time_column))
    now_t = utc_now(refresh_rate=refresh_rate)
    # inactivity: now - latest_t > allowed period
    joined = latest.join(now_t).select(
        latest_t=latest.latest_t,
        now=now_t.timestamp_utc,
    )
    alerts = joined.filter(
        joined.now - joined.latest_t > allowed_inactivity_period
    ).select(inactive_since=joined.latest_t)
    resumed = joined.filter(
        joined.now - joined.latest_t <= allowed_inactivity_period
    ).select(resumed_at=joined.latest_t)
    return alerts, resumed
