"""window_join — join rows sharing a window (reference:
python/pathway/stdlib/temporal/_window_join.py). Composed from window
assignment + the regular equi-join."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import desugar
from pathway_tpu.internals.expression import ApplyExpression
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.temporal._window import (
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
    Window,
)


def _with_windows(table: Table, time_expr, window: Window, prefix: str) -> Table:
    from pathway_tpu.stdlib.temporal._window import _check_time_window_types

    mapping = {thisclass.this: table}
    time_e = desugar(time_expr, mapping)
    _check_time_window_types(table, time_e, window)
    if not isinstance(window, (TumblingWindow, SlidingWindow)):
        raise TypeError("window_join supports tumbling/sliding windows")
    assign = window.assign
    assign_expr = ApplyExpression(
        lambda t: assign(t), dt.ANY_TUPLE, time_e, deterministic=True
    )
    with_w = table.with_columns(**{f"{prefix}window": assign_expr})
    flat = with_w.flatten(with_w[f"{prefix}window"])
    return flat


class WindowJoinResult:
    def __init__(
        self,
        left_flat: Table,
        right_flat: Table,
        join_result: JoinResult,
        left_orig: Table,
        right_orig: Table,
    ):
        self._jr = join_result
        self._left_flat = left_flat
        self._right_flat = right_flat
        self._left_orig = left_orig
        self._right_orig = right_orig

    def select(self, *args, **kwargs) -> Table:
        # user expressions reference the ORIGINAL tables (reference API);
        # remap them onto the window-flattened copies the join runs over
        remap = lambda e: _remap_sides(  # noqa: E731
            e, self._left_orig, self._right_orig,
            self._left_flat, self._right_flat,
        )
        args = tuple(remap(a) for a in args)
        kwargs = {k: remap(v) for k, v in kwargs.items()}
        return self._jr.select(*args, **kwargs)


def window_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    window: Window,
    *on,
    how: JoinMode = JoinMode.INNER,
) -> WindowJoinResult:
    """Join rows that fall into the same time window (reference:
    stdlib/temporal/_window_join.py window_join:26).

    >>> import pathway_tpu as pw
    >>> left = pw.debug.table_from_markdown('''
    ... t | a
    ... 1 | 1
    ... ''')
    >>> right = pw.debug.table_from_markdown('''
    ... t | b
    ... 2 | 10
    ... ''')
    >>> res = left.window_join(
    ...     right, left.t, right.t, pw.temporal.tumbling(duration=5)
    ... ).select(a=pw.left.a, b=pw.right.b)
    >>> pw.debug.compute_and_print(res, include_id=False)
    a | b
    1 | 10
    """
    from pathway_tpu.internals.parse_graph import record_marker

    # window_join has no behavior= knob at all, so the marker exists for
    # graph inventory, not for the missing-behavior lint (PWT201 skips it
    # — there would be no way to satisfy the lint).
    record_marker(
        "window_join", has_behavior=False, window=type(window).__name__
    )
    if isinstance(how, str):
        how = JoinMode[how.upper()]
    if isinstance(window, SessionWindow):
        left_flat, right_flat = _session_sides(
            self, other, self_time, other_time, window, on
        )
    else:
        left_flat = _with_windows(self, self_time, window, "_pw_l")
        right_flat = _with_windows(other, other_time, window, "_pw_r")
    conds = [left_flat["_pw_lwindow"] == right_flat["_pw_rwindow"]]
    for cond in on:
        conds.append(_remap_sides(cond, self, other, left_flat, right_flat))
    jr = JoinResult(left_flat, right_flat, tuple(conds), mode=how)
    return WindowJoinResult(left_flat, right_flat, jr, self, other)


def _session_sides(left, right, left_time, right_time, window, on):
    """Session windows for a join are computed over the UNION of both
    sides' times (per join-key instance): rows whose session ids match
    then pair in the ordinary equi-join (reference:
    stdlib/temporal/_window_join.py session handling)."""
    from pathway_tpu.internals.expression import MakeTupleExpression
    from pathway_tpu.internals.joins import split_equality_condition
    from pathway_tpu.internals.reducers import reducers
    from pathway_tpu.stdlib.temporal._window import windowby

    lt_e = desugar(left_time, {thisclass.this: left})
    rt_e = desugar(right_time, {thisclass.this: right})
    lons, rons = [], []
    for cond in on:
        c = desugar(
            cond,
            {
                thisclass.left: left,
                thisclass.right: right,
                thisclass.this: left,
            },
        )
        a, b = split_equality_condition(c, left, right)
        lons.append(a)
        rons.append(b)

    def union_side(tab, t_e, key_exprs):
        cols = {"_pw_t": t_e}
        if key_exprs:
            cols["_pw_i"] = MakeTupleExpression(*key_exprs)
        return tab.select(**cols)

    union = union_side(left, lt_e, lons).concat_reindex(
        union_side(right, rt_e, rons)
    )
    win = windowby(
        union,
        union._pw_t,
        window=window,
        instance=union._pw_i if lons else None,
    )
    sess = win._flat  # one row per union row, with session start/end
    gb = [sess._pw_t] + ([sess._pw_instance] if lons else [])
    key_map = sess.groupby(*gb).reduce(
        *gb,
        _pw_s=reducers.any(sess._pw_window_start),
        _pw_e=reducers.any(sess._pw_window_end),
    )

    def flat_side(tab, t_e, key_exprs, prefix):
        conds = [t_e == key_map._pw_t]
        if key_exprs:
            conds.append(MakeTupleExpression(*key_exprs) == key_map._pw_instance)
        return tab.join(key_map, *conds).select(
            *[tab[c] for c in tab.column_names()],
            **{
                f"{prefix}window": MakeTupleExpression(
                    key_map._pw_s, key_map._pw_e
                )
            },
        )

    return (
        flat_side(left, lt_e, lons, "_pw_l"),
        flat_side(right, rt_e, rons, "_pw_r"),
    )


def _remap_sides(cond, left, right, left_flat, right_flat):
    import copy

    from pathway_tpu.internals.expression import (
        ColumnExpression,
        ColumnReference,
        IdReference,
        ThisColumnReference,
    )

    def rec(e):
        if isinstance(e, ThisColumnReference):
            if e._this is thisclass.left:
                return left_flat[e._name]
            if e._this is thisclass.right:
                return right_flat[e._name]
            raise ValueError("window_join conditions use pw.left/pw.right")
        if isinstance(e, IdReference):
            return e
        if isinstance(e, ColumnReference):
            if e._table is left:
                return left_flat[e.name]
            if e._table is right:
                return right_flat[e.name]
            return e
        out = copy.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, ColumnExpression):
                setattr(out, attr, rec(value))
            elif isinstance(value, tuple) and any(
                isinstance(v, ColumnExpression) for v in value
            ):
                setattr(
                    out,
                    attr,
                    tuple(
                        rec(v) if isinstance(v, ColumnExpression) else v
                        for v in value
                    ),
                )
        return out

    return rec(cond)


def window_join_inner(self, other, self_time, other_time, window, *on, **kw):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.INNER)


def window_join_left(self, other, self_time, other_time, window, *on, **kw):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.LEFT)


def window_join_right(self, other, self_time, other_time, window, *on, **kw):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.RIGHT)


def window_join_outer(self, other, self_time, other_time, window, *on, **kw):
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.OUTER)
