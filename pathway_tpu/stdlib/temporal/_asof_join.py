"""asof_join — match each left row with the nearest right row in time
(reference: python/pathway/stdlib/temporal/_asof_join.py:479)."""

from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional, Set

from pathway_tpu.engine.engine import Engine, Node
from pathway_tpu.engine.operators import _DiffCache, _freeze
from pathway_tpu.engine.value import Pointer, ref_scalar
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import desugar
from pathway_tpu.internals.expression import MakeTupleExpression
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import Table, _compile_on


class Direction(enum.Enum):
    BACKWARD = "backward"  # right.t <= left.t (latest such)
    FORWARD = "forward"  # right.t >= left.t (earliest such)
    NEAREST = "nearest"


class AsofJoinNode(Node):
    name = "asof_join"
    snapshot_attrs = ('left_index', 'right_index', 'cache')

    def __init__(
        self,
        engine: Engine,
        left: Node,
        right: Node,
        left_time_prog,
        right_time_prog,
        left_key_prog,
        right_key_prog,
        direction: Direction,
        *,
        left_width: int,
        right_width: int,
        left_outer: bool,
        right_outer: bool,
        defaults: Dict[int, Any] | None = None,
    ):
        # multi-worker: co-locate rows by join key (empty key = one worker)
        from pathway_tpu.engine.exchange import exchange_by_value

        left = exchange_by_value(engine, left, left_key_prog)
        right = exchange_by_value(engine, right, right_key_prog)
        super().__init__(engine, [left, right])
        self.left_time_prog = left_time_prog
        self.right_time_prog = right_time_prog
        self.left_key_prog = left_key_prog
        self.right_key_prog = right_key_prog
        self.direction = direction
        self.left_width = left_width
        self.right_width = right_width
        self.left_outer = left_outer
        self.right_outer = right_outer
        self.left_index: Dict[Any, Dict] = {}
        self.right_index: Dict[Any, Dict] = {}
        self.cache = _DiffCache()

    def _apply(self, index, deltas, time_prog, key_prog, affected: Set):
        if not deltas:
            return
        keys = [d[0] for d in deltas]
        rows = ([d[1] for d in deltas],)
        tvs = time_prog(keys, rows)
        jvs = key_prog(keys, rows)
        for (key, values, diff), tv, jv in zip(deltas, tvs, jvs):
            jv = _freeze(jv)
            affected.add(jv)
            bucket = index.setdefault(jv, {})
            if diff > 0:
                bucket[key] = (tv, values)
            else:
                bucket.pop(key, None)
                if not bucket:
                    del index[jv]

    def _match(self, lt, rights_sorted):
        """rights_sorted: list of (time, key, row) ascending."""
        import bisect

        times = [r[0] for r in rights_sorted]
        if self.direction == Direction.BACKWARD:
            i = bisect.bisect_right(times, lt) - 1
            return rights_sorted[i] if i >= 0 else None
        if self.direction == Direction.FORWARD:
            i = bisect.bisect_left(times, lt)
            return rights_sorted[i] if i < len(rights_sorted) else None
        # NEAREST
        i = bisect.bisect_left(times, lt)
        candidates = []
        if i > 0:
            candidates.append(rights_sorted[i - 1])
        if i < len(rights_sorted):
            candidates.append(rights_sorted[i])
        if not candidates:
            return None
        return min(candidates, key=lambda r: abs(r[0] - lt))

    def process(self, time: int) -> None:
        left_deltas = self.take(0)
        right_deltas = self.take(1)
        if not left_deltas and not right_deltas:
            return
        affected: Set = set()
        self._apply(
            self.left_index, left_deltas, self.left_time_prog, self.left_key_prog, affected
        )
        self._apply(
            self.right_index,
            right_deltas,
            self.right_time_prog,
            self.right_key_prog,
            affected,
        )
        out = []
        l_nones = (None,) * self.left_width
        r_nones = (None,) * self.right_width
        for jv in affected:
            lefts = self.left_index.get(jv, {})
            rights = self.right_index.get(jv, {})
            rights_sorted = sorted(
                ((tv, k, row) for k, (tv, row) in rights.items()),
                key=lambda r: (r[0], r[1]),
            )
            new_rows: Dict[Pointer, tuple] = {}
            matched_right: Set = set()
            for lk, (lt, lrow) in lefts.items():
                m = self._match(lt, rights_sorted)
                if m is not None:
                    _rt, rk, rrow = m
                    matched_right.add(rk)
                    new_rows[ref_scalar(lk, rk)] = (lk, rk, *lrow, *rrow)
                elif self.left_outer:
                    new_rows[ref_scalar(lk, None)] = (lk, None, *lrow, *r_nones)
            if self.right_outer:
                for _tv, rk, rrow in rights_sorted:
                    if rk not in matched_right:
                        new_rows[ref_scalar(None, rk)] = (None, rk, *l_nones, *rrow)
            self.cache.diff(jv, new_rows, out)
        self.emit(time, out)


class AsofJoinResult(JoinResult):
    def __init__(
        self,
        left: Table,
        right: Table,
        left_time_expr,
        right_time_expr,
        on: tuple,
        mode: JoinMode,
        direction: Direction,
        defaults: dict | None = None,
    ):
        super().__init__(left, right, on, mode=mode)
        mapping = {thisclass.left: left, thisclass.right: right, thisclass.this: left}
        self._left_time = desugar(left_time_expr, mapping)
        self._right_time = desugar(right_time_expr, mapping)
        self._direction = direction

    def _join_node(self, ctx):
        cached = ctx.join_nodes.get(id(self))
        if cached is not None:
            return cached
        node = AsofJoinNode(
            ctx.engine,
            ctx.node(self._left),
            ctx.node(self._right),
            _compile_on(ctx, [self._left], self._left_time),
            _compile_on(ctx, [self._right], self._right_time),
            _compile_on(ctx, [self._left], MakeTupleExpression(*self._on_left)),
            _compile_on(ctx, [self._right], MakeTupleExpression(*self._on_right)),
            self._direction,
            left_width=len(self._left.column_names()),
            right_width=len(self._right.column_names()),
            left_outer=self._mode in (JoinMode.LEFT, JoinMode.OUTER),
            right_outer=self._mode in (JoinMode.RIGHT, JoinMode.OUTER),
        )
        from pathway_tpu.engine.exchange import exchange_by_key

        node = exchange_by_key(ctx.engine, node)
        ctx.join_nodes[id(self)] = node
        return node


def asof_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    *on,
    how: JoinMode = JoinMode.INNER,
    defaults: dict | None = None,
    direction: Direction = Direction.BACKWARD,
    behavior=None,
) -> AsofJoinResult:
    """Join each left row with the latest right row at or before its time
    (reference: stdlib/temporal/_asof_join.py asof_join:479).

    >>> import pathway_tpu as pw
    >>> trades = pw.debug.table_from_markdown('''
    ... t | qty
    ... 3 | 1
    ... ''')
    >>> quotes = pw.debug.table_from_markdown('''
    ... t | price
    ... 1 | 10
    ... 5 | 20
    ... ''')
    >>> res = trades.asof_join(
    ...     quotes, trades.t, quotes.t
    ... ).select(qty=pw.left.qty, price=pw.right.price)
    >>> pw.debug.compute_and_print(res, include_id=False)
    qty | price
    1   | 10
    """
    from pathway_tpu.internals.parse_graph import record_marker

    record_marker("asof_join", has_behavior=behavior is not None)
    if isinstance(how, str):
        how = JoinMode[how.upper()]
    if isinstance(direction, str):
        direction = Direction[direction.upper()]
    return AsofJoinResult(
        self, other, self_time, other_time, on, how, direction, defaults
    )


def asof_join_inner(self, other, self_time, other_time, *on, **kw):
    kw.pop("how", None)
    return asof_join(self, other, self_time, other_time, *on, how=JoinMode.INNER, **kw)


def asof_join_left(self, other, self_time, other_time, *on, **kw):
    kw.pop("how", None)
    return asof_join(self, other, self_time, other_time, *on, how=JoinMode.LEFT, **kw)


def asof_join_right(self, other, self_time, other_time, *on, **kw):
    kw.pop("how", None)
    return asof_join(self, other, self_time, other_time, *on, how=JoinMode.RIGHT, **kw)


def asof_join_outer(self, other, self_time, other_time, *on, **kw):
    kw.pop("how", None)
    return asof_join(self, other, self_time, other_time, *on, how=JoinMode.OUTER, **kw)
