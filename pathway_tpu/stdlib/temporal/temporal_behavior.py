"""Temporal behaviors: late-data cutoff, delays, exactly-once emission
(reference: python/pathway/stdlib/temporal/temporal_behavior.py:29,83).

In batch mode behaviors are no-ops (all data shares one time); in streaming
they wire the engine's buffer/forget/freeze operators (reference:
src/engine/dataflow/operators/time_column.rs).

>>> import pathway_tpu as pw
>>> b = pw.temporal.common_behavior(delay=2, cutoff=10)
>>> type(b).__name__
'CommonBehavior'
>>> e = pw.temporal.exactly_once_behavior()
>>> type(e).__name__
'ExactlyOnceBehavior'
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


class Behavior:
    pass


@dataclass
class CommonBehavior(Behavior):
    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


def common_behavior(delay=None, cutoff=None, keep_results: bool = True) -> CommonBehavior:
    return CommonBehavior(delay=delay, cutoff=cutoff, keep_results=keep_results)


@dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Any = None


def exactly_once_behavior(shift=None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift=shift)
