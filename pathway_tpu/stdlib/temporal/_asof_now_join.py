"""asof_now_join — join left rows against the right side's state *at arrival
time*; results are never retro-updated when the right side changes later
(reference: python/pathway/stdlib/temporal/_asof_now_join.py:176). This is
the join that serves index queries (DataIndex.query_as_of_now)."""

from __future__ import annotations

from typing import Any, Dict, List, Set

from pathway_tpu.engine.engine import Engine, Node
from pathway_tpu.engine.operators import _freeze
from pathway_tpu.engine.value import Pointer, ref_scalar
from pathway_tpu.internals.expression import MakeTupleExpression
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.table import Table, _compile_on


class AsofNowJoinNode(Node):
    """Left deltas join the right index as-of the current batch; right deltas
    only update the index (matching the reference's asof-now contract,
    external_index.rs batch-by-time)."""

    name = "asof_now_join"
    snapshot_attrs = ('right_index',)

    def __init__(
        self,
        engine: Engine,
        left: Node,
        right: Node,
        left_key_prog,
        right_key_prog,
        *,
        left_width: int,
        right_width: int,
        left_outer: bool,
        id_mode: str = "left",
    ):
        # multi-worker: co-locate queries with the index shard they probe
        from pathway_tpu.engine.exchange import exchange_by_value

        left = exchange_by_value(engine, left, left_key_prog)
        right = exchange_by_value(engine, right, right_key_prog)
        super().__init__(engine, [left, right])
        self.left_key_prog = left_key_prog
        self.right_key_prog = right_key_prog
        self.left_width = left_width
        self.right_width = right_width
        self.left_outer = left_outer
        self.id_mode = id_mode
        self.right_index: Dict[Any, Dict] = {}

    def process(self, time: int) -> None:
        left_deltas = self.take(0)
        right_deltas = self.take(1)
        # update the index first: queries at time t see index state at t
        if right_deltas:
            keys = [d[0] for d in right_deltas]
            rows = ([d[1] for d in right_deltas],)
            jvs = self.right_key_prog(keys, rows)
            for (key, values, diff), jv in zip(right_deltas, jvs):
                jv = _freeze(jv)
                bucket = self.right_index.setdefault(jv, {})
                if diff > 0:
                    bucket[key] = values
                else:
                    bucket.pop(key, None)
        if not left_deltas:
            return
        out = []
        r_nones = (None,) * self.right_width
        keys = [d[0] for d in left_deltas]
        rows = ([d[1] for d in left_deltas],)
        jvs = self.left_key_prog(keys, rows)
        for (lk, lrow, diff), jv in zip(left_deltas, jvs):
            jv = _freeze(jv)
            rights = self.right_index.get(jv, {})
            matched = False
            for rk, rrow in rights.items():
                matched = True
                out_key = lk if self.id_mode == "left" else ref_scalar(lk, rk)
                out.append((out_key, (lk, rk, *lrow, *rrow), diff))
            if not matched and self.left_outer:
                out_key = lk if self.id_mode == "left" else ref_scalar(lk, None)
                out.append((out_key, (lk, None, *lrow, *r_nones), diff))
        self.emit(time, out)


class AsofNowJoinResult(JoinResult):
    def __init__(self, left, right, on, mode: JoinMode, id_expr=None):
        super().__init__(left, right, on, mode=mode, id_expr=id_expr)
        if self._id_mode == "both":
            # asof_now results default to left-row keying when unique
            self._id_mode_effective = "both"
        else:
            self._id_mode_effective = self._id_mode

    def _join_node(self, ctx):
        cached = ctx.join_nodes.get(id(self))
        if cached is not None:
            return cached
        node = AsofNowJoinNode(
            ctx.engine,
            ctx.node(self._left),
            ctx.node(self._right),
            _compile_on(ctx, [self._left], MakeTupleExpression(*self._on_left)),
            _compile_on(ctx, [self._right], MakeTupleExpression(*self._on_right)),
            left_width=len(self._left.column_names()),
            right_width=len(self._right.column_names()),
            left_outer=self._mode in (JoinMode.LEFT, JoinMode.OUTER),
            id_mode="left" if self._id_mode_effective == "left" else "both",
        )
        from pathway_tpu.engine.exchange import exchange_by_key

        node = exchange_by_key(ctx.engine, node)
        ctx.join_nodes[id(self)] = node
        return node


def asof_now_join(
    self: Table,
    other: Table,
    *on,
    how: JoinMode = JoinMode.INNER,
    id=None,
    **kwargs,
) -> AsofNowJoinResult:
    if isinstance(how, str):
        how = JoinMode[how.upper()]
    return AsofNowJoinResult(self, other, on, how, id_expr=id)


def asof_now_join_inner(self, other, *on, **kw):
    kw.pop("how", None)
    return asof_now_join(self, other, *on, how=JoinMode.INNER, **kw)


def asof_now_join_left(self, other, *on, **kw):
    kw.pop("how", None)
    return asof_now_join(self, other, *on, how=JoinMode.LEFT, **kw)
