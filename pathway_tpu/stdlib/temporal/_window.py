"""Windows: tumbling / sliding / session / intervals_over (reference:
python/pathway/stdlib/temporal/_window.py:590-857).

Window assignment is columnar: each row gets its covering windows, is
flattened, and grouped by (instance, window_start, window_end) — the same
mechanics as the reference (`_window.py:256-380`). Session windows are
computed by a dedicated engine node that re-chains affected instances per
batch (replacing the reference's sort + pointer-jumping-in-iterate,
`_window.py:65-140`, with a recompute-style operator)."""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import desugar
from pathway_tpu.internals.expression import ApplyExpression, ColumnExpression
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.internals.table import Table, _compile_on
from pathway_tpu.internals.universe import Universe


class Window:
    pass


@dataclass
class TumblingWindow(Window):
    duration: Any
    origin: Any = None

    def assign(self, t):
        origin = self.origin if self.origin is not None else _zero_like(t)
        d = self.duration
        n = (t - origin) // d
        start = origin + n * d
        return ((start, start + d),)


@dataclass
class SlidingWindow(Window):
    hop: Any
    duration: Any = None
    ratio: int | None = None
    origin: Any = None

    def assign(self, t):
        d = self.duration if self.duration is not None else self.hop * self.ratio
        h = self.hop
        origin = self.origin if self.origin is not None else _zero_like(t)
        # all starts s = origin + k*h with s <= t < s + d
        k_max = (t - origin) // h
        out = []
        k = k_max
        while True:
            start = origin + k * h
            if start + d <= t:
                break
            out.append((start, start + d))
            k -= 1
        out.reverse()
        return tuple(out)


@dataclass
class SessionWindow(Window):
    predicate: Callable | None = None
    max_gap: Any = None


@dataclass
class IntervalsOverWindow(Window):
    at: ColumnExpression
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True


def _zero_like(t):
    if isinstance(t, datetime.datetime):
        if t.tzinfo is not None:
            return datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
        return datetime.datetime(1970, 1, 1)
    if isinstance(t, float):
        return 0.0
    return 0


def tumbling(duration, origin=None) -> TumblingWindow:
    return TumblingWindow(duration=duration, origin=origin)


def sliding(hop, duration=None, ratio: int | None = None, origin=None) -> SlidingWindow:
    return SlidingWindow(hop=hop, duration=duration, ratio=ratio, origin=origin)


def session(*, predicate: Callable | None = None, max_gap=None) -> SessionWindow:
    if (predicate is None) == (max_gap is None):
        raise ValueError("session() requires exactly one of predicate / max_gap")
    return SessionWindow(predicate=predicate, max_gap=max_gap)


def intervals_over(
    *, at, lower_bound, upper_bound, is_outer: bool = True
) -> IntervalsOverWindow:
    return IntervalsOverWindow(
        at=at, lower_bound=lower_bound, upper_bound=upper_bound, is_outer=is_outer
    )


class WindowedTable:
    """Result of windowby, supporting reduce (reference: _window.py
    WindowedTable)."""

    def __init__(self, flat: Table, grouping_names: List[str], source: Table):
        self._flat = flat
        self._grouping_names = grouping_names
        self._source = source

    def reduce(self, *args, **kwargs) -> Table:
        flat = self._flat
        mapping = {thisclass.this: flat}
        new_args = [desugar(a, mapping) for a in args]
        new_kwargs = {k: desugar(v, mapping) for k, v in kwargs.items()}
        grouped = flat.groupby(*(flat[g] for g in self._grouping_names))
        return grouped.reduce(*new_args, **new_kwargs)


def _check_time_window_types(table: Table, time_e, window) -> None:
    """Numeric time columns need numeric durations; datetime columns need
    timedeltas (reference: temporal/utils.py check_joint_types — mismatch
    is a BUILD-time TypeError, not silent Error rows)."""
    import datetime as _dt_mod

    try:
        time_dtype = table.eval_type(time_e)
    except Exception:  # noqa: BLE001 — untyped expressions skip the gate
        return
    durations = [
        getattr(window, attr, None)
        for attr in ("duration", "hop", "max_gap", "lower_bound", "upper_bound")
    ]
    durations = [d for d in durations if d is not None and not callable(d)]
    core = dt.unoptionalize(time_dtype)
    for d in durations:
        is_delta = isinstance(d, _dt_mod.timedelta)
        if core in (dt.INT, dt.FLOAT) and is_delta:
            raise TypeError(
                f"window duration {d!r} is a timedelta but the time "
                f"column is {core}; use a number"
            )
        if core in (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC) and not is_delta:
            raise TypeError(
                f"window duration {d!r} is a number but the time column "
                f"is {core}; use a datetime.timedelta"
            )


def _remap_by_name(expr, target: Table):
    """Rebind column references onto `target` by column name (columns
    survive flatten/with_columns under their names)."""
    import copy as copy_mod

    from pathway_tpu.internals.expression import (
        ColumnExpression,
        ColumnReference,
        IdReference,
    )

    def rec(e):
        if isinstance(e, IdReference):
            return IdReference(target)
        if isinstance(e, ColumnReference):
            if e.name in target.column_names():
                return target[e.name]
            return e
        out = copy_mod.copy(e)
        for attr, value in list(vars(e).items()):
            if isinstance(value, ColumnExpression):
                setattr(out, attr, rec(value))
            elif isinstance(value, tuple) and any(
                isinstance(v, ColumnExpression) for v in value
            ):
                setattr(
                    out,
                    attr,
                    tuple(
                        rec(v) if isinstance(v, ColumnExpression) else v
                        for v in value
                    ),
                )
        return out

    return rec(expr)


def _wrap_temporal(table: Table, node_cls, threshold_expr, time_expr, **kw) -> Table:
    from pathway_tpu.internals.table import _compile_on

    def build(ctx):
        node = ctx.node(table)
        return node_cls(
            ctx.engine,
            node,
            _compile_on(ctx, [table], threshold_expr),
            _compile_on(ctx, [table], time_expr),
            **kw,
        )

    return Table(schema=table._schema, universe=Universe(), build=build)


def _behavior_plan(behavior, start_of, end_of):
    """[(node_cls, threshold_of)] for a behavior. `start_of`/`end_of`
    map the current table to the buffer/cutoff anchor expressions —
    window bounds for windowby, the raw time column for join inputs —
    so BOTH appliers share one branch structure (reference:
    temporal_behavior.py; engine ops time_column.rs)."""
    from pathway_tpu.engine.temporal_nodes import (
        BufferNode,
        ForgetNode,
        FreezeNode,
    )
    from pathway_tpu.stdlib.temporal.temporal_behavior import (
        CommonBehavior,
        ExactlyOnceBehavior,
    )

    plan = []
    if isinstance(behavior, ExactlyOnceBehavior):
        shift = behavior.shift

        def threshold(t):
            end = end_of(t)
            return end + shift if shift else end

        plan.append((FreezeNode, threshold))
        plan.append((BufferNode, threshold))
    elif isinstance(behavior, CommonBehavior):
        if behavior.delay is not None:
            plan.append(
                (BufferNode, lambda t: start_of(t) + behavior.delay)
            )
        if behavior.cutoff is not None:
            plan.append(
                (FreezeNode, lambda t: end_of(t) + behavior.cutoff)
            )
            if not behavior.keep_results:
                plan.append(
                    (ForgetNode, lambda t: end_of(t) + behavior.cutoff)
                )
    return plan


def _apply_plan(table: Table, time_expr, plan) -> Table:
    out = table
    for node_cls, threshold_of in plan:
        # expressions must rebind onto the current (possibly already
        # wrapped) table — columns keep their names through the chain
        out = _wrap_temporal(
            out,
            node_cls,
            threshold_of(out),
            _remap_by_name(time_expr, out),
        )
    return out


def _apply_behavior(flat2: Table, time_on_flat, behavior) -> Table:
    """Wrap the flattened window-assignment table with buffer/freeze/forget
    per the behavior, anchored on the window bounds columns."""
    plan = _behavior_plan(
        behavior,
        start_of=lambda t: t["_pw_window_start"],
        end_of=lambda t: t["_pw_window_end"],
    )
    return _apply_plan(flat2, time_on_flat, plan)


def _apply_behavior_on_time(table: Table, time_expr, behavior) -> Table:
    """Behavior gating keyed on a plain TIME column (interval/asof join
    inputs): delay buffers rows until time+delay, cutoff freezes/forgets
    rows behind time+cutoff. Same plan as _apply_behavior with the time
    column as both anchor bounds."""
    anchor = lambda t: _remap_by_name(time_expr, t)  # noqa: E731
    plan = _behavior_plan(behavior, start_of=anchor, end_of=anchor)
    return _apply_plan(table, time_expr, plan)


def windowby(
    table: Table,
    time_expr,
    *,
    window: Window,
    instance=None,
    behavior=None,
    shard=None,
) -> WindowedTable:
    """Assign windows and group (reference: stdlib/temporal/_window.py
    windowby:590).

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... t  | v
    ... 1  | 10
    ... 7  | 20
    ... 13 | 5
    ... ''')
    >>> win = pw.temporal.windowby(
    ...     t, t.t, window=pw.temporal.tumbling(duration=10)
    ... ).reduce(
    ...     start=pw.this._pw_window_start,
    ...     total=pw.reducers.sum(pw.this.v),
    ... )
    >>> pw.debug.compute_and_print(win, include_id=False)
    start | total
    10    | 5
    0     | 30
    """
    from pathway_tpu.internals.parse_graph import record_marker

    record_marker(
        "windowby",
        has_behavior=behavior is not None,
        window=type(window).__name__,
    )
    if instance is None and shard is not None:
        instance = shard
    mapping = {thisclass.this: table}
    time_e = desugar(time_expr, mapping)
    instance_e = desugar(instance, mapping) if instance is not None else None
    _check_time_window_types(table, time_e, window)

    if isinstance(window, (TumblingWindow, SlidingWindow)):
        assign = window.assign
        assign_expr = ApplyExpression(
            lambda t: assign(t), dt.ANY_TUPLE, time_e, deterministic=True
        )
        with_windows = table.with_columns(_pw_window=assign_expr)
        flat = with_windows.flatten(with_windows._pw_window)
        cols = {
            "_pw_window_start": flat._pw_window.get(0),
            "_pw_window_end": flat._pw_window.get(1),
        }
        if instance_e is not None:
            # instance columns survive flatten under their original name;
            # remap BOTH pw.this and concrete-table references onto the
            # flattened row set (a concrete t.g ref would otherwise dangle
            # on the pre-flatten universe and read None)
            cols["_pw_instance"] = _remap_by_name(
                desugar(instance, {thisclass.this: flat}), flat
            )
        flat2 = flat.with_columns(**cols)
        if behavior is not None:
            flat2 = _apply_behavior(
                flat2, _remap_by_name(time_e, flat2), behavior
            )
        grouping = ["_pw_window_start", "_pw_window_end"]
        if instance_e is not None:
            grouping.append("_pw_instance")
        return WindowedTable(flat2, grouping, table)

    if isinstance(window, SessionWindow):
        session_cols = _session_assign(table, time_e, instance_e, window)
        flat2_cols: Dict[str, ColumnExpression] = {
            name: table[name] for name in table.column_names()
        }
        flat2_cols["_pw_window_start"] = session_cols["start"]
        flat2_cols["_pw_window_end"] = session_cols["end"]
        if instance_e is not None:
            flat2_cols["_pw_instance"] = instance_e
        flat2 = table.select(**flat2_cols)
        if behavior is not None:
            flat2 = _apply_behavior(
                flat2, _remap_by_name(time_e, flat2), behavior
            )
        grouping = ["_pw_window_start", "_pw_window_end"]
        if instance_e is not None:
            grouping.append("_pw_instance")
        return WindowedTable(flat2, grouping, table)

    if isinstance(window, IntervalsOverWindow):
        return _intervals_over_windowby(table, time_e, window)

    raise TypeError(f"unknown window type {type(window)}")


def _session_assign(table: Table, time_e, instance_e, window: SessionWindow) -> Dict:
    """Build a same-universe table with session (start, end) columns."""

    def build(ctx):
        node = ctx.node(table)
        time_prog = _compile_on(ctx, [table], time_e)
        inst_prog = (
            _compile_on(ctx, [table], instance_e) if instance_e is not None else None
        )
        from pathway_tpu.engine.exchange import exchange_by_key, exchange_by_value

        # multi-worker: sessions chain within an instance — co-locate it,
        # then send the per-row assignments back to their key owners
        node = exchange_by_value(
            ctx.engine,
            node,
            inst_prog or (lambda keys, rows: [None] * len(keys)),
        )
        return exchange_by_key(ctx.engine, SessionAssignNode(
            ctx.engine, node, time_prog, inst_prog, window.predicate, window.max_gap
        ))

    schema = schema_from_columns(
        {
            "start": ColumnSchema(name="start", dtype=dt.ANY),
            "end": ColumnSchema(name="end", dtype=dt.ANY),
        }
    )
    sess_table = Table(schema=schema, universe=table._universe, build=build)
    return {"start": sess_table.start, "end": sess_table.end}


from pathway_tpu.engine.engine import Engine, Node  # noqa: E402
from pathway_tpu.engine.operators import _DiffCache, _freeze  # noqa: E402


class SessionAssignNode(Node):
    """Assigns (session_start, session_end) per row by re-chaining each
    affected instance (reference: session windows via sort + pointer jumping,
    stdlib/temporal/_window.py:65-140)."""

    name = "session_assign"
    snapshot_attrs = ('rows', 'cache')

    def __init__(self, engine, input_, time_prog, inst_prog, predicate, max_gap):
        super().__init__(engine, [input_])
        self.time_prog = time_prog
        self.inst_prog = inst_prog
        self.predicate = predicate
        self.max_gap = max_gap
        self.rows: Dict[Any, tuple] = {}  # key -> (time_value, instance)
        self.cache = _DiffCache()

    def process(self, time: int) -> None:
        deltas = self.take(0)
        if not deltas:
            return
        keys = [d[0] for d in deltas]
        rows = ([d[1] for d in deltas],)
        times = self.time_prog(keys, rows)
        insts = (
            self.inst_prog(keys, rows)
            if self.inst_prog is not None
            else [None] * len(keys)
        )
        affected: Set = set()
        for (key, values, diff), tv, inst in zip(deltas, times, insts):
            inst = _freeze(inst)
            affected.add(inst)
            if diff > 0:
                self.rows[key] = (tv, inst)
            else:
                self.rows.pop(key, None)
        out = []
        for inst in affected:
            members = sorted(
                ((tv, k) for k, (tv, i) in self.rows.items() if i == inst)
            )
            new_rows: Dict[Any, tuple] = {}
            if members:
                chain: List[List] = [[members[0]]]
                for prev, cur in zip(members, members[1:]):
                    merge = (
                        self.predicate(prev[0], cur[0])
                        if self.predicate is not None
                        else (cur[0] - prev[0]) <= self.max_gap
                    )
                    if merge:
                        chain[-1].append(cur)
                    else:
                        chain.append([cur])
                for sess in chain:
                    start = sess[0][0]
                    end = sess[-1][0]
                    for _tv, k in sess:
                        new_rows[k] = (start, end)
            self.cache.diff(inst, new_rows, out)
        self.emit(time, out)


def _intervals_over_windowby(
    table: Table, time_e, window: IntervalsOverWindow
) -> WindowedTable:
    """intervals_over: per `at` point, membership of rows with time in
    [at+lower, at+upper] (reference: _window.py:509)."""
    from pathway_tpu.internals.expression import collect_tables

    at_expr = window.at
    at_tables = list(collect_tables(at_expr, set()))
    if len(at_tables) != 1:
        raise ValueError("intervals_over at= must reference exactly one table")
    at_table = at_tables[0]
    lower, upper, is_outer = window.lower_bound, window.upper_bound, window.is_outer

    def build(ctx):
        data_node = ctx.node(table)
        at_node = ctx.node(at_table)
        time_prog = _compile_on(ctx, [table], time_e)
        at_prog = _compile_on(ctx, [at_table], at_expr)
        from pathway_tpu.engine.exchange import (
            exchange_by_key,
            exchange_to_worker,
        )

        # multi-worker: every at-point may touch any data row — gather
        data_node = exchange_to_worker(ctx.engine, data_node, 0)
        at_node = exchange_to_worker(ctx.engine, at_node, 0)
        return exchange_by_key(ctx.engine, IntervalsOverNode(
            ctx.engine,
            data_node,
            at_node,
            time_prog,
            at_prog,
            lower,
            upper,
            is_outer,
            data_width=len(table.column_names()),
        ))

    cols = dict(table._schema.columns().items())
    out_cols = {
        name: ColumnSchema(name=name, dtype=dt.Optionalize(c.dtype))
        for name, c in cols.items()
    }
    out_cols["_pw_window"] = ColumnSchema(name="_pw_window", dtype=dt.ANY)
    # the reference exposes the interval's at-point as
    # `_pw_window_location` (stdlib/temporal/_window.py intervals_over)
    out_cols["_pw_window_location"] = ColumnSchema(
        name="_pw_window_location", dtype=dt.ANY
    )
    flat = Table(
        schema=schema_from_columns(out_cols), universe=Universe(), build=build
    )
    return WindowedTable(flat, ["_pw_window", "_pw_window_location"], table)


class IntervalsOverNode(Node):
    """Membership rows for each at-point's interval neighborhood."""

    name = "intervals_over"
    snapshot_attrs = ('data_rows', 'at_points', 'cache')

    def __init__(
        self,
        engine,
        data_node,
        at_node,
        time_prog,
        at_prog,
        lower,
        upper,
        is_outer,
        *,
        data_width: int,
    ):
        super().__init__(engine, [data_node, at_node])
        self.time_prog = time_prog
        self.at_prog = at_prog
        self.lower = lower
        self.upper = upper
        self.is_outer = is_outer
        self.data_width = data_width
        self.data_rows: Dict[Any, tuple] = {}  # key -> (time, row)
        self.at_points: Dict[Any, Any] = {}  # key -> at value
        self.cache = _DiffCache()

    def process(self, time: int) -> None:
        from pathway_tpu.engine.value import ref_scalar

        data_deltas = self.take(0)
        at_deltas = self.take(1)
        if not data_deltas and not at_deltas:
            return
        affected_ats: Set = set()
        changed_times: List = []
        if data_deltas:
            keys = [d[0] for d in data_deltas]
            rows = ([d[1] for d in data_deltas],)
            tvs = self.time_prog(keys, rows)
            for (key, values, diff), tv in zip(data_deltas, tvs):
                if diff > 0:
                    self.data_rows[key] = (tv, values)
                else:
                    self.data_rows.pop(key, None)
                changed_times.append(tv)
        if at_deltas:
            keys = [d[0] for d in at_deltas]
            rows = ([d[1] for d in at_deltas],)
            avs = self.at_prog(keys, rows)
            for (key, values, diff), av in zip(at_deltas, avs):
                if diff > 0:
                    self.at_points[key] = av
                else:
                    self.at_points.pop(key, None)
                affected_ats.add(key)
        if changed_times:
            for ak, av in self.at_points.items():
                for tv in changed_times:
                    if av + self.lower <= tv <= av + self.upper:
                        affected_ats.add(ak)
                        break
        out = []
        for ak in affected_ats:
            new_rows: Dict[Any, tuple] = {}
            if ak in self.at_points:
                av = self.at_points[ak]
                members = [
                    (k, row)
                    for k, (tv, row) in self.data_rows.items()
                    if av + self.lower <= tv <= av + self.upper
                ]
                if members:
                    for k, row in members:
                        new_rows[ref_scalar(ak, k)] = (*row, (av,), av)
                elif self.is_outer:
                    new_rows[ref_scalar(ak, None)] = (
                        *(None,) * self.data_width,
                        (av,),
                        av,
                    )
            self.cache.diff(ak, new_rows, out)
        self.emit(time, out)
