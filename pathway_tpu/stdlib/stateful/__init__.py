"""pw.stateful (reference: python/pathway/stdlib/stateful/deduplicate.py)."""

from __future__ import annotations

from typing import Any, Callable


def deduplicate(
    table,
    *,
    value=None,
    col=None,
    instance=None,
    acceptor: Callable[[Any, Any], bool] | None = None,
    name: str | None = None,
    persistent_id: str | None = None,
):
    """Keep the latest accepted value per instance (reference:
    stdlib/stateful/deduplicate.py).

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... instance | v | __time__
    ... 1        | 1 |     2
    ... 1        | 5 |     4
    ... ''')
    >>> res = pw.stateful.deduplicate(
    ...     t, value=pw.this.v, instance=pw.this.instance,
    ...     acceptor=lambda new, old: new > old,
    ... )
    >>> pw.debug.compute_and_print(
    ...     res.select(v=pw.this.v), include_id=False
    ... )
    v
    5
    """
    return table.deduplicate(
        value=value if value is not None else col,
        instance=instance,
        acceptor=acceptor,
        name=name,
    )


__all__ = ["deduplicate"]
