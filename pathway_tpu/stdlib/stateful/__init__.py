"""pw.stateful (reference: python/pathway/stdlib/stateful/deduplicate.py)."""

from __future__ import annotations

from typing import Any, Callable


def deduplicate(
    table,
    *,
    value=None,
    col=None,
    instance=None,
    acceptor: Callable[[Any, Any], bool] | None = None,
    name: str | None = None,
    persistent_id: str | None = None,
):
    """Keep the latest accepted value per instance (reference:
    stdlib/stateful/deduplicate.py)."""
    return table.deduplicate(
        value=value if value is not None else col,
        instance=instance,
        acceptor=acceptor,
        name=name,
    )


__all__ = ["deduplicate"]
