"""Row filters (reference: python/pathway/stdlib/utils/filtering.py).

>>> import pathway_tpu as pw
>>> from pathway_tpu.stdlib.utils.filtering import argmax_rows
>>> t = pw.debug.table_from_markdown('''
... g | v
... a | 1
... a | 5
... b | 2
... ''')
>>> pw.debug.compute_and_print(
...     argmax_rows(t, pw.this.g, what=pw.this.v), include_id=False
... )
g | v
b | 2
a | 5
"""

from __future__ import annotations

from pathway_tpu.internals import thisclass
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table


def argmax_rows(table: Table, *on, what) -> Table:
    """Keep, per group of `on`, the row maximizing `what` (reference:
    filtering.py argmax_rows:8)."""
    filter_t = (
        table.groupby(*on)
        .reduce(argmax_id=reducers.argmax(what))
        .with_id(thisclass.this.argmax_id)
    )
    return table.restrict(filter_t)


def argmin_rows(table: Table, *on, what) -> Table:
    filter_t = (
        table.groupby(*on)
        .reduce(argmin_id=reducers.argmin(what))
        .with_id(thisclass.this.argmin_id)
    )
    return table.restrict(filter_t)
