"""Column utilities (reference: python/pathway/stdlib/utils/col.py).

>>> import pathway_tpu as pw
>>> t = pw.debug.table_from_rows(
...     pw.schema_from_types(pair=tuple), [((1, "x"),)]
... )
>>> from pathway_tpu.stdlib.utils.col import unpack_col
>>> r = unpack_col(t.pair, pw.this.num, pw.this.name)
>>> pw.debug.compute_and_print(r, include_id=False)
num | name
1   | x
"""

from __future__ import annotations

from typing import Any, Type

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import desugar
from pathway_tpu.internals.schema import Schema


def unpack_col(column, *unpacked_columns, schema: Type[Schema] | None = None):
    """Expand a tuple column into separate columns (reference:
    utils/col.py unpack_col)."""
    from pathway_tpu.internals.expression import collect_tables

    tables = list(collect_tables(column, set()))
    if len(tables) != 1:
        raise ValueError("unpack_col expects a single-table column")
    table = tables[0]
    if schema is not None:
        names = list(schema.keys())
    else:
        names = [
            c if isinstance(c, str) else c.name for c in unpacked_columns
        ]
    cols = {name: column.get(i) for i, name in enumerate(names)}
    return table.select(**cols)


def flatten_column(column, origin_id: str | None = None):
    from pathway_tpu.internals.expression import collect_tables

    tables = list(collect_tables(column, set()))
    table = tables[0]
    return table.flatten(column)


def multiapply_all_rows(*cols, fun, result_col_names):
    """Apply `fun` over the FULL columns at once; fun receives one list per
    input column and returns one aligned list per result column (reference:
    utils/col.py multiapply_all_rows — whole-column semantics, e.g.
    normalization against global statistics)."""
    from pathway_tpu.engine.value import Pointer, ref_scalar
    from pathway_tpu.internals import api as pw_api
    from pathway_tpu.internals.expression import collect_tables
    from pathway_tpu.internals.reducers import reducers

    tables = set()
    for c in cols:
        tables |= collect_tables(c, set())
    if len(tables) != 1:
        raise ValueError("multiapply_all_rows expects columns of one table")
    (table,) = tables

    packed = table.select(
        _pw_row=pw_api.make_tuple(thisclass.this.id, *cols)
    ).groupby().reduce(rows=reducers.tuple(thisclass.this._pw_row))

    n_out = len(result_col_names)

    def run(rows) -> tuple:
        rows = list(rows or ())
        keys = [r[0] for r in rows]
        columns = [[r[i + 1] for r in rows] for i in range(len(cols))]
        results = fun(*columns)
        if n_out == 1 and not isinstance(results, tuple):
            results = (results,)
        return tuple(
            (k, *(col[i] for col in results)) for i, k in enumerate(keys)
        )

    flat = packed.select(
        pairs=pw_api.apply_with_type(run, tuple, thisclass.this.rows)
    ).flatten(thisclass.this.pairs)
    keyed = flat.with_id(
        pw_api.apply_with_type(
            lambda p: p, Pointer, thisclass.this.pairs.get(0)
        )
    )
    return keyed.select(
        **{
            name: thisclass.this.pairs.get(i + 1)
            for i, name in enumerate(result_col_names)
        }
    )


def apply_all_rows(*cols, fun, result_col_name):
    """Single-result variant of multiapply_all_rows (reference:
    utils/col.py apply_all_rows)."""
    return multiapply_all_rows(
        *cols, fun=fun, result_col_names=[result_col_name]
    )


def groupby_reduce_majority(column, majority_col_name: str = "majority"):
    from pathway_tpu.internals.expression import collect_tables
    from pathway_tpu.internals.reducers import reducers

    tables = list(collect_tables(column, set()))
    table = tables[0]
    counted = table.groupby(column).reduce(
        **{majority_col_name: column, "_pw_count": reducers.count()}
    )
    return counted
