"""Column utilities (reference: python/pathway/stdlib/utils/col.py)."""

from __future__ import annotations

from typing import Any, Type

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import desugar
from pathway_tpu.internals.schema import Schema


def unpack_col(column, *unpacked_columns, schema: Type[Schema] | None = None):
    """Expand a tuple column into separate columns (reference:
    utils/col.py unpack_col)."""
    from pathway_tpu.internals.expression import collect_tables

    tables = list(collect_tables(column, set()))
    if len(tables) != 1:
        raise ValueError("unpack_col expects a single-table column")
    table = tables[0]
    if schema is not None:
        names = list(schema.keys())
    else:
        names = [
            c if isinstance(c, str) else c.name for c in unpacked_columns
        ]
    cols = {name: column.get(i) for i, name in enumerate(names)}
    return table.select(**cols)


def flatten_column(column, origin_id: str | None = None):
    from pathway_tpu.internals.expression import collect_tables

    tables = list(collect_tables(column, set()))
    table = tables[0]
    return table.flatten(column)


def multiapply_all_rows(*cols, fun, result_col_names):
    raise NotImplementedError("multiapply_all_rows: use batched UDFs instead")


def apply_all_rows(*cols, fun, result_col_name):
    raise NotImplementedError("apply_all_rows: use batched UDFs instead")


def groupby_reduce_majority(column, majority_col_name: str = "majority"):
    from pathway_tpu.internals.expression import collect_tables
    from pathway_tpu.internals.reducers import reducers

    tables = list(collect_tables(column, set()))
    table = tables[0]
    counted = table.groupby(column).reduce(
        **{majority_col_name: column, "_pw_count": reducers.count()}
    )
    return counted
