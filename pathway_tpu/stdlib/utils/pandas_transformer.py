"""@pw.pandas_transformer — pandas functions as table transformers
(reference: python/pathway/stdlib/utils/pandas_transformer.py:124).

Input tables materialize into pandas DataFrames (row keys become the
index), the wrapped function runs on them, and the returned DataFrame
becomes a table again: index values that are row Pointers keep them,
integer indexes derive fresh stable keys. Recomputed per engine batch —
whole-table semantics by definition (the reference does the same: the
function sees full frames, not deltas)."""

from __future__ import annotations

from typing import Any, Type

from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.schema import Schema
from pathway_tpu.internals.table import Table
from pathway_tpu.engine.value import Pointer, ref_scalar


def _pack_whole_table(table: Table, tag: int):
    cols = [table[c] for c in table.column_names()]
    tagged = table.select(
        _pw_row=pw_api.make_tuple(tag, thisclass.this.id, *cols)
    )
    return tagged


def pandas_transformer(
    output_schema: Type[Schema], output_universe: str | int | None = None
):
    def decorator(func):
        out_names = list(output_schema.keys())

        def wrapper(*tables: Table) -> Table:
            import pandas as pd

            universe_arg: int | None = None
            if output_universe is not None:
                if isinstance(output_universe, int):
                    universe_arg = output_universe
                else:
                    raise NotImplementedError(
                        "output_universe by argument NAME is not supported; "
                        "pass the positional index of the input table"
                    )
                if not 0 <= universe_arg < len(tables):
                    raise ValueError(
                        f"output_universe={universe_arg} out of range for "
                        f"{len(tables)} input tables"
                    )

            column_names = [t.column_names() for t in tables]

            packed_inputs = [
                _pack_whole_table(t, i) for i, t in enumerate(tables)
            ]
            union = packed_inputs[0]
            if len(packed_inputs) > 1:
                union = union.concat_reindex(*packed_inputs[1:])
            packed = union.groupby().reduce(
                rows=reducers.tuple(thisclass.this._pw_row)
            )

            def run(rows) -> tuple:
                per_input: list[list] = [[] for _ in tables]
                for row in rows or ():
                    per_input[row[0]].append(row[1:])
                frames = []
                for names, data in zip(column_names, per_input):
                    frames.append(
                        pd.DataFrame(
                            [r[1:] for r in data],
                            columns=names,
                            index=[r[0] for r in data],
                        )
                    )
                result = func(*frames)
                if universe_arg is not None:
                    # promised universe: every output row must keep a key
                    # of the chosen input table (reference: the output
                    # index IS the output universe)
                    allowed = {r[0] for r in per_input[universe_arg]}
                    stray = [i for i in result.index if i not in allowed]
                    if stray:
                        raise ValueError(
                            "pandas_transformer: output index not in the "
                            f"universe of input {universe_arg}: {stray[:3]}"
                        )
                out = []
                for idx, row in zip(result.index, result.itertuples(index=False)):
                    out.append((idx, *tuple(row)[: len(out_names)]))
                return tuple(out)

            flat = (
                packed.select(
                    pairs=pw_api.apply_with_type(
                        run, tuple, thisclass.this.rows
                    )
                )
                .flatten(thisclass.this.pairs)
            )

            def to_key(v) -> Pointer:
                if isinstance(v, Pointer):
                    return v
                return ref_scalar("__pandas_transformer__", v)

            keyed = flat.with_id(
                pw_api.apply_with_type(
                    to_key, Pointer, thisclass.this.pairs.get(0)
                )
            )
            return keyed.select(
                **{
                    name: thisclass.this.pairs.get(i + 1)
                    for i, name in enumerate(out_names)
                }
            )

        return wrapper

    return decorator
