"""pw.utils (reference: python/pathway/stdlib/utils/)."""

from pathway_tpu.stdlib.utils import bucketing, col, filtering
from pathway_tpu.stdlib.utils.filtering import argmax_rows, argmin_rows
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer

__all__ = [
    "argmax_rows",
    "argmin_rows",
    "bucketing",
    "col",
    "filtering",
    "pandas_transformer",
]
