"""pw.utils (reference: python/pathway/stdlib/utils/)."""

from pathway_tpu.stdlib.utils import col

__all__ = ["col"]
