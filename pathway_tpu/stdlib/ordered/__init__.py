"""pw.ordered — order-aware helpers (reference:
python/pathway/stdlib/ordered/diff.py)."""

from __future__ import annotations

from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import desugar


def diff(table, timestamp, *values, instance=None):
    """Difference with the previous row in `timestamp` order (reference:
    stdlib/ordered/diff.py — built on sort's prev pointers).

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... t | v
    ... 1 | 10
    ... 2 | 13
    ... 3 | 11
    ... ''')
    >>> res = t.diff(pw.this.t, pw.this.v)
    >>> pw.debug.compute_and_print(
    ...     res.select(v=pw.this.diff_v), include_id=False
    ... )
    v
    -2
    None
    3
    """
    mapping = {thisclass.this: table}
    ts = desugar(timestamp, mapping)
    from pathway_tpu.internals.api import require, unwrap

    sorted_t = table.sort(key=ts, instance=instance)
    prev_rows = table.ix(sorted_t.prev, optional=True)
    cols = {}
    for v in values:
        ref = desugar(v, mapping)
        # first row (prev is None) gets None, not an Error (reference:
        # ordered/diff.py wraps the subtraction in pw.require on prev)
        cols[f"diff_{ref.name}"] = require(
            ref - unwrap(prev_rows[ref.name]), sorted_t.prev
        )
    return table.select(**cols)


__all__ = ["diff"]
