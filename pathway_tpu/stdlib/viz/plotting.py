"""Live plots from tables (reference:
python/pathway/stdlib/viz/plotting.py plot:35 — a user plotting function
over a Bokeh ColumnDataSource, streamed updates in notebooks).

Bokeh/Panel are optional: without them, `plot` returns a `PlotHandle`
exposing the same streaming `ColumnDataSource`-like dict the user function
receives, so pipelines remain testable headless; matplotlib (if present)
can render a static snapshot via `PlotHandle.to_matplotlib`."""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List


class StreamingSource:
    """Dict-of-columns view of a table, updated from the change stream —
    the headless stand-in for bokeh's ColumnDataSource."""

    def __init__(self, table):
        self.column_names: List[str] = table.column_names()
        self._rows: Dict[Any, tuple] = {}
        self._lock = threading.Lock()
        self._listeners: List[Callable[[], None]] = []

        from pathway_tpu.io._subscribe import subscribe

        def on_change(key, row, time, is_addition):
            with self._lock:
                if is_addition:
                    self._rows[key] = tuple(row[c] for c in self.column_names)
                else:
                    self._rows.pop(key, None)
            for listener in list(self._listeners):
                listener()

        subscribe(table, on_change=on_change)

    @property
    def data(self) -> Dict[str, list]:
        with self._lock:
            rows = list(self._rows.values())
        return {
            name: [r[i] for r in rows]
            for i, name in enumerate(self.column_names)
        }

    def on_update(self, listener: Callable[[], None]) -> None:
        self._listeners.append(listener)


class PlotHandle:
    def __init__(self, source: StreamingSource, plotting_function):
        self.source = source
        self.plotting_function = plotting_function

    def to_matplotlib(self, x: str, y: str):
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots()
        data = self.source.data
        ax.plot(data[x], data[y], "o-")
        ax.set_xlabel(x)
        ax.set_ylabel(y)
        return fig


def plot(table, plotting_function: Callable, sorting_col=None):
    """reference: plotting.py plot:35."""
    try:
        import bokeh.models  # type: ignore
        import panel as pn  # type: ignore

        source = bokeh.models.ColumnDataSource(
            data={c: [] for c in table.column_names()}
        )
        fig = plotting_function(source)
        streaming = StreamingSource(table)

        def push():
            source.data = streaming.data

        streaming.on_update(push)
        return pn.Column(pn.pane.Bokeh(fig))
    except Exception:  # noqa: BLE001 — bokeh/panel absent
        source = StreamingSource(table)
        try:
            fig = plotting_function(source)
        except Exception:  # noqa: BLE001 — function expects bokeh API
            fig = None
        return PlotHandle(source, plotting_function)
