"""Live table visualization (reference:
python/pathway/stdlib/viz/table_viz.py show:26 — Panel/Bokeh live table in
notebooks, styled DataFrame snapshots).

Panel/Bokeh are optional: with them installed, `show` returns a live
`panel.Column` exactly like the reference; without them it returns a
`TableVisualization` handle whose snapshot renders as text/HTML — the same
subscribe-driven update loop either way."""

from __future__ import annotations

import threading
from typing import Any, Dict, List


class TableVisualization:
    """Accumulates a live snapshot of a table for display."""

    def __init__(self, table, *, include_id: bool = True, sorting_col=None):
        self.column_names: List[str] = table.column_names()
        self.include_id = include_id
        self.sorting_col = sorting_col
        self._rows: Dict[Any, tuple] = {}
        self._lock = threading.Lock()

        from pathway_tpu.io._subscribe import subscribe

        def on_change(key, row, time, is_addition):
            with self._lock:
                if is_addition:
                    self._rows[key] = tuple(
                        row[c] for c in self.column_names
                    )
                else:
                    self._rows.pop(key, None)

        subscribe(table, on_change=on_change)

    def snapshot(self) -> List[tuple]:
        with self._lock:
            items = list(self._rows.items())
        if self.sorting_col is not None:
            idx = self.column_names.index(self.sorting_col)
            items.sort(key=lambda kv: repr(kv[1][idx]))
        else:
            items.sort(key=lambda kv: kv[0])
        return items

    def to_pandas(self):
        import pandas as pd

        items = self.snapshot()
        df = pd.DataFrame(
            [v for _k, v in items], columns=self.column_names
        )
        if self.include_id:
            df.index = [repr(k) for k, _v in items]
        return df

    def __str__(self) -> str:
        items = self.snapshot()
        header = list(self.column_names)
        lines = [" | ".join(header)]
        for _k, values in items:
            lines.append(" | ".join(str(v) for v in values))
        return "\n".join(lines)

    def _repr_html_(self) -> str:
        try:
            return self.to_pandas().to_html()
        except Exception:  # noqa: BLE001
            return f"<pre>{self}</pre>"


def show(table, *, include_id: bool = True, short_pointers: bool = True,
         sorting_col=None, **kwargs):
    """reference: table_viz.py show:26. Returns a live panel when
    panel/bokeh are importable, else a TableVisualization handle."""
    viz = TableVisualization(
        table, include_id=include_id, sorting_col=sorting_col
    )
    try:
        import panel as pn  # type: ignore

        df_pane = pn.pane.DataFrame(viz.to_pandas(), **kwargs)

        def refresh():
            df_pane.object = viz.to_pandas()

        pn.state.add_periodic_callback(refresh, period=500)
        return pn.Column(df_pane)
    except Exception:  # noqa: BLE001 — panel absent: text-mode handle
        return viz


def _repr_mimebundle_(self, include, exclude):
    """Notebook hook grafted onto Table (reference: table_viz.py:20).

    Rendering a table must not mutate the graph (a bare `t` in a notebook
    cell would otherwise register one subscriber sink per display), so the
    repr shows the schema; `t.show()` / interactive mode give live data."""
    return {
        "text/plain": (
            repr(self)
            + " — call .show() or enable_interactive_mode() + .live() "
            "for data"
        )
    }
