"""pw.viz — live table/plot visualization (reference:
python/pathway/stdlib/viz/). Grafts `.show()` and `.plot()` onto Table as
the reference does."""

from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.viz.plotting import PlotHandle, StreamingSource, plot
from pathway_tpu.stdlib.viz.table_viz import (
    TableVisualization,
    _repr_mimebundle_,
    show,
)

from pathway_tpu.internals.interactive import live as _live

Table.show = show
Table.live = _live
Table.plot = plot
Table._repr_mimebundle_ = _repr_mimebundle_

__all__ = [
    "PlotHandle",
    "StreamingSource",
    "TableVisualization",
    "plot",
    "show",
]
