"""pw.graphs — graph algorithms (reference: python/pathway/stdlib/graphs/:
bellman_ford/impl.py, pagerank/impl.py, louvain_communities/impl.py).
All are fixed-point computations over edge tables via pw.iterate."""

from pathway_tpu.stdlib.graphs.common import Edge, Vertex, Graph
from pathway_tpu.stdlib.graphs.pagerank import pagerank
from pathway_tpu.stdlib.graphs.bellman_ford import bellman_ford

__all__ = ["Edge", "Vertex", "Graph", "pagerank", "bellman_ford"]
