"""pw.graphs — graph algorithms (reference: python/pathway/stdlib/graphs/:
bellman_ford/impl.py, pagerank/impl.py, louvain_communities/impl.py).
All are fixed-point computations over edge tables via pw.iterate."""

from pathway_tpu.stdlib.graphs.common import (
    Clustering,
    Edge,
    Graph,
    Vertex,
    Weight,
    WeightedGraph,
)
from pathway_tpu.stdlib.graphs.pagerank import pagerank
from pathway_tpu.stdlib.graphs.bellman_ford import bellman_ford
from pathway_tpu.stdlib.graphs.louvain import _louvain_level, louvain_communities

__all__ = [
    "Clustering",
    "Edge",
    "Graph",
    "Vertex",
    "Weight",
    "WeightedGraph",
    "pagerank",
    "bellman_ford",
    "louvain_communities",
]
