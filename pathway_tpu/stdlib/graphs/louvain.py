"""Louvain community detection (reference:
python/pathway/stdlib/graphs/louvain_communities/impl.py).

API parity: `louvain_communities(G)` returns a clustering table keyed by
vertex with a cluster-id column `c`; `_louvain_level(G)` runs one level.

Design departure, deliberate: the reference unrolls the local-move loop
into an incremental dataflow (propose via modularity-gain argmax, resolve
oscillations with fingerprint tie-breaks, iterate to fixpoint). Here the
edge set aggregates into one group and a batched UDF runs the classic
sequential multi-level Louvain — every input delta recomputes communities
for the new graph in one pass. The trade: O(graph) work per batch instead
of O(delta), for exact classic-Louvain quality and far less machinery; at
streaming-graph scales where O(delta) matters the reference's quality also
degrades (simultaneous moves), so this keeps results stable."""

from __future__ import annotations

import random
from typing import Any, Dict, List, Tuple

from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.table import Table


def _louvain_python(
    edges: List[Tuple[Any, Any, float]], seed: int = 0, levels: int = 10
) -> Dict[Any, Any]:
    """Classic multi-level Louvain on an undirected weighted edge list.
    Returns vertex -> representative community label."""
    rng = random.Random(seed)
    # current graph: adjacency with weights; vertex -> community of the
    # ORIGINAL vertices it aggregates
    adj: Dict[Any, Dict[Any, float]] = {}
    self_loops: Dict[Any, float] = {}
    for u, v, w in edges:
        w = float(w)
        if u == v:
            self_loops[u] = self_loops.get(u, 0.0) + w
            adj.setdefault(u, {})
            continue
        adj.setdefault(u, {})[v] = adj.setdefault(u, {}).get(v, 0.0) + w
        adj.setdefault(v, {})[u] = adj.setdefault(v, {}).get(u, 0.0) + w
    members: Dict[Any, List[Any]] = {u: [u] for u in adj}

    for _level in range(levels):
        m2 = sum(sum(nbrs.values()) for nbrs in adj.values()) + 2.0 * sum(
            self_loops.values()
        )
        if m2 <= 0:
            break
        comm = {u: u for u in adj}
        deg = {
            u: sum(nbrs.values()) + 2.0 * self_loops.get(u, 0.0)
            for u, nbrs in adj.items()
        }
        comm_deg = dict(deg)
        improved_any = False
        order = sorted(adj, key=lambda u: (isinstance(u, str), repr(u)))
        rng.shuffle(order)
        for _sweep in range(20):
            moved = 0
            for u in order:
                cu = comm[u]
                # weights from u to each adjacent community
                to_comm: Dict[Any, float] = {}
                for v, w in adj[u].items():
                    to_comm[comm[v]] = to_comm.get(comm[v], 0.0) + w
                comm_deg[cu] -= deg[u]
                best_c, best_gain = cu, to_comm.get(cu, 0.0) - (
                    comm_deg[cu] * deg[u] / m2
                )
                for c, w_uc in to_comm.items():
                    if c == cu:
                        continue
                    gain = w_uc - comm_deg[c] * deg[u] / m2
                    if gain > best_gain + 1e-12:
                        best_c, best_gain = c, gain
                comm_deg[best_c] = comm_deg.get(best_c, 0.0) + deg[u]
                if best_c != cu:
                    comm[u] = best_c
                    moved += 1
            if moved == 0:
                break
            improved_any = True
        if not improved_any:
            break
        # aggregate: one super-vertex per community
        new_adj: Dict[Any, Dict[Any, float]] = {}
        new_self: Dict[Any, float] = {}
        new_members: Dict[Any, List[Any]] = {}
        for u, nbrs in adj.items():
            cu = comm[u]
            new_members.setdefault(cu, []).extend(members[u])
            new_self[cu] = new_self.get(cu, 0.0) + self_loops.get(u, 0.0)
            new_adj.setdefault(cu, {})
            for v, w in nbrs.items():
                cv = comm[v]
                if cu == cv:
                    # each intra-community edge appears twice in adj
                    new_self[cu] = new_self.get(cu, 0.0) + w / 2.0
                else:
                    new_adj[cu][cv] = new_adj[cu].get(cv, 0.0) + w
        if len(new_adj) == len(adj):
            break
        adj, self_loops, members = new_adj, new_self, new_members

    out: Dict[Any, Any] = {}
    for super_v, orig in members.items():
        label = min(orig, key=lambda x: (isinstance(x, str), repr(x)))
        for o in orig:
            out[o] = label
    return out


def louvain_communities(G, *, seed: int = 0) -> Table:
    """Multi-level Louvain over a weighted graph (reference:
    louvain_communities/impl.py). `G` is a WeightedGraph (or any object
    with .WE edges table holding u, v, weight) — returns a table keyed by
    vertex with column `c` (community label Pointer)."""
    edges = getattr(G, "WE", None)
    if edges is None:
        edges = getattr(G, "E", G)
    has_weight = "weight" in edges.column_names()
    triples = edges.select(
        t=pw_api.make_tuple(
            edges.u, edges.v, edges.weight if has_weight else 1.0
        )
    )
    import pathway_tpu.internals.reducers as red

    packed = triples.groupby().reduce(
        all_edges=red.reducers.tuple(thisclass.this.t)
    )

    def run(all_edges) -> tuple:
        labels = _louvain_python(list(all_edges or ()), seed=seed)
        return tuple(sorted(labels.items(), key=lambda kv: repr(kv[0])))

    labeled = packed.select(
        pairs=pw_api.apply_with_type(run, tuple, thisclass.this.all_edges)
    ).flatten(thisclass.this.pairs)
    out = labeled.select(
        u=thisclass.this.pairs.get(0), c=thisclass.this.pairs.get(1)
    )
    return out.with_id(out.u).select(c=thisclass.this.c)


# one Louvain level = same entry point with levels=1 semantics; kept for
# reference parity
def _louvain_level(G, *, seed: int = 0) -> Table:
    return louvain_communities(G, seed=seed)
