"""Graph data model (reference: stdlib/graphs/common.py)."""

from __future__ import annotations

from pathway_tpu.internals.schema import Schema


class Vertex(Schema):
    pass


class Edge(Schema):
    u: object  # Pointer to source vertex
    v: object  # Pointer to target vertex


class Graph:
    def __init__(self, V, E):
        self.V = V
        self.E = E
