"""Graph data model (reference: stdlib/graphs/common.py)."""

from __future__ import annotations

from pathway_tpu.internals.schema import Schema


class Vertex(Schema):
    pass


class Edge(Schema):
    u: object  # Pointer to source vertex
    v: object  # Pointer to target vertex


class Graph:
    def __init__(self, V, E):
        self.V = V
        self.E = E


class Weight(Schema):
    weight: float


class Clustering(Schema):
    c: object  # Pointer to the cluster representative


class WeightedGraph(Graph):
    """Graph with weighted edges (reference: stdlib/graphs/graph.py
    WeightedGraph). `WE` holds u, v, weight."""

    def __init__(self, V, E, WE=None):
        super().__init__(V, E)
        self.WE = WE if WE is not None else E

    @classmethod
    def from_vertices_and_weighted_edges(cls, V, WE) -> "WeightedGraph":
        return cls(V, WE, WE)
