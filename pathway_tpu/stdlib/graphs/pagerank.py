"""PageRank (reference: python/pathway/stdlib/graphs/pagerank/impl.py).

Edges table has pointer columns u -> v; returns a table keyed by vertex
with a `rank` column (scaled integers, as the reference does to stay in
exact arithmetic)."""

from __future__ import annotations

import pathway_tpu.internals.reducers as red
from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.api import iterate
from pathway_tpu.internals.table import Table


def pagerank(edges: Table, steps: int = 5, damping: int = 85) -> Table:
    """Iterative PageRank over an edge table with columns u, v.

    >>> import pathway_tpu as pw
    >>> edges = pw.debug.table_from_markdown('''
    ... a | b
    ... x | y
    ... y | z
    ... z | y
    ... ''')
    >>> E = edges.select(
    ...     u=edges.pointer_from(pw.this.a), v=edges.pointer_from(pw.this.b)
    ... )
    >>> from pathway_tpu.stdlib.graphs.pagerank import pagerank
    >>> ranks = pagerank(E, steps=3)
    >>> ranks.column_names()
    ['rank']
    """
    # vertex set = endpoints of edges
    us = edges.select(vid=edges.u)
    vs = edges.select(vid=edges.v)
    vertices = (
        us.concat_reindex(vs)
        .groupby(thisclass.this.vid)
        .reduce(vid=thisclass.this.vid)
    )
    degs = edges.groupby(edges.u).reduce(
        vid=edges.u, degree=red.count()
    )
    base = vertices.with_id(vertices.vid).select(rank=10_000)

    def step(ranks):
        # rank flows: each vertex sends rank/degree to its neighbors
        with_deg = degs.with_id(degs.vid)
        edge_flow = edges.select(
            target=edges.v,
            flow=ranks.ix(edges.u, optional=True).rank
            // pw_api.coalesce(with_deg.ix(edges.u, optional=True).degree, 1),
        )
        inflow = edge_flow.groupby(edge_flow.target).reduce(
            vid=edge_flow.target,
            total=red.sum_(edge_flow.flow),
        )
        keyed_inflow = inflow.with_id(inflow.vid)
        return ranks.select(
            rank=(
                pw_api.coalesce(keyed_inflow.ix(ranks.id, optional=True).total, 0)
                * damping
                + 1500 * 10
            )
            // 100
        )

    result = base
    for _ in range(steps):
        result = step(result)
    return result
