"""Bellman-Ford shortest paths (reference:
python/pathway/stdlib/graphs/bellman_ford/impl.py) — fixed point via
pw.iterate."""

from __future__ import annotations

import math

import pathway_tpu.internals.reducers as red
from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals.api import iterate
from pathway_tpu.internals.table import Table


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """vertices: (is_source: bool); edges: (u, v pointers, dist float).
    Returns dist_from_source per vertex.

    >>> import pathway_tpu as pw
    >>> verts = pw.debug.table_from_markdown('''
    ... name | is_source
    ... a    | True
    ... b    | False
    ... ''').with_id_from(pw.this.name)
    >>> e = pw.debug.table_from_markdown('''
    ... us | vs | dist
    ... a  | b  | 2.0
    ... ''')
    >>> E = e.select(
    ...     u=verts.pointer_from(e.us),
    ...     v=verts.pointer_from(e.vs),
    ...     dist=pw.this.dist,
    ... )
    >>> from pathway_tpu.stdlib.graphs.bellman_ford import bellman_ford
    >>> pw.debug.compute_and_print(bellman_ford(verts, E), include_id=False)
    dist
    2.0
    0.0
    """

    base = vertices.select(
        dist=pw_api.if_else(vertices.is_source, 0.0, math.inf)
    )

    def step(dists):
        relaxed = edges.select(
            target=edges.v,
            candidate=dists.ix(edges.u, optional=True).dist + edges.dist,
        )
        best = relaxed.groupby(relaxed.target).reduce(
            vid=relaxed.target,
            best=red.min_(relaxed.candidate),
        )
        keyed = best.with_id(best.vid)
        looked = keyed.ix(dists.id, optional=True)
        return dists.select(
            dist=pw_api.if_else(
                pw_api.coalesce(looked.best, math.inf) < dists.dist,
                pw_api.coalesce(looked.best, math.inf),
                dists.dist,
            )
        )

    return iterate(step, dists=base)
