"""pw.io.debezium — CDC ingestion via Debezium-format messages (reference:
python/pathway/io/debezium read:17; Rust parser
src/connectors/data_format.rs DebeziumMessageParser:1122).

Debezium envelopes carry `payload.before` / `payload.after` and an op code
(`c`reate / `u`pdate / `d`elete / `r`ead-snapshot); updates decompose into a
retraction of `before` plus an insertion of `after` — exactly the engine's
diff semantics.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, Optional, Tuple

from pathway_tpu.internals import dtype as dt
from pathway_tpu.io import _mq
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


def parse_debezium_message(payload: bytes | str) -> list[tuple[dict, int]]:
    """Parse one Debezium message into [(row_dict, diff)] (reference:
    DebeziumMessageParser::parse, data_format.rs:1122)."""
    if isinstance(payload, bytes):
        payload = payload.decode(errors="replace")
    obj = json.loads(payload)
    body = obj.get("payload", obj)
    if body is None:
        return []
    op = body.get("op", "c")
    before = body.get("before")
    after = body.get("after")
    out: list[tuple[dict, int]] = []
    if op in ("c", "r"):
        if after is not None:
            out.append((after, 1))
    elif op == "u":
        if before is not None:
            out.append((before, -1))
        if after is not None:
            out.append((after, 1))
    elif op == "d":
        if before is not None:
            out.append((before, -1))
    return out


class _DebeziumSubject(ConnectorSubjectBase):
    def __init__(self, client_factory, schema, mode: str):
        super().__init__()
        self.client_factory = client_factory
        self.schema = schema
        self.mode = mode

    def run(self) -> None:
        client = self.client_factory()
        names = set(self.schema.keys())
        try:
            while True:
                batch = client.poll(0.2)
                if batch is None:
                    return
                got = False
                for key, payload, meta in batch:
                    got = True
                    for row, diff in parse_debezium_message(payload):
                        clean = {
                            k: _mq._coerce(v, self.schema[k].dtype)
                            for k, v in row.items()
                            if k in names
                        }
                        if diff > 0:
                            self.next(**clean)
                        else:
                            self._remove(clean)
                if got:
                    self.commit()
                    client.commit()
                elif self.mode == "static":
                    return
        finally:
            client.close()


def read(
    rdkafka_settings: dict | None = None,
    topic_name: str | None = None,
    *,
    schema=None,
    autocommit_duration_ms: int | None = 1500,
    mode: str = "streaming",
    name: str | None = None,
    _client_factory=None,
    **kwargs,
):
    """Read a Debezium CDC stream as an evolving table (reference:
    io/debezium read:17)."""
    if schema is None:
        raise ValueError("pw.io.debezium.read requires schema")
    if _client_factory is None:
        from pathway_tpu.io.kafka import _ConfluentClient

        def _client_factory():
            return _ConfluentClient(rdkafka_settings, topic_name, for_read=True)

    def factory():
        return _DebeziumSubject(_client_factory, schema, mode)

    return connector_table(schema, factory, mode=mode, name=name)
