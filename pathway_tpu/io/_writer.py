"""Shared output-writer machinery for io sinks.

TPU-native equivalent of the reference Writer trait + ConsolidateForOutput
(reference: src/connectors/data_storage.rs:660 `trait Writer`,
src/engine/dataflow/operators/output.rs — updates grouped into per-time
batches before hitting the backend). Every DB/MQ writer module builds on
`attach_writer`, which batches the change stream per engine time and hands
`RowEvent` batches to a backend-specific `OutputWriter`.
"""

from __future__ import annotations

import datetime
import json
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence

import numpy as np

from pathway_tpu.internals.parse_graph import G


@dataclass
class RowEvent:
    """One change-stream delta (reference: FormatterContext values+diff,
    src/connectors/data_format.rs:474)."""

    key: Any
    values: Dict[str, Any]
    time: int
    diff: int  # +1 insert / -1 delete


class OutputWriter:
    """Backend writer interface (reference: data_storage.rs:660).

    `write_batch` receives all deltas of one closed engine time, in order.

    Transactional contract (exactly-once sinks): a writer that sets
    `transactional = True` and is bound to a `SinkCommitLog` participates
    in the engine's snapshot-aligned two-phase commit.  Output for epoch
    T becomes durable only when the operator-snapshot frontier reaches
    >= T; the streaming driver drives the protocol around each snapshot:

        begin_epoch(T)   before the events of epoch T arrive
        prepare(F)       BEFORE the snapshot manifest is written —
                         durably stage everything <= F
        commit(F)        AFTER the manifest — idempotent finalize
        recover(M)       at (re)start / rollback — discard everything
                         past the restore frontier M (M = -1 on a full
                         replay) and re-run any unfinished finalize

    All defaults are no-ops so existing writers are unaffected.
    """

    transactional = False

    def fork(self, worker_id: int) -> "OutputWriter":
        """Per-worker instance (multi-worker runs attach each worker's
        own session; default: shared instance, as before)."""
        return self

    def bind_commit_log(self, log) -> None:
        """Receive this worker's SinkCommitLog when persistence is on."""

    def begin_epoch(self, time: int) -> None:
        pass

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        raise NotImplementedError

    def prepare(self, frontier: int) -> None:
        pass

    def commit(self, frontier: int) -> None:
        pass

    def recover(self, frontier: int) -> None:
        pass

    def committed_frontier(self) -> int:
        return -1

    def flush(self) -> None:  # called after each time
        pass

    def close(self) -> None:  # called at end of stream
        pass


def attach_writer(table, writer: OutputWriter, *, name: str | None = None) -> None:
    """Route `table`'s change stream into `writer`, batched per engine time
    (reference: ConsolidateForOutput grouping, operators/output.rs)."""
    column_names = table.column_names()

    def attach(ctx, nodes):
        from pathway_tpu.engine.engine import SubscribeNode

        engine = ctx.engine
        (node,) = nodes
        sink_name = name or type(writer).__name__
        w = writer.fork(engine.worker_id)
        if getattr(w, "transactional", False):
            pcfg = getattr(engine, "_persistence_config", None)
            if pcfg is not None and getattr(pcfg, "snapshot_interval_ms", 0) > 0:
                from pathway_tpu.persistence import SinkCommitLog

                w.bind_commit_log(
                    SinkCommitLog(
                        pcfg.backend._backend, sink_name, engine.worker_id
                    )
                )
                engine.register_txn_sink(w)
        pending: List[RowEvent] = []

        def on_change(key, row, time, is_addition):
            pending.append(
                RowEvent(
                    key=key,
                    values={c: row[c] for c in column_names},
                    time=time,
                    diff=1 if is_addition else -1,
                )
            )

        def on_time_end(time):
            w.begin_epoch(time)
            if pending:
                w.write_batch(list(pending))
                pending.clear()
            w.flush()

        def on_end():
            if pending:
                w.write_batch(list(pending))
                pending.clear()
            w.close()

        sub = SubscribeNode(
            ctx.engine,
            node,
            on_change=on_change,
            on_time_end=on_time_end,
            on_end=on_end,
            column_names=column_names,
            # freshness label: explicit sink name, else the writer class
            sink_name=sink_name,
        )
        # failover rollback (Engine.reset_for_rollback): rows buffered for
        # an epoch the rollback abandoned are regenerated by replay — drop
        # them here so they cannot double-write into the new timeline
        sub.on_rollback = pending.clear

    G.add_sink([table], attach)


def jsonable(v):
    """Engine Value -> plain JSON-serializable (reference: JsonLinesFormatter
    value conversion, data_format.rs:2059)."""
    from pathway_tpu.engine.value import Json, Pointer

    if isinstance(v, Json):
        return v.value
    if isinstance(v, Pointer):
        return repr(v)
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, datetime.datetime):
        return v.isoformat()
    if isinstance(v, datetime.timedelta):
        return v.total_seconds()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def format_json_event(event: RowEvent, *, time_name: str = "time", diff_name: str = "diff") -> str:
    obj = {k: jsonable(v) for k, v in event.values.items()}
    obj[time_name] = event.time
    obj[diff_name] = event.diff
    return json.dumps(obj)
