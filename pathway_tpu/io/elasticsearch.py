"""pw.io.elasticsearch — Elasticsearch sink (reference:
python/pathway/io/elasticsearch write:89, ElasticSearchAuth:16; Rust
Elasticsearch writer in src/connectors/data_storage.rs)."""

from __future__ import annotations

from typing import Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


class ElasticSearchAuth:
    """Auth settings holder (reference: io/elasticsearch:16)."""

    def __init__(self, kind: str, **kwargs):
        self.kind = kind
        self.kwargs = kwargs

    @classmethod
    def basic(cls, username: str, password: str):
        return cls("basic", username=username, password=password)

    @classmethod
    def apikey(cls, apikey_id: str, apikey: str):
        return cls("apikey", apikey_id=apikey_id, apikey=apikey)

    @classmethod
    def bearer(cls, bearer: str):
        return cls("bearer", bearer=bearer)

    def as_client_kwargs(self) -> dict:
        if self.kind == "basic":
            return {"basic_auth": (self.kwargs["username"], self.kwargs["password"])}
        if self.kind == "apikey":
            return {"api_key": (self.kwargs["apikey_id"], self.kwargs["apikey"])}
        if self.kind == "bearer":
            return {"bearer_auth": self.kwargs["bearer"]}
        return {}


class ElasticsearchWriter(OutputWriter):
    def __init__(self, client, index_name: str):
        self.client = client
        self.index_name = index_name

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        for ev in events:
            doc = {k: jsonable(v) for k, v in ev.values.items()}
            doc["time"] = ev.time
            doc["diff"] = ev.diff
            self.client.index(index=self.index_name, document=doc)

    def close(self) -> None:
        close = getattr(self.client, "close", None)
        if close:
            close()


def write(
    table,
    host: str,
    auth: ElasticSearchAuth | None,
    index_name: str,
    *,
    name: str | None = None,
    _client=None,
    **kwargs,
) -> None:
    """Index each change-stream delta as a document (reference:
    io/elasticsearch write:89)."""
    if _client is None:
        try:
            from elasticsearch import Elasticsearch  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.elasticsearch requires the elasticsearch package; "
                "install it or inject a client via _client"
            )
        _client = Elasticsearch(
            host, **(auth.as_client_kwargs() if auth else {})
        )
    attach_writer(table, ElasticsearchWriter(_client, index_name), name=name)
