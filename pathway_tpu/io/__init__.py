"""pw.io — connectors (reference: python/pathway/io/__init__.py).

Connector modules are populated progressively; `subscribe` and the python
ConnectorSubject are the core primitives (reference: io/_subscribe.py:16,
io/python/__init__.py:47).
"""

from __future__ import annotations

from pathway_tpu.io._subscribe import (
    OnChangeCallback,
    OnFinishCallback,
    subscribe,
)
from pathway_tpu.io.fs import CsvParserSettings
from pathway_tpu.io._synchronization import register_input_synchronization_group

from pathway_tpu.io import csv, fs, jsonlines, null, plaintext, python

__all__ = [
    "subscribe",
    "CsvParserSettings",
    "OnChangeCallback",
    "OnFinishCallback",
    "register_input_synchronization_group",
    "csv",
    "fs",
    "jsonlines",
    "null",
    "plaintext",
    "python",
]


def __getattr__(name):
    # lazily import heavier connector modules
    import importlib

    known = {
        "http",
        "kafka",
        "redpanda",
        "debezium",
        "s3",
        "minio",
        "sqlite",
        "postgres",
        "elasticsearch",
        "mongodb",
        "nats",
        "mqtt",
        "deltalake",
        "iceberg",
        "bigquery",
        "pubsub",
        "dynamodb",
        "questdb",
        "logstash",
        "slack",
        "gdrive",
        "airbyte",
        "pyfilesystem",
    }
    if name in known:
        return importlib.import_module(f"pathway_tpu.io.{name}")
    raise AttributeError(f"module pathway_tpu.io has no attribute {name!r}")
