"""pw.io.iceberg — Apache Iceberg connector (reference:
python/pathway/io/iceberg; Rust implementation
src/connectors/data_lake/iceberg.rs:1-560 — snapshot-based reads + appends
over the Iceberg v2 table spec).

Implemented natively over pyarrow.parquet with spec-shaped v2 table
metadata: `metadata/v<N>.metadata.json` carries table-uuid / schemas with
field ids / partition-specs / sort-orders / sequence numbers /
snapshot-log / metadata-log, each snapshot references a manifest LIST
which references manifest files which reference parquet data files, and
`version-hint.text` points catalogs at the current version.  Manifests
and manifest lists are spec-compliant Avro object container files with
Iceberg field-ids (written by the self-contained codec in `io/_avro.py`);
tables written by older versions with JSON manifests still read.  The
change stream carries the reference's `time`/`diff` columns.
"""

from __future__ import annotations

import io as io_mod
import json
import time as time_mod
from typing import Dict, List, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)
from pathway_tpu.io._lake_fs import (
    LakeFS,
    as_fs as _as_fs,
    read_parquet as _read_parquet,
    resolve_lake_fs,
    write_parquet as _write_parquet,
)
from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable
from pathway_tpu.io.deltalake import _coerce_delta

_META_DIR = "metadata"
_DATA_DIR = "data"

# Avro schema for manifest files (Iceberg spec §Manifests, v2 subset of
# manifest_entry with the spec's field-ids)
_MANIFEST_ENTRY_SCHEMA = {
    "type": "record",
    "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int", "field-id": 0},
        {"name": "snapshot_id", "type": ["null", "long"], "field-id": 1},
        {
            "name": "sequence_number",
            "type": ["null", "long"],
            "field-id": 3,
        },
        {
            "name": "file_sequence_number",
            "type": ["null", "long"],
            "field-id": 4,
        },
        {
            "name": "data_file",
            "field-id": 2,
            "type": {
                "type": "record",
                "name": "r2",
                "fields": [
                    {"name": "content", "type": "int", "field-id": 134},
                    {"name": "file_path", "type": "string", "field-id": 100},
                    {
                        "name": "file_format",
                        "type": "string",
                        "field-id": 101,
                    },
                    {
                        "name": "partition",
                        "field-id": 102,
                        "type": {
                            "type": "record",
                            "name": "r102",
                            "fields": [],
                        },
                    },
                    {
                        "name": "record_count",
                        "type": "long",
                        "field-id": 103,
                    },
                    {
                        "name": "file_size_in_bytes",
                        "type": "long",
                        "field-id": 104,
                    },
                ],
            },
        },
    ],
}

# Avro schema for manifest lists (Iceberg spec §Manifest Lists, v2 subset)
_MANIFEST_FILE_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "content", "type": "int", "field-id": 517},
        {"name": "sequence_number", "type": "long", "field-id": 515},
        {"name": "min_sequence_number", "type": "long", "field-id": 516},
        {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        {"name": "added_files_count", "type": "int", "field-id": 504},
        {"name": "existing_files_count", "type": "int", "field-id": 505},
        {"name": "deleted_files_count", "type": "int", "field-id": 506},
        {"name": "added_rows_count", "type": "long", "field-id": 512},
        {"name": "existing_rows_count", "type": "long", "field-id": 513},
        {"name": "deleted_rows_count", "type": "long", "field-id": 514},
    ],
}


def _load_manifest_list(fs: LakeFS, path: str) -> List[dict]:
    """Manifest-list entries from an Avro file (spec) or legacy JSON."""
    fs = _as_fs(fs)
    if path.endswith(".avro"):
        from pathway_tpu.io._avro import read_ocf

        _schema, records = read_ocf(fs.read_bytes(path))
        return records
    return json.loads(fs.read_bytes(path)).get("manifests", [])


def _load_manifest_entries(fs: LakeFS, path: str) -> List[dict]:
    """Manifest entries from an Avro file (spec) or legacy JSON."""
    fs = _as_fs(fs)
    if path.endswith(".avro"):
        from pathway_tpu.io._avro import read_ocf

        _schema, records = read_ocf(fs.read_bytes(path))
        return records
    return json.loads(fs.read_bytes(path)).get("entries", [])


def _current_metadata(fs: LakeFS):
    fs = _as_fs(fs)
    versions = sorted(
        int(f.split(".")[0][1:])
        for f in fs.listdir(_META_DIR)
        if f.endswith(".metadata.json")
    )
    if not versions:
        return None, 0
    v = versions[-1]
    return json.loads(fs.read_bytes(f"{_META_DIR}/v{v}.metadata.json")), v


def _iceberg_type(dtype) -> str:
    """pathway dtype -> Iceberg primitive type name (spec §Schemas)."""
    core = dt.unoptionalize(dtype)
    return {
        dt.INT: "long",
        dt.FLOAT: "double",
        dt.BOOL: "boolean",
        dt.STR: "string",
        dt.BYTES: "binary",
        dt.DATE_TIME_NAIVE: "timestamp",
        dt.DATE_TIME_UTC: "timestamptz",
        dt.DURATION: "long",
    }.get(core, "string")


class IcebergTableWriter(OutputWriter):
    """Appends change-stream batches as Iceberg v2 snapshots (reference:
    iceberg.rs snapshot commit path)."""

    def __init__(
        self, uri: str | LakeFS, column_names: Sequence[str], schema=None
    ):
        import pyarrow  # noqa: F401

        self.fs = _as_fs(uri)
        self.column_names = list(column_names)
        self.schema = schema
        self.fs.makedirs(_META_DIR)
        self.fs.makedirs(_DATA_DIR)
        self._counter = 0

    def _schema_fields(self) -> List[dict]:
        fields = []
        for i, name in enumerate(self.column_names, start=1):
            ftype = "string"
            if self.schema is not None and name in set(self.schema.keys()):
                ftype = _iceberg_type(self.schema[name].dtype)
            fields.append(
                {"id": i, "name": name, "required": False, "type": ftype}
            )
        n = len(self.column_names)
        fields.append(
            {"id": n + 1, "name": "time", "required": True, "type": "long"}
        )
        fields.append(
            {"id": n + 2, "name": "diff", "required": True, "type": "long"}
        )
        return fields

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        import uuid

        import pyarrow as pa

        cols: Dict[str, list] = {name: [] for name in self.column_names}
        cols["time"] = []
        cols["diff"] = []
        for ev in events:
            for name in self.column_names:
                cols[name].append(jsonable(ev.values.get(name)))
            cols["time"].append(ev.time)
            cols["diff"].append(ev.diff)
        self._counter += 1
        now_ms = int(time_mod.time() * 1000)
        fname = (
            f"{_DATA_DIR}/data-{int(time_mod.time() * 1e6)}"
            f"-{self._counter:05d}.parquet"
        )
        file_size = _write_parquet(self.fs, fname, pa.table(cols))

        meta, version = _current_metadata(self.fs)
        new_version = version + 1
        if meta is None:
            meta = {
                "format-version": 2,
                "table-uuid": str(uuid.uuid4()),
                "location": self.fs.display_uri,
                "last-sequence-number": 0,
                "last-updated-ms": now_ms,
                "last-column-id": len(self.column_names) + 2,
                "schemas": [
                    {
                        "schema-id": 0,
                        "type": "struct",
                        "fields": self._schema_fields(),
                    }
                ],
                "current-schema-id": 0,
                "partition-specs": [{"spec-id": 0, "fields": []}],
                "default-spec-id": 0,
                "last-partition-id": 999,
                "sort-orders": [{"order-id": 0, "fields": []}],
                "default-sort-order-id": 0,
                "properties": {"write.format.default": "parquet"},
                "current-snapshot-id": -1,
                "snapshots": [],
                "snapshot-log": [],
                "metadata-log": [],
            }
        seq = meta.get("last-sequence-number", 0) + 1
        snapshot_id = uuid.uuid4().int >> 65  # spec: arbitrary unique i64
        parent = meta.get("current-snapshot-id", -1)

        # manifest: one entry per data file, spec-compliant Avro with
        # field-ids (reference: iceberg.rs via iceberg-rust's writers)
        from pathway_tpu.io._avro import write_ocf

        manifest_name = f"{_META_DIR}/manifest-{snapshot_id}.avro"
        manifest_entries = [
            {
                "status": 1,  # ADDED
                "snapshot_id": snapshot_id,
                "sequence_number": seq,
                "file_sequence_number": seq,
                "data_file": {
                    "content": 0,  # DATA
                    "file_path": fname,
                    "file_format": "PARQUET",
                    "partition": {},
                    "record_count": len(events),
                    "file_size_in_bytes": file_size,
                },
            }
        ]
        sink = io_mod.BytesIO()
        write_ocf(
            sink,
            _MANIFEST_ENTRY_SCHEMA,
            manifest_entries,
            metadata={
                "format-version": "2",
                "content": "data",
                "partition-spec-id": "0",
            },
        )
        manifest_len = len(sink.getvalue())
        self.fs.write_bytes(manifest_name, sink.getvalue())

        # manifest list: the spec requires a snapshot's manifest list to
        # represent FULL table state, so carry every prior manifest
        # forward and append the new one
        prior_manifests: List[dict] = []
        cur_id = meta.get("current-snapshot-id", -1)
        for prev_snap in meta.get("snapshots", []):
            if prev_snap["snapshot-id"] == cur_id and "manifest-list" in prev_snap:
                try:
                    prior_manifests = _load_manifest_list(
                        self.fs, prev_snap["manifest-list"]
                    )
                except (OSError, FileNotFoundError):
                    prior_manifests = []
                break
        mlist_name = f"{_META_DIR}/snap-{snapshot_id}-manifest-list.avro"
        new_entry = {
            "manifest_path": manifest_name,
            "manifest_length": manifest_len,
            "partition_spec_id": 0,
            "content": 0,
            "sequence_number": seq,
            "min_sequence_number": seq,
            "added_snapshot_id": snapshot_id,
            "added_files_count": 1,
            "existing_files_count": 0,
            "deleted_files_count": 0,
            "added_rows_count": len(events),
            "existing_rows_count": 0,
            "deleted_rows_count": 0,
        }
        # legacy-JSON entries carried forward may lack newer spec fields
        prior_manifests = [
            {
                "min_sequence_number": e.get("sequence_number", 0),
                "existing_rows_count": 0,
                "deleted_rows_count": 0,
                **e,
            }
            for e in prior_manifests
        ]
        mlist_sink = io_mod.BytesIO()
        write_ocf(
            mlist_sink,
            _MANIFEST_FILE_SCHEMA,
            prior_manifests + [new_entry],
            metadata={
                "format-version": "2",
                "snapshot-id": str(snapshot_id),
                "sequence-number": str(seq),
                "parent-snapshot-id": str(parent),
            },
        )
        self.fs.write_bytes(mlist_name, mlist_sink.getvalue())

        meta["snapshots"].append(
            {
                "snapshot-id": snapshot_id,
                "parent-snapshot-id": parent if parent != -1 else None,
                "sequence-number": seq,
                "timestamp-ms": now_ms,
                "manifest-list": mlist_name,
                "summary": {
                    "operation": "append",
                    "added-data-files": "1",
                    "added-records": str(len(events)),
                },
                "schema-id": 0,
            }
        )
        meta["current-snapshot-id"] = snapshot_id
        meta["last-sequence-number"] = seq
        meta["last-updated-ms"] = now_ms
        meta.setdefault("snapshot-log", []).append(
            {"snapshot-id": snapshot_id, "timestamp-ms": now_ms}
        )
        if version:
            meta.setdefault("metadata-log", []).append(
                {
                    "metadata-file": f"{_META_DIR}/v{version}.metadata.json",
                    "timestamp-ms": now_ms,
                }
            )
        self.fs.write_bytes(
            f"{_META_DIR}/v{new_version}.metadata.json",
            json.dumps(meta).encode("utf-8"),
        )
        # catalogs resolve the current version through the hint file
        self.fs.write_bytes(
            f"{_META_DIR}/version-hint.text",
            str(new_version).encode("ascii"),
        )


def _resolve_table_fs(
    catalog_uri,
    warehouse,
    namespace,
    table_name,
    s3_connection_settings=None,
    _object_client=None,
) -> LakeFS:
    """A table lives under ``warehouse/<namespace...>/<table_name>``.

    The reference's ``catalog_uri`` names an Iceberg REST catalog
    (io/iceberg/__init__.py:52); this implementation speaks the
    warehouse layout directly (local or object store) and refuses to
    silently treat a catalog URL as a directory — pass ``warehouse=``."""
    if warehouse is None:
        if catalog_uri is None:
            raise ValueError(
                "pw.io.iceberg needs warehouse=<path or s3:// uri>"
            )
        if catalog_uri.startswith(("http://", "https://", "thrift://")):
            raise ValueError(
                "pw.io.iceberg needs warehouse=<path or s3:// uri>: this "
                "implementation maintains Iceberg v2 tables directly in a "
                "warehouse (local or object store) and does not speak the "
                f"REST catalog protocol ({catalog_uri!r} is a catalog "
                "URL, which would otherwise be silently treated as a "
                "directory)"
            )
        # path-like catalog_uri: historical alias for warehouse
        warehouse = catalog_uri
    uri = warehouse
    parts = [p for p in (namespace or []) if p]
    if table_name:
        parts.append(table_name)
    if parts:
        uri = uri.rstrip("/") + "/" + "/".join(parts)
    return resolve_lake_fs(
        uri,
        s3_connection_settings=s3_connection_settings,
        _object_client=_object_client,
    )


def write(
    table,
    catalog_uri: str | None = None,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    *,
    warehouse: str | None = None,
    min_commit_frequency: int | None = 60_000,
    s3_connection_settings=None,
    name: str | None = None,
    _object_client=None,
    **kwargs,
) -> None:
    """Append the change stream to an Iceberg table (reference: io/iceberg
    write)."""
    fs = _resolve_table_fs(
        catalog_uri,
        warehouse,
        namespace,
        table_name,
        s3_connection_settings,
        _object_client,
    )
    attach_writer(
        table,
        IcebergTableWriter(
            fs, table.column_names(), schema=getattr(table, "schema", None)
        ),
        name=name,
    )


class _IcebergSubject(ConnectorSubjectBase):
    def __init__(self, uri, schema, mode, refresh_interval):
        super().__init__()
        self.fs = _as_fs(uri)
        self.schema = schema
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._seen_snapshots: set[int] = set()
        # manifest lists carry full table state; incremental reads must
        # dedupe at the data-file level
        self._seen_files: set[str] = set()

    def _poll(self) -> bool:
        meta, _ = _current_metadata(self.fs)
        if meta is None:
            return False
        names = list(self.schema.keys())
        changed = False
        for snap in meta.get("snapshots", []):
            sid = snap["snapshot-id"]
            if sid in self._seen_snapshots:
                continue
            self._seen_snapshots.add(sid)
            data_files: List[str] = []
            if "manifest-list" in snap:
                mlist = _load_manifest_list(self.fs, snap["manifest-list"])
                for mf in mlist:
                    entries = _load_manifest_entries(
                        self.fs, mf["manifest_path"]
                    )
                    for entry in entries:
                        if entry.get("status") != 2:  # not DELETED
                            path = entry["data_file"]["file_path"]
                            if path not in self._seen_files:
                                self._seen_files.add(path)
                                data_files.append(path)
            else:  # pre-spec layout written by older versions
                manifest = json.loads(self.fs.read_bytes(snap["manifest"]))
                data_files = [
                    f
                    for f in manifest.get("data_files", [])
                    if f not in self._seen_files
                ]
                self._seen_files.update(data_files)
            for fname in data_files:
                for rec in _read_parquet(self.fs, fname).to_pylist():
                    row = {
                        k: _coerce_delta(rec.get(k), self.schema[k].dtype)
                        for k in names
                        if k in rec
                    }
                    if rec.get("diff", 1) > 0:
                        self.next(**row)
                    else:
                        self._remove(row)
                changed = True
        return changed

    def run(self) -> None:
        while True:
            if self._poll():
                self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        return {
            "seen": sorted(self._seen_snapshots),
            "seen_files": sorted(self._seen_files),
        }

    def _restore_persisted_state(self, state) -> None:
        if state:
            self._seen_snapshots.update(state.get("seen", []))
            self._seen_files.update(state.get("seen_files", []))


def read(
    catalog_uri: str | None = None,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    schema=None,
    *,
    warehouse: str | None = None,
    mode: str = "streaming",
    refresh_interval: float = 0.5,
    s3_connection_settings=None,
    name: str | None = None,
    _object_client=None,
    **kwargs,
):
    """Read an Iceberg table as a (streaming) table (reference: io/iceberg
    read)."""
    fs = _resolve_table_fs(
        catalog_uri,
        warehouse,
        namespace,
        table_name,
        s3_connection_settings,
        _object_client,
    )

    def factory():
        return _IcebergSubject(fs, schema, mode, refresh_interval)

    return connector_table(schema, factory, mode=mode, name=name)
