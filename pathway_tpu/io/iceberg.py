"""pw.io.iceberg — Apache Iceberg connector (reference:
python/pathway/io/iceberg; Rust implementation
src/connectors/data_lake/iceberg.rs — snapshot-based reads + appends).

Implemented natively over pyarrow.parquet with a simplified Iceberg-style
metadata layout: `metadata/v<N>.metadata.json` holds the schema and the
list of snapshots, each snapshot referencing a manifest (JSON list of data
files). Round-trips with itself; the change stream carries the reference's
`time`/`diff` columns.
"""

from __future__ import annotations

import json
import os
import time as time_mod
from typing import Dict, List, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)
from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable
from pathway_tpu.io.deltalake import _coerce_delta

_META_DIR = "metadata"
_DATA_DIR = "data"


def _current_metadata(uri: str):
    meta_dir = os.path.join(uri, _META_DIR)
    if not os.path.isdir(meta_dir):
        return None, 0
    versions = sorted(
        int(f.split(".")[0][1:])
        for f in os.listdir(meta_dir)
        if f.endswith(".metadata.json")
    )
    if not versions:
        return None, 0
    v = versions[-1]
    with open(os.path.join(meta_dir, f"v{v}.metadata.json")) as fh:
        return json.load(fh), v


class IcebergTableWriter(OutputWriter):
    def __init__(self, uri: str, column_names: Sequence[str]):
        import pyarrow  # noqa: F401

        self.uri = uri
        self.column_names = list(column_names)
        os.makedirs(os.path.join(uri, _META_DIR), exist_ok=True)
        os.makedirs(os.path.join(uri, _DATA_DIR), exist_ok=True)
        self._counter = 0

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols: Dict[str, list] = {name: [] for name in self.column_names}
        cols["time"] = []
        cols["diff"] = []
        for ev in events:
            for name in self.column_names:
                cols[name].append(jsonable(ev.values.get(name)))
            cols["time"].append(ev.time)
            cols["diff"].append(ev.diff)
        self._counter += 1
        fname = os.path.join(
            _DATA_DIR, f"data-{int(time_mod.time() * 1e6)}-{self._counter:05d}.parquet"
        )
        pq.write_table(pa.table(cols), os.path.join(self.uri, fname))

        meta, version = _current_metadata(self.uri)
        if meta is None:
            meta = {"format-version": 2, "snapshots": []}
        manifest_name = os.path.join(_META_DIR, f"manifest-{version + 1}.json")
        with open(os.path.join(self.uri, manifest_name), "w") as fh:
            json.dump({"data_files": [fname]}, fh)
        meta["snapshots"].append(
            {
                "snapshot-id": version + 1,
                "timestamp-ms": int(time_mod.time() * 1000),
                "manifest": manifest_name,
            }
        )
        meta["current-snapshot-id"] = version + 1
        path = os.path.join(self.uri, _META_DIR, f"v{version + 1}.metadata.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(meta, fh)
        os.rename(tmp, path)


def write(
    table,
    catalog_uri: str | None = None,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    *,
    warehouse: str | None = None,
    min_commit_frequency: int | None = 60_000,
    name: str | None = None,
    **kwargs,
) -> None:
    """Append the change stream to an Iceberg table (reference: io/iceberg
    write)."""
    uri = warehouse or catalog_uri
    if namespace or table_name:
        uri = os.path.join(uri, *(namespace or []), table_name or "")
    attach_writer(table, IcebergTableWriter(uri, table.column_names()), name=name)


class _IcebergSubject(ConnectorSubjectBase):
    def __init__(self, uri, schema, mode, refresh_interval):
        super().__init__()
        self.uri = uri
        self.schema = schema
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._seen_snapshots: set[int] = set()

    def _poll(self) -> bool:
        import pyarrow.parquet as pq

        meta, _ = _current_metadata(self.uri)
        if meta is None:
            return False
        names = list(self.schema.keys())
        changed = False
        for snap in meta.get("snapshots", []):
            sid = snap["snapshot-id"]
            if sid in self._seen_snapshots:
                continue
            self._seen_snapshots.add(sid)
            with open(os.path.join(self.uri, snap["manifest"])) as fh:
                manifest = json.load(fh)
            for fname in manifest.get("data_files", []):
                for rec in pq.read_table(os.path.join(self.uri, fname)).to_pylist():
                    row = {
                        k: _coerce_delta(rec.get(k), self.schema[k].dtype)
                        for k in names
                        if k in rec
                    }
                    if rec.get("diff", 1) > 0:
                        self.next(**row)
                    else:
                        self._remove(row)
                changed = True
        return changed

    def run(self) -> None:
        while True:
            if self._poll():
                self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        return {"seen": sorted(self._seen_snapshots)}

    def _restore_persisted_state(self, state) -> None:
        if state:
            self._seen_snapshots.update(state.get("seen", []))


def read(
    catalog_uri: str | None = None,
    namespace: list[str] | None = None,
    table_name: str | None = None,
    schema=None,
    *,
    warehouse: str | None = None,
    mode: str = "streaming",
    refresh_interval: float = 0.5,
    name: str | None = None,
    **kwargs,
):
    """Read an Iceberg table as a (streaming) table (reference: io/iceberg
    read)."""
    uri = warehouse or catalog_uri
    if namespace or table_name:
        uri = os.path.join(uri, *(namespace or []), table_name or "")

    def factory():
        return _IcebergSubject(uri, schema, mode, refresh_interval)

    return connector_table(schema, factory, mode=mode, name=name)
