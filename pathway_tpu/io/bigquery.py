"""pw.io.bigquery — BigQuery sink (reference: python/pathway/io/bigquery
write:57, buffered via _OutputBuffer:15 — streaming inserts of change-stream
rows with time/diff columns)."""

from __future__ import annotations

from typing import Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


class BigQueryWriter(OutputWriter):
    def __init__(self, client, table_ref: str, max_batch_size: int | None = None):
        self.client = client
        self.table_ref = table_ref
        self.max_batch_size = max_batch_size

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        rows = []
        for ev in events:
            obj = {k: jsonable(v) for k, v in ev.values.items()}
            obj["time"] = ev.time
            obj["diff"] = ev.diff
            rows.append(obj)
        step = self.max_batch_size or len(rows) or 1
        for i in range(0, len(rows), step):
            errors = self.client.insert_rows_json(
                self.table_ref, rows[i : i + step]
            )
            if errors:
                raise RuntimeError(f"BigQuery insert errors: {errors}")


def write(
    table,
    dataset_name: str,
    table_name: str,
    service_user_credentials_file: str | None = None,
    *,
    max_batch_size: int | None = None,
    name: str | None = None,
    _client=None,
    **kwargs,
) -> None:
    """Stream change-stream rows into a BigQuery table (reference:
    io/bigquery write:57)."""
    if _client is None:
        try:
            from google.cloud import bigquery  # type: ignore
            from google.oauth2.service_account import Credentials  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.bigquery requires google-cloud-bigquery; install it or "
                "inject a client via _client"
            )
        creds = (
            Credentials.from_service_account_file(service_user_credentials_file)
            if service_user_credentials_file
            else None
        )
        _client = bigquery.Client(credentials=creds)
    attach_writer(
        table,
        BigQueryWriter(
            _client, f"{dataset_name}.{table_name}", max_batch_size=max_batch_size
        ),
        name=name,
    )
