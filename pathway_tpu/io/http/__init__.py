"""pw.io.http — REST ingress/egress (reference: python/pathway/io/http).

`rest_connector` turns HTTP requests into stream rows and completes the
response from a result table's change stream (reference:
io/http/_server.py:482 PathwayWebserver, :696 rest_connector).
"""

from pathway_tpu.io.http._server import (
    EndpointDocumentation,
    PathwayWebserver,
    rest_connector,
)
from pathway_tpu.io.http._client import read, write

__all__ = [
    "PathwayWebserver",
    "EndpointDocumentation",
    "rest_connector",
    "read",
    "write",
]
