"""HTTP client connector: poll/stream an HTTP endpoint into a table, and
POST table changes out (reference: python/pathway/io/http/__init__.py client
read/write, _streaming.py)."""

from __future__ import annotations

import json as json_mod
import time as time_mod
import urllib.request
from typing import Any, Callable, Dict, Optional, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class _HttpSubject(ConnectorSubjectBase):
    def __init__(self, url, schema, method, headers, payload, refresh_interval, mode):
        super().__init__()
        self.url = url
        self.schema = schema
        self.method = method
        self.headers = headers or {}
        self.payload = payload
        self.refresh_interval = refresh_interval
        self.mode = mode

    def _fetch(self):
        data = None
        if self.payload is not None:
            data = json_mod.dumps(self.payload).encode()
        req = urllib.request.Request(
            self.url, data=data, method=self.method, headers=self.headers
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = resp.read()
        try:
            parsed = json_mod.loads(body)
        except json_mod.JSONDecodeError:
            parsed = body.decode(errors="replace")
        names = set(self.schema.keys())
        if isinstance(parsed, list):
            for obj in parsed:
                if isinstance(obj, dict):
                    self.next(**{k: v for k, v in obj.items() if k in names})
                else:
                    self.next(data=obj)
        elif isinstance(parsed, dict):
            self.next(**{k: v for k, v in parsed.items() if k in names})
        else:
            self.next(data=parsed)

    def run(self) -> None:
        from pathway_tpu.internals.backoff import Backoff

        # full jitter + per-worker seed: workers polling the same origin
        # decorrelate their retries; max_elapsed caps the total backoff a
        # dead endpoint can accumulate before the reader fails loudly
        backoff = Backoff(
            base=0.5,
            cap=30.0,
            full_jitter=True,
            max_elapsed=120.0,
            seed=self._worker_id,
        )
        while True:
            try:
                self._fetch()
            except Exception:  # noqa: BLE001 — network/HTTP errors
                if backoff.exhausted():
                    self.report_retry(0.0)
                    raise
                delay = backoff.next_delay()
                self.report_retry(delay)
                time_mod.sleep(delay)
                continue
            backoff.reset()
            self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)


def read(
    url: str,
    *,
    schema=None,
    method: str = "GET",
    headers: Dict[str, str] | None = None,
    payload=None,
    mode: str = "streaming",
    refresh_interval: float = 5.0,
    format: str = "json",
    **kwargs,
):
    if schema is None:
        schema = schema_from_columns(
            {"data": ColumnSchema(name="data", dtype=dt.ANY)},
            name="HttpSchema",
        )
    return connector_table(
        schema,
        lambda: _HttpSubject(
            url, schema, method, headers, payload, refresh_interval, mode
        ),
        mode=mode,
    )


def write(
    table,
    url: str,
    *,
    method: str = "POST",
    headers: Dict[str, str] | None = None,
    format: str = "json",
    **kwargs,
) -> None:
    """POST each change as JSON (reference: io/http write)."""
    column_names = table.column_names()
    headers = dict(headers or {})
    headers.setdefault("Content-Type", "application/json")

    def attach(ctx, nodes):
        from pathway_tpu.engine.engine import SubscribeNode
        from pathway_tpu.io.http._server import _jsonable_payload

        (node,) = nodes

        def on_change(key, row, time, is_addition):
            obj = {c: _jsonable_payload(row[c]) for c in column_names}
            obj["time"] = time
            obj["diff"] = 1 if is_addition else -1
            req = urllib.request.Request(
                url,
                data=json_mod.dumps(obj).encode(),
                method=method,
                headers=headers,
            )
            try:
                urllib.request.urlopen(req, timeout=30).read()
            except Exception as exc:  # noqa: BLE001
                import logging

                logging.getLogger("pathway_tpu").warning(
                    "http write failed: %s", exc
                )

        SubscribeNode(
            ctx.engine, node, on_change=on_change, column_names=column_names
        )

    G.add_sink([table], attach)
