"""REST ingress: PathwayWebserver + rest_connector.

TPU-native rebuild of the reference HTTP server connector (reference:
python/pathway/io/http/_server.py — PathwayWebserver:482 (aiohttp + CORS +
OpenAPI), rest_connector:696: request→row, response via subscribe). Each
request becomes a stream row keyed by a fresh pointer; the response completes
when the result table emits a row with that key.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from pathway_tpu.engine.value import Json, Pointer, ref_scalar
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import (
    ColumnSchema,
    Schema,
    schema_from_columns,
)
from pathway_tpu.internals import qtrace as _qtrace
from pathway_tpu.internals import serving as _serving
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)

_request_ids = itertools.count(1)


@dataclass
class EndpointDocumentation:
    """OpenAPI-ish endpoint metadata (reference: _server.py
    EndpointDocumentation:127)."""

    summary: str | None = None
    description: str | None = None
    tags: Sequence[str] = ()
    method_types: Sequence[str] | None = None


class PathwayWebserver:
    """One aiohttp server shared by many rest_connector routes (reference:
    _server.py PathwayWebserver:482)."""

    def __init__(self, host: str, port: int, with_cors: bool = False):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        # route -> (methods, handler, documentation)
        self._routes: Dict[str, tuple] = {}
        self._pending: Dict[Pointer, asyncio.Future] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._start_lock = threading.Lock()

    def register_route(
        self,
        route: str,
        methods: Sequence[str],
        handler: Callable,
        documentation: EndpointDocumentation | None = None,
    ) -> None:
        self._routes[route] = (
            tuple(m.upper() for m in methods),
            handler,
            documentation,
        )

    def openapi_description_json(self) -> dict:
        paths: dict = {}
        for route, (methods, _h, doc) in self._routes.items():
            entry = {}
            for m in methods:
                entry[m.lower()] = {
                    "summary": getattr(doc, "summary", None) or route,
                    "description": getattr(doc, "description", None) or "",
                    "responses": {"200": {"description": "OK"}},
                }
            paths[route] = entry
        return {
            "openapi": "3.0.3",
            "info": {"title": "pathway_tpu app", "version": "1.0"},
            "paths": paths,
        }

    def _ensure_started(self) -> None:
        with self._start_lock:
            if self._started.is_set():
                return

            def run_loop():
                loop = asyncio.new_event_loop()
                asyncio.set_event_loop(loop)
                self._loop = loop
                loop.run_until_complete(self._serve())
                loop.run_forever()

            t = threading.Thread(
                target=run_loop, daemon=True, name=f"webserver:{self.port}"
            )
            t.start()
            self._started.wait(timeout=10)

    async def _serve(self) -> None:
        from aiohttp import web

        app = web.Application()

        async def dispatch(request: "web.Request"):
            if request.path == "/_schema" or request.path == "/openapi.json":
                return web.json_response(self.openapi_description_json())
            entry = self._routes.get(request.path)
            if entry is None:
                return web.json_response({"error": "not found"}, status=404)
            methods, handler, _doc = entry
            if request.method == "OPTIONS" and self.with_cors:
                return self._with_cors_headers(web.Response(status=204))
            if request.method not in methods:
                return web.json_response(
                    {"error": "method not allowed"}, status=405
                )
            if request.method in ("GET", "DELETE"):
                payload = dict(request.rel_url.query)
            else:
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    return web.json_response(
                        {"error": "invalid json"}, status=400
                    )
                if not isinstance(payload, dict):
                    payload = {"value": payload}
            try:
                result = await handler(payload, request)
            except _RequestThrottled as exc:
                import math

                resp = web.json_response(
                    {"error": str(exc), "reason": exc.reason},
                    status=429,
                    headers={
                        "Retry-After": str(
                            max(1, math.ceil(exc.retry_after))
                        )
                    },
                )
                if self.with_cors:
                    resp = self._with_cors_headers(resp)
                return resp
            except _RequestRejected as exc:
                return web.json_response({"error": str(exc)}, status=400)
            resp = web.json_response(result)
            if self.with_cors:
                resp = self._with_cors_headers(resp)
            return resp

        app.router.add_route("*", "/{tail:.*}", dispatch)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._started.set()

    def _with_cors_headers(self, resp):
        resp.headers["Access-Control-Allow-Origin"] = "*"
        resp.headers["Access-Control-Allow-Methods"] = "*"
        resp.headers["Access-Control-Allow-Headers"] = "*"
        return resp

    # -- response plumbing ------------------------------------------------
    def _register_pending(self, key: Pointer) -> asyncio.Future:
        fut = self._loop.create_future()
        self._pending[key] = fut
        return fut

    def complete(self, key: Pointer, payload: Any) -> None:
        if _qtrace.ENABLED:
            _qtrace.tracker().mark(str(key), "emitted")
        fut = self._pending.pop(key, None)
        if fut is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(payload)
            )


class _RequestRejected(Exception):
    pass


class _RequestThrottled(Exception):
    """Admission-control shed: becomes a 429 with a Retry-After header —
    the request never touched the engine or the device."""

    def __init__(self, retry_after: float, reason: str):
        super().__init__(f"overloaded ({reason})")
        self.retry_after = retry_after
        self.reason = reason


class _RestSubject(ConnectorSubjectBase):
    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        methods: Sequence[str],
        schema,
        delete_completed_queries: bool,
        request_validator: Callable | None,
        documentation: EndpointDocumentation | None,
    ):
        super().__init__()
        self.webserver = webserver
        self.route = route
        self.methods = methods
        self.schema = schema
        self.delete_completed_queries = delete_completed_queries
        self.request_validator = request_validator
        self.documentation = documentation
        self._payloads: Dict[Pointer, dict] = {}
        # next()/commit() are called from the aiohttp loop (per-query
        # path, delete-completed), and from the serving batcher's flush
        # thread — one lock keeps each commit an atomic engine batch
        self._emit_lock = threading.Lock()

    def run(self) -> None:
        names = list(self.schema.keys())
        dtypes = self.schema.dtypes()
        defaults = self.schema.default_values()

        async def handler(payload: dict, request):
            # admission first: shedding must cost less than anything it
            # sheds (no validation, no engine row, no device work)
            tier = _serving.tier() if _serving.ENABLED else None
            admitted = False
            # resolved once, whether or not a tier admits: the tenant
            # rides the qtrace span into batched dispatch so exemplars,
            # digests, and the cost ledger can attribute per tenant
            tenant = (
                request.headers.get("X-Tenant", "default")
                if request is not None
                else "default"
            )
            if tier is not None:
                verdict = tier.admission.admit(tenant)
                if verdict is not None:
                    retry_after, reason = verdict
                    raise _RequestThrottled(retry_after, reason)
                admitted = True
            try:
                if self.request_validator is not None:
                    try:
                        validation = self.request_validator(payload)
                        if validation is not None and validation is not True:
                            raise _RequestRejected(str(validation))
                    except _RequestRejected:
                        raise
                    except Exception as exc:  # noqa: BLE001
                        raise _RequestRejected(str(exc)) from exc
                key = ref_scalar("rest", self.route, next(_request_ids))
                if _qtrace.ENABLED:
                    _qtrace.tracker().begin(
                        str(key), route=self.route, key=key, tenant=tenant
                    )
                row = {}
                for name in names:
                    if name in payload:
                        row[name] = _coerce(payload[name], dtypes[name])
                    elif name in defaults:
                        row[name] = defaults[name]
                    else:
                        row[name] = None
                fut = self.webserver._register_pending(key)
                self._payloads[key] = row
                if tier is not None and tier.window_ms > 0:
                    # park on the micro-batcher: concurrent requests
                    # coalesce under ONE commit → one engine batch →
                    # one fused device dispatch for the whole flush
                    tier.batcher(self.route, self._flush_batch).submit(
                        (key, row)
                    )
                else:
                    with self._emit_lock:
                        self.next(**row, _pw_key=key)
                        self.commit()
                    if _qtrace.ENABLED:
                        _qtrace.tracker().mark(str(key), "enqueued")
                result = await fut
                if _qtrace.ENABLED:
                    _qtrace.tracker().finish(str(key))
                if self.delete_completed_queries:
                    old = self._payloads.pop(key, None)
                    if old is not None:
                        with self._emit_lock:
                            self._remove({**old, "_pw_key": key})
                            self.commit()
                return result
            finally:
                if admitted:
                    tier.admission.release()

        self.webserver.register_route(
            self.route, self.methods, handler, self.documentation
        )
        self.webserver._ensure_started()
        # block forever: requests arrive via the aiohttp loop
        threading.Event().wait()

    def _flush_batch(self, items) -> None:
        """Serving-batcher flush: push every parked (key, row) and commit
        ONCE — the engine sees one batch, the index one fused dispatch.
        Runs on the batcher thread."""
        with self._emit_lock:
            for key, row in items:
                self.next(**row, _pw_key=key)
            self.commit()
        if _qtrace.ENABLED:
            keys = [key for key, _row in items]
            tq = _qtrace.tracker()
            tq.mark_keys(keys, "enqueued")
            tq.note_batch_occupancy(keys, len(items))


def _coerce(value, dtype: dt.DType):
    core = dt.unoptionalize(dtype)
    if core is dt.JSON:
        return Json(value)
    if core is dt.FLOAT and isinstance(value, int):
        return float(value)
    if core is dt.INT and isinstance(value, str) and value.isdigit():
        return int(value)
    if isinstance(value, (dict, list)):
        return Json(value)
    return value


def rest_connector(
    host: str | None = None,
    port: int | None = None,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema=None,
    methods: Sequence[str] = ("POST",),
    autocommit_duration_ms: int | None = 1500,
    keep_queries: bool | None = None,
    delete_completed_queries: bool | None = None,
    request_validator: Callable | None = None,
    documentation: EndpointDocumentation | None = None,
):
    """HTTP requests as a stream + a response writer (reference:
    io/http/_server.py rest_connector:696). Returns (queries, response_writer);
    call response_writer(result_table) with a table keyed like `queries`
    whose `result` column is the response payload."""
    if webserver is None:
        if host is None or port is None:
            raise ValueError("provide either webserver= or host=+port=")
        webserver = PathwayWebserver(host, port)
    if delete_completed_queries is None:
        delete_completed_queries = not keep_queries if keep_queries is not None else True
    if schema is None:
        schema = schema_from_columns(
            {"query": ColumnSchema(name="query", dtype=dt.JSON)},
            name="RestSchema",
        )

    subject_holder = []

    def factory():
        subject = _RestSubject(
            webserver,
            route,
            methods,
            schema,
            delete_completed_queries,
            request_validator,
            documentation,
        )
        subject_holder.append(subject)
        return subject

    queries = connector_table(
        schema, factory, mode="streaming", name=f"rest:{route}", exclusive=True
    )

    def response_writer(result_table, **kwargs) -> None:
        from pathway_tpu.io._subscribe import subscribe

        column_names = result_table.column_names()

        def on_change(key, row, time, is_addition):
            if not is_addition:
                return
            if "result" in row:
                payload = _jsonable_payload(row["result"])
            else:
                payload = {c: _jsonable_payload(row[c]) for c in column_names}
            webserver.complete(key, payload)

        # gather results to the worker running the webserver — only that
        # process holds the pending response futures
        subscribe(result_table, on_change=on_change, on_worker=0)

    return queries, response_writer


def _jsonable_payload(v):
    import datetime

    import numpy as np

    if isinstance(v, Json):
        return v.value
    if isinstance(v, Pointer):
        return repr(v)
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.generic,)):
        return v.item()
    if isinstance(v, datetime.datetime):
        return v.isoformat()
    if isinstance(v, datetime.timedelta):
        return v.total_seconds()
    if isinstance(v, (list, tuple)):
        return [_jsonable_payload(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable_payload(x) for k, x in v.items()}
    from pathway_tpu.engine.value import Error

    if isinstance(v, Error):
        return None
    return v
