"""pw.io.mqtt — MQTT connector (reference: python/pathway/io/mqtt read:22,
write:167; Rust side rumqttc in src/connectors/data_storage.rs).

The paho-mqtt client is optional/gated; tests inject `_client_factory`.
"""

from __future__ import annotations

import queue as queue_mod

from pathway_tpu.io import _mq


class _PahoClient(_mq.MessageQueueClient):
    def __init__(self, uri: str, topic: str, *, for_read: bool, qos: int = 1):
        try:
            import paho.mqtt.client as paho  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.mqtt requires the paho-mqtt package; install it or "
                "inject a client via _client_factory"
            )
        from urllib.parse import urlparse

        self.topic = topic
        self.qos = qos
        self._messages: queue_mod.SimpleQueue = queue_mod.SimpleQueue()
        parsed = urlparse(uri if "//" in uri else f"mqtt://{uri}")
        self._client = paho.Client()
        if parsed.username:
            self._client.username_pw_set(parsed.username, parsed.password)
        self._client.connect(parsed.hostname or "localhost", parsed.port or 1883)
        if for_read:
            def on_message(client, userdata, msg):
                self._messages.put((None, msg.payload, {"topic": msg.topic}))

            self._client.on_message = on_message
            self._client.subscribe(topic, qos=qos)
        self._client.loop_start()

    def poll(self, timeout: float):
        out = []
        try:
            out.append(self._messages.get(timeout=timeout))
            while True:
                out.append(self._messages.get_nowait())
        except queue_mod.Empty:
            pass
        return out

    def produce(self, topic, key, payload):
        self._client.publish(topic, payload, qos=self.qos)

    def close(self):
        self._client.loop_stop()
        self._client.disconnect()


def read(
    uri: str,
    topic: str,
    *,
    schema=None,
    format: str = "raw",
    mode: str = "streaming",
    qos: int = 1,
    name: str | None = None,
    _client_factory=None,
    **kwargs,
):
    """Read an MQTT topic as a streaming table (reference: io/mqtt read:22)."""
    if _client_factory is None:

        def _client_factory():
            return _PahoClient(uri, topic, for_read=True, qos=qos)

    return _mq.mq_read(
        _client_factory, schema=schema, format=format, mode=mode, name=name
    )


def write(
    table,
    uri: str,
    topic: str,
    *,
    format: str = "json",
    qos: int = 1,
    name: str | None = None,
    _client=None,
    **kwargs,
) -> None:
    """Publish the table's change stream to an MQTT topic (reference:
    io/mqtt write:167)."""
    if _client is None:
        _client = _PahoClient(uri, topic, for_read=False, qos=qos)
    _mq.mq_write(table, _client, topic, format=format, name=name)
