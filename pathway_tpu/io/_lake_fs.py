"""Storage backend abstraction for the data-lake connectors.

The reference opens Delta/Iceberg tables over local disk, S3, or Azure via
storage options (reference: src/connectors/data_lake/delta.rs:215,273 —
`register_handlers`/storage options resolution). Here the same role is
played by a small filesystem interface: the lake modules speak
root-relative POSIX paths and every byte goes through a `LakeFS`, so a
table at ``s3://bucket/prefix`` uses the identical commit protocol as one
at ``/data/table``.

Object stores have no atomic rename; single-writer-per-table is assumed
(the reference's delta-rs makes the same assumption for S3 without a
locking client).
"""

from __future__ import annotations

import os
from typing import List


class LakeFS:
    """Minimal filesystem surface the lake formats need. Paths are
    POSIX-style and relative to the table root."""

    display_uri: str

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        """Atomically publish `data` at `path` (tmp+rename locally,
        single put on object stores)."""
        raise NotImplementedError

    def listdir(self, dirpath: str) -> List[str]:
        """Immediate child names of a directory; [] when absent."""
        raise NotImplementedError

    def makedirs(self, dirpath: str) -> None:
        raise NotImplementedError

    def mtime(self, path: str) -> float | None:
        """Modification time, or None when the backend cannot provide one
        (object stores) — callers must treat None as 'unknown', never as
        epoch 0."""
        raise NotImplementedError


class LocalLakeFS(LakeFS):
    def __init__(self, root: str):
        self.root = root
        self.display_uri = os.path.abspath(root)

    def _p(self, path: str) -> str:
        return os.path.join(self.root, *path.split("/")) if path else self.root

    def read_bytes(self, path: str) -> bytes:
        with open(self._p(path), "rb") as fh:
            return fh.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        full = self._p(path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.rename(tmp, full)

    def listdir(self, dirpath: str) -> List[str]:
        full = self._p(dirpath)
        if not os.path.isdir(full):
            return []
        return os.listdir(full)

    def makedirs(self, dirpath: str) -> None:
        os.makedirs(self._p(dirpath), exist_ok=True)

    def mtime(self, path: str) -> float | None:
        try:
            return os.path.getmtime(self._p(path))
        except OSError:
            return None  # unknown, NOT epoch 0


class ObjectLakeFS(LakeFS):
    """Lake over any object client with put/get/list (boto3 S3, Azure
    blobs, or an injected in-memory fake — the same client interface the
    persistence layer's ObjectStoreBackend uses)."""

    def __init__(self, client, prefix: str, display_uri: str):
        self.client = client
        self.prefix = prefix.strip("/")
        self.display_uri = display_uri

    def _k(self, path: str) -> str:
        return f"{self.prefix}/{path}" if self.prefix else path

    def read_bytes(self, path: str) -> bytes:
        data = self.client.get(self._k(path))
        if data is None:
            raise FileNotFoundError(self._k(path))
        return data

    def write_bytes(self, path: str, data: bytes) -> None:
        self.client.put(self._k(path), data)

    def listdir(self, dirpath: str) -> List[str]:
        prefix = self._k(dirpath).rstrip("/") + "/"
        names = set()
        for key in self.client.list(prefix):
            rest = key[len(prefix):]
            if rest:
                names.add(rest.split("/", 1)[0])
        return sorted(names)

    def makedirs(self, dirpath: str) -> None:
        pass  # object stores have no directories

    def mtime(self, path: str) -> float | None:
        return None  # commitInfo timestamps are authoritative on stores


def _split_bucket_uri(uri: str, scheme: str) -> tuple[str, str]:
    rest = uri[len(scheme):]
    bucket, _, prefix = rest.partition("/")
    if not bucket:
        raise ValueError(f"{uri!r}: missing bucket/container name")
    return bucket, prefix.strip("/")


def resolve_lake_fs(
    uri: str,
    *,
    s3_connection_settings=None,
    _object_client=None,
) -> LakeFS:
    """Map a table URI to a backend: ``s3://`` / ``az://`` to an object
    store (credentials via `s3_connection_settings`, the io.s3 settings
    object; `_object_client` injects a ready client, used by tests),
    anything else to the local filesystem."""
    if uri.startswith("s3://"):
        bucket, prefix = _split_bucket_uri(uri, "s3://")
        if _object_client is None:
            kwargs = (
                s3_connection_settings.boto3_kwargs()
                if s3_connection_settings is not None
                else {}
            )
            from pathway_tpu.persistence import _Boto3ObjectClient

            _object_client = _Boto3ObjectClient(bucket, **kwargs)
        return ObjectLakeFS(_object_client, prefix, uri)
    if uri.startswith(("az://", "azure://")):
        scheme = "az://" if uri.startswith("az://") else "azure://"
        container, prefix = _split_bucket_uri(uri, scheme)
        if _object_client is None:
            conn = os.environ.get("AZURE_STORAGE_CONNECTION_STRING")
            if not conn:
                raise ValueError(
                    f"{uri!r}: Azure lakes need credentials — set "
                    "AZURE_STORAGE_CONNECTION_STRING (the azure-sdk "
                    "convention) or inject a client"
                )
            from pathway_tpu.persistence import _AzureBlobClient

            _object_client = _AzureBlobClient(
                container, connection_string=conn
            )
        return ObjectLakeFS(_object_client, prefix, uri)
    return LocalLakeFS(uri)


def as_fs(fs_or_uri) -> LakeFS:
    """Coerce a LakeFS or URI/path to a LakeFS."""
    if isinstance(fs_or_uri, LakeFS):
        return fs_or_uri
    return resolve_lake_fs(fs_or_uri)


def write_parquet(fs: LakeFS, path: str, table) -> int:
    """Serialize an arrow table and publish it; returns the byte size."""
    import io as io_mod

    import pyarrow.parquet as pq

    sink = io_mod.BytesIO()
    pq.write_table(table, sink)
    data = sink.getvalue()
    fs.write_bytes(path, data)
    return len(data)


def read_parquet(fs: LakeFS, path: str):
    import io as io_mod

    import pyarrow.parquet as pq

    return pq.read_table(io_mod.BytesIO(fs.read_bytes(path)))
