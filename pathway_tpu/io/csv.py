"""pw.io.csv — sugar over fs with csv format (reference: io/csv)."""

from __future__ import annotations

from pathway_tpu.io import fs


def read(path: str, *, schema=None, mode: str = "streaming", **kwargs):
    return fs.read(path, format="csv", schema=schema, mode=mode, **kwargs)


def write(table, filename: str, **kwargs) -> None:
    fs.write(table, filename, format="csv", **kwargs)
