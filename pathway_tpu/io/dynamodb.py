"""pw.io.dynamodb — DynamoDB snapshot sink (reference:
python/pathway/io/dynamodb write:19; Rust writer
src/connectors/aws/dynamodb.rs:375 — upsert/delete keyed by partition+sort
key, i.e. snapshot semantics)."""

from __future__ import annotations

from typing import Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


class DynamoDBWriter(OutputWriter):
    def __init__(self, table_client, partition_key: str, sort_key: str | None):
        self.table_client = table_client
        self.partition_key = partition_key
        self.sort_key = sort_key

    def _key(self, ev: RowEvent) -> dict:
        key = {self.partition_key: jsonable(ev.values[self.partition_key])}
        if self.sort_key is not None:
            key[self.sort_key] = jsonable(ev.values[self.sort_key])
        return key

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        for ev in sorted(events, key=lambda e: e.diff):
            if ev.diff > 0:
                item = {k: jsonable(v) for k, v in ev.values.items()}
                self.table_client.put_item(Item=item)
            else:
                self.table_client.delete_item(Key=self._key(ev))


def write(
    table,
    table_name: str,
    partition_key,
    sort_key=None,
    *,
    init_mode: str = "default",
    name: str | None = None,
    _table_client=None,
    **kwargs,
) -> None:
    """Maintain the table as a DynamoDB item snapshot (reference:
    io/dynamodb write:19)."""
    pk = getattr(partition_key, "name", partition_key)
    sk = getattr(sort_key, "name", sort_key) if sort_key is not None else None
    if _table_client is None:
        try:
            import boto3  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.dynamodb requires boto3; install it or inject a table "
                "client via _table_client"
            )
        _table_client = boto3.resource("dynamodb").Table(table_name)
    attach_writer(table, DynamoDBWriter(_table_client, pk, sk), name=name)
