"""pw.io.mongodb — MongoDB sink (reference: python/pathway/io/mongodb
write:17; Rust side Bson formatter data_format.rs:2257 + MongoDB writer)."""

from __future__ import annotations

from typing import Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


class MongoWriter(OutputWriter):
    def __init__(self, collection, max_batch_size: int | None = None):
        self.collection = collection
        self.max_batch_size = max_batch_size

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        docs = []
        for ev in events:
            doc = {k: jsonable(v) for k, v in ev.values.items()}
            doc["time"] = ev.time
            doc["diff"] = ev.diff
            docs.append(doc)
        step = self.max_batch_size or len(docs) or 1
        for i in range(0, len(docs), step):
            self.collection.insert_many(docs[i : i + step])


def write(
    table,
    *,
    connection_string: str | None = None,
    database: str | None = None,
    collection: str | None = None,
    max_batch_size: int | None = None,
    name: str | None = None,
    _collection=None,
    **kwargs,
) -> None:
    """Append change-stream documents to a MongoDB collection (reference:
    io/mongodb write:17)."""
    if _collection is None:
        try:
            from pymongo import MongoClient  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.mongodb requires pymongo; install it or inject a "
                "collection via _collection"
            )
        client = MongoClient(connection_string)
        _collection = client[database][collection]
    attach_writer(
        table, MongoWriter(_collection, max_batch_size=max_batch_size), name=name
    )
