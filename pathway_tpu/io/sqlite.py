"""pw.io.sqlite — SQLite CDC reader (reference: python/pathway/io/sqlite
read:19; Rust side StorageType::Sqlite, src/connectors/data_storage.rs).

Fully functional via the stdlib sqlite3 module: polls the table and diffs
consecutive snapshots into insert/delete deltas keyed by the declared
primary key, reproducing the reference's change-data-capture behavior.
"""

from __future__ import annotations

import sqlite3
import time as time_mod
from typing import Any, Dict, Tuple

from pathway_tpu.internals import dtype as dt
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class _SqliteSubject(ConnectorSubjectBase):
    def __init__(self, path, table_name, schema, mode, refresh_interval):
        super().__init__()
        self.path = path
        self.table_name = table_name
        self.schema = schema
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._snapshot: Dict[Any, Tuple] = {}

    def _read_rows(self, conn) -> Dict[Any, Tuple]:
        names = list(self.schema.keys())
        pk = self.schema.primary_key_columns() or names
        cols = ", ".join(names)
        rows: Dict[Any, Tuple] = {}
        for rec in conn.execute(f"SELECT {cols} FROM {self.table_name}"):
            row = dict(zip(names, rec))
            key = tuple(row[c] for c in pk)
            rows[key] = tuple(
                _coerce(row[c], self.schema[c].dtype) for c in names
            )
        return rows

    def run(self) -> None:
        names = list(self.schema.keys())
        conn = sqlite3.connect(self.path)
        try:
            while True:
                current = self._read_rows(conn)
                changed = False
                for key, values in current.items():
                    old = self._snapshot.get(key)
                    if old == values:
                        continue
                    if old is not None:
                        self._remove(dict(zip(names, old)))
                    self.next(**dict(zip(names, values)))
                    changed = True
                for key in list(self._snapshot):
                    if key not in current:
                        self._remove(dict(zip(names, self._snapshot[key])))
                        changed = True
                self._snapshot = current
                if changed:
                    self.commit()
                if self.mode == "static":
                    return
                time_mod.sleep(self.refresh_interval)
        finally:
            conn.close()

    def _persisted_state(self):
        return {
            "snapshot": [[list(k), list(v)] for k, v in self._snapshot.items()]
        }

    def _restore_persisted_state(self, state) -> None:
        if state and "snapshot" in state:
            self._snapshot = {
                tuple(k): tuple(v) for k, v in state["snapshot"]
            }


def _coerce(v, dtype):
    core = dt.unoptionalize(dtype)
    if v is None:
        return None
    if core is dt.FLOAT and isinstance(v, int):
        return float(v)
    if core is dt.BYTES and isinstance(v, str):
        return v.encode()
    return v


def read(
    path: str,
    table_name: str,
    schema,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 0.2,
    name: str | None = None,
    **kwargs,
):
    """Stream changes of an SQLite table (reference: io/sqlite read:19).

    The schema's primary key columns identify rows across polls; value
    changes become retraction+insertion pairs.
    """

    def factory():
        return _SqliteSubject(path, table_name, schema, mode, refresh_interval)

    return connector_table(schema, factory, mode=mode, name=name)
