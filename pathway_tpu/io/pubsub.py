"""pw.io.pubsub — Google Pub/Sub sink (reference: python/pathway/io/pubsub
write:50, buffered via _OutputBuffer:12 — publishes each delta as a message
with time/diff attributes)."""

from __future__ import annotations

import json
from typing import Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


class PubSubWriter(OutputWriter):
    def __init__(self, publisher, topic_path: str):
        self.publisher = publisher
        self.topic_path = topic_path
        self._futures = []

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        for ev in events:
            payload = json.dumps(
                {k: jsonable(v) for k, v in ev.values.items()}
            ).encode()
            fut = self.publisher.publish(
                self.topic_path,
                payload,
                time=str(ev.time),
                diff=str(ev.diff),
            )
            self._futures.append(fut)

    def flush(self) -> None:
        for fut in self._futures:
            result = getattr(fut, "result", None)
            if result:
                result()
        self._futures.clear()


def write(
    table,
    publisher=None,
    project_id: str | None = None,
    topic_id: str | None = None,
    *,
    name: str | None = None,
    **kwargs,
) -> None:
    """Publish change-stream deltas to a Pub/Sub topic (reference:
    io/pubsub write:50). `publisher` may be any object with
    publish(topic, data, **attrs) — the google-cloud-pubsub PublisherClient
    if installed, or a fake in tests."""
    if publisher is None:
        try:
            from google.cloud import pubsub_v1  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.pubsub requires google-cloud-pubsub; install it or "
                "pass a publisher client"
            )
        publisher = pubsub_v1.PublisherClient()
    topic_path = (
        publisher.topic_path(project_id, topic_id)
        if hasattr(publisher, "topic_path") and project_id
        else (topic_id or "")
    )
    attach_writer(table, PubSubWriter(publisher, topic_path), name=name)
