"""pw.io.airbyte — Airbyte-sourced connector (reference:
python/pathway/io/airbyte — read:345, Docker/Cloud Run runner in logic.py;
full-refresh and incremental sync modes over the Airbyte protocol).

An Airbyte source is any runner producing Airbyte-protocol JSON lines
(RECORD / STATE messages). `DockerAirbyteSource` shells out to the
connector image via docker; tests inject a runner emitting protocol lines.
"""

from __future__ import annotations

import json
import subprocess
import tempfile
import time as time_mod
from typing import Any, Dict, Iterable, List, Optional

import yaml

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class AirbyteSourceRunner:
    """Produces Airbyte protocol messages (dicts) for one sync run."""

    def sync(self, state: Any) -> Iterable[dict]:
        raise NotImplementedError


class DockerAirbyteSource(AirbyteSourceRunner):
    """Runs an Airbyte connector image with `docker run` (reference:
    io/airbyte/logic.py docker runner)."""

    def __init__(self, image: str, config: dict, streams: List[str]):
        self.image = image
        self.config = config
        self.streams = streams

    def sync(self, state):
        with tempfile.TemporaryDirectory() as tmp:
            cfg = f"{tmp}/config.json"
            with open(cfg, "w") as fh:
                json.dump(self.config, fh)
            catalog = {
                "streams": [
                    {
                        "stream": {"name": s, "json_schema": {}},
                        "sync_mode": "incremental" if state else "full_refresh",
                        "destination_sync_mode": "append",
                    }
                    for s in self.streams
                ]
            }
            cat = f"{tmp}/catalog.json"
            with open(cat, "w") as fh:
                json.dump(catalog, fh)
            cmd = [
                "docker", "run", "--rm", "-v", f"{tmp}:/cfg",
                self.image, "read", "--config", "/cfg/config.json",
                "--catalog", "/cfg/catalog.json",
            ]
            if state is not None:
                st = f"{tmp}/state.json"
                with open(st, "w") as fh:
                    json.dump(state, fh)
                cmd += ["--state", "/cfg/state.json"]
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue
            proc.wait()


class _AirbyteSubject(ConnectorSubjectBase):
    def __init__(self, runner: AirbyteSourceRunner, streams, mode, refresh_interval):
        super().__init__()
        self.runner = runner
        self.streams = set(streams) if streams else None
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._state: Any = None

    def run(self) -> None:
        from pathway_tpu.engine.value import Json

        while True:
            got = False
            for msg in self.runner.sync(self._state):
                mtype = msg.get("type")
                if mtype == "RECORD":
                    rec = msg["record"]
                    if self.streams and rec.get("stream") not in self.streams:
                        continue
                    self.next(data=Json(rec.get("data", {})))
                    got = True
                elif mtype == "STATE":
                    self._state = msg.get("state")
            if got:
                self.commit()
            if self.mode == "static" or self._state is None:
                return  # full-refresh source: one sync per run
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        return {"state": self._state}

    def _restore_persisted_state(self, state) -> None:
        if state:
            self._state = state.get("state")


def read(
    config_file_path: str | None = None,
    streams: List[str] | None = None,
    *,
    mode: str = "streaming",
    refresh_interval_ms: int = 60_000,
    name: str | None = None,
    _runner: AirbyteSourceRunner | None = None,
    **kwargs,
):
    """Read records from an Airbyte connector (reference: io/airbyte
    read:345). The connector config yaml is produced by
    `pathway airbyte create-source` (cli.py:311)."""
    if _runner is None:
        with open(config_file_path) as fh:
            config = yaml.safe_load(fh)
        source = config.get("source", config)
        image = source.get("docker_image") or source.get("image")
        conf = source.get("config", {})
        _runner = DockerAirbyteSource(image, conf, streams or [])
    schema = schema_from_columns(
        {"data": ColumnSchema(name="data", dtype=dt.JSON)}, name="AirbyteSchema"
    )

    def factory():
        return _AirbyteSubject(
            _runner, streams, mode, refresh_interval_ms / 1000.0
        )

    return connector_table(schema, factory, mode=mode, name=name)
