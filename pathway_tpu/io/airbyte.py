"""pw.io.airbyte — Airbyte-sourced connector (reference:
python/pathway/io/airbyte — read:345, Docker/Cloud Run runner in logic.py;
full-refresh and incremental sync modes over the Airbyte protocol).

An Airbyte source is any runner producing Airbyte-protocol JSON lines
(RECORD / STATE messages). `DockerAirbyteSource` shells out to the
connector image via docker; tests inject a runner emitting protocol lines.
"""

from __future__ import annotations

import json
import logging
import os
import re
import subprocess
import tempfile
import time as time_mod
from typing import Any, Dict, Iterable, List, Optional

import yaml

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class AirbyteSourceRunner:
    """Produces Airbyte protocol messages (dicts) for one sync run.

    Shared machinery for every execution backend: configured-catalog
    construction, tolerant protocol parsing, and an injectable command
    executor (tests pass `_execute`)."""

    _execute = None  # injectable: fn(args) -> stdout text

    def sync(self, state: Any) -> Iterable[dict]:
        raise NotImplementedError

    def cleanup(self) -> None:
        """Release backend resources (venv dir, cloud job)."""

    def _configured_catalog(self, state) -> dict:
        return {
            "streams": [
                {
                    "stream": {"name": s, "json_schema": {}},
                    "sync_mode": "incremental" if state else "full_refresh",
                    "destination_sync_mode": "append",
                }
                for s in self.streams
            ]
        }

    def _exec(self, args: List[str]) -> str:
        if self._execute is not None:
            return self._execute(args)
        return subprocess.run(
            args, check=True, capture_output=True, text=True
        ).stdout

    @staticmethod
    def _parse_protocol(lines: Iterable[str]) -> Iterable[dict]:
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue


class DockerAirbyteSource(AirbyteSourceRunner):
    """Runs an Airbyte connector image with `docker run` (reference:
    io/airbyte/logic.py docker runner)."""

    def __init__(self, image: str, config: dict, streams: List[str]):
        self.image = image
        self.config = config
        self.streams = streams

    def sync(self, state):
        with tempfile.TemporaryDirectory() as tmp:
            cfg = f"{tmp}/config.json"
            with open(cfg, "w") as fh:
                json.dump(self.config, fh)
            cat = f"{tmp}/catalog.json"
            with open(cat, "w") as fh:
                json.dump(self._configured_catalog(state), fh)
            cmd = [
                "docker", "run", "--rm", "-v", f"{tmp}:/cfg",
                self.image, "read", "--config", "/cfg/config.json",
                "--catalog", "/cfg/catalog.json",
            ]
            if state is not None:
                st = f"{tmp}/state.json"
                with open(st, "w") as fh:
                    json.dump(state, fh)
                cmd += ["--state", "/cfg/state.json"]
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
            yield from self._parse_protocol(proc.stdout)
            proc.wait()


class VenvAirbyteSource(AirbyteSourceRunner):
    """Runs a pip-installable Airbyte connector inside a private venv
    (reference: third_party/airbyte_serverless venv execution)."""

    def __init__(self, package: str, config: dict, streams: List[str], *, _execute=None):
        self.package = package
        self.config = config
        self.streams = streams
        self._execute = _execute
        self._venv = None

    def _entrypoint(self) -> str:
        """Console-script path: Airbyte convention names the script after
        the connector (`airbyte-source-faker` installs `source-faker`)."""
        base = re.split(r"[=<>!\[ ]", self.package)[0]
        candidates = [base]
        if base.startswith("airbyte-"):
            candidates.insert(0, base[len("airbyte-"):])
        for cand in candidates:
            path = os.path.join(self._venv, "bin", cand)
            if self._execute is not None or os.path.exists(path):
                return path
        bin_dir = os.path.join(self._venv, "bin")
        if os.path.isdir(bin_dir):
            for f in sorted(os.listdir(bin_dir)):
                if f.startswith(("source-", "destination-")):
                    return os.path.join(bin_dir, f)
        raise FileNotFoundError(
            f"no connector entrypoint found in {bin_dir} for {self.package}"
        )

    def sync(self, state):
        import sys

        if self._venv is None:
            self._venv = tempfile.mkdtemp(prefix="pw_airbyte_venv_")
            self._exec([sys.executable, "-m", "venv", self._venv])
            self._exec([f"{self._venv}/bin/pip", "install", self.package])
        with tempfile.TemporaryDirectory() as tmp:
            cfg = f"{tmp}/config.json"
            with open(cfg, "w") as fh:
                json.dump(self.config, fh)
            cat = f"{tmp}/catalog.json"
            with open(cat, "w") as fh:
                json.dump(self._configured_catalog(state), fh)
            args = [
                self._entrypoint(), "read",
                "--config", cfg, "--catalog", cat,
            ]
            if state is not None:
                st = f"{tmp}/state.json"
                with open(st, "w") as fh:
                    json.dump(state, fh)
                args += ["--state", st]
            out = self._exec(args)
        yield from self._parse_protocol(out.splitlines())

    def cleanup(self) -> None:
        if self._venv is not None:
            import shutil

            shutil.rmtree(self._venv, ignore_errors=True)
            self._venv = None


# bootstrap script run inside the Cloud Run job: Airbyte images export
# AIRBYTE_ENTRYPOINT; config/catalog/state arrive base64-encoded in env
# vars set per execution (the same scheme the reference's
# airbyte_serverless remote runner uses)
_CLOUD_RUN_WRAPPER = (
    'echo $AIRBYTE_CONFIG_B64 | base64 -d > /tmp/config.json; '
    'echo $AIRBYTE_CATALOG_B64 | base64 -d > /tmp/catalog.json; '
    'if [ -n "$AIRBYTE_STATE_B64" ]; then '
    'echo $AIRBYTE_STATE_B64 | base64 -d > /tmp/state.json; '
    'STATE_ARGS="--state /tmp/state.json"; fi; '
    '$AIRBYTE_ENTRYPOINT read --config /tmp/config.json '
    '--catalog /tmp/catalog.json $STATE_ARGS; '
    # terminal sentinel: Cloud Logging ingestion is eventually consistent,
    # so the reader polls until it sees this line (or times out) before
    # trusting that the tail of the protocol stream has landed.  Preserve
    # the connector's exit status so a crashed connector still fails the
    # job (and `--wait` still raises) instead of echo masking it with 0.
    'rc=$?; echo PATHWAY_AIRBYTE_SYNC_DONE; exit $rc'
)

# how long to keep polling Cloud Logging for the sync's tail to land
_LOG_POLL_TIMEOUT_S = 120.0
_LOG_POLL_INTERVAL_S = 3.0


class CloudRunAirbyteSource(AirbyteSourceRunner):
    """Executes the connector as a Google Cloud Run job (reference:
    io/airbyte read(execution_type="remote") over the airbyte_serverless
    remote runner). The job wraps the image entrypoint in a shell that
    decodes config/catalog/state from env vars; protocol output is read
    back from Cloud Logging for the specific execution. Shells out to
    `gcloud` (ambient credentials); tests inject `_execute`."""

    def __init__(
        self,
        image: str,
        config: dict,
        streams: List[str],
        *,
        region: str = "europe-west1",
        job_name: str | None = None,
        env_vars: dict | None = None,
        log_poll_timeout: float = _LOG_POLL_TIMEOUT_S,
        log_poll_interval: float = _LOG_POLL_INTERVAL_S,
        _execute=None,
    ):
        import uuid

        self.log_poll_timeout = log_poll_timeout
        self.log_poll_interval = log_poll_interval

        self.image = image
        self.config = config
        self.streams = streams
        self.region = region
        self._auto_named = job_name is None
        self.job_name = job_name or f"pw-airbyte-{uuid.uuid4().hex[:12]}"
        self.env_vars = env_vars or {}
        self._execute = _execute
        self._created = False

    def sync(self, state):
        import base64

        if not self._created:
            env_flags = []
            for k, v in self.env_vars.items():
                env_flags += ["--set-env-vars", f"{k}={v}"]
            self._exec(
                [
                    "gcloud", "run", "jobs", "create", self.job_name,
                    "--image", self.image, "--region", self.region,
                    "--max-retries", "0",
                    "--command", "/bin/sh",
                    "--args", "-c," + _CLOUD_RUN_WRAPPER,
                ]
                + env_flags
            )
            self._created = True

        def b64(obj) -> str:
            return base64.b64encode(json.dumps(obj).encode()).decode()

        env = (
            f"AIRBYTE_CONFIG_B64={b64(self.config)},"
            f"AIRBYTE_CATALOG_B64={b64(self._configured_catalog(state))}"
        )
        if state is not None:
            env += f",AIRBYTE_STATE_B64={b64(state)}"
        execution = self._exec(
            [
                "gcloud", "run", "jobs", "execute", self.job_name,
                "--region", self.region, "--wait",
                "--update-env-vars", env,
                "--format", "value(metadata.name)",
            ]
        ).strip()
        exec_filter = (
            'resource.type="cloud_run_job" AND '
            f'labels."run.googleapis.com/execution_name"="{execution}"'
        )
        sentinel_cmd = [
            "gcloud", "logging", "read",
            exec_filter + ' AND textPayload="PATHWAY_AIRBYTE_SYNC_DONE"',
            "--format", "value(textPayload)",
            "--limit", "1",
        ]
        read_cmd = [
            "gcloud", "logging", "read",
            exec_filter,
            "--format", "value(textPayload)",
            "--order", "asc",
        ]
        # `jobs execute --wait` returning does NOT mean the logs have been
        # ingested: Cloud Logging lags by seconds, and a missing final
        # STATE message silently causes re-reads or gaps on the next
        # incremental sync.  Phase 1: poll a cheap sentinel-only query (so
        # large syncs are not re-downloaded every 3s) until the wrapper's
        # terminal line is ingested or we time out.
        deadline = time_mod.monotonic() + self.log_poll_timeout
        while (
            "PATHWAY_AIRBYTE_SYNC_DONE" not in self._exec(sentinel_cmd)
            and time_mod.monotonic() < deadline
        ):
            time_mod.sleep(self.log_poll_interval)
        # Phase 2: full ordered read.  Cloud Logging does not guarantee
        # cross-entry ingestion order, so the sentinel landing first does
        # not mean the tail did — re-read until the line count is stable
        # across two consecutive reads (still bounded by the deadline).
        logs = self._exec(read_cmd)
        while time_mod.monotonic() < deadline:
            time_mod.sleep(self.log_poll_interval)
            again = self._exec(read_cmd)
            if again.count("\n") == logs.count("\n"):
                logs = again
                break
            logs = again
        if "PATHWAY_AIRBYTE_SYNC_DONE" not in logs:
            # settle for what has landed, but loudly: a missing tail can
            # drop the final STATE message and cause re-reads/gaps on the
            # next incremental sync
            logging.getLogger(__name__).warning(
                "airbyte cloud-run sync %s: log stream still incomplete "
                "after %.0fs of polling; the final STATE message may be "
                "missing and the next incremental sync may re-read or "
                "skip records",
                execution,
                self.log_poll_timeout,
            )
        yield from self._parse_protocol(logs.splitlines())

    def cleanup(self) -> None:
        if self._created and self._auto_named:
            # auto-named jobs would otherwise accumulate in the project
            try:
                self._exec(
                    [
                        "gcloud", "run", "jobs", "delete", self.job_name,
                        "--region", self.region, "--quiet",
                    ]
                )
            except Exception:  # noqa: BLE001
                pass
            self._created = False


class _AirbyteSubject(ConnectorSubjectBase):
    def __init__(self, runner: AirbyteSourceRunner, streams, mode, refresh_interval):
        super().__init__()
        self.runner = runner
        self.streams = set(streams) if streams else None
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._state: Any = None

    def run(self) -> None:
        from pathway_tpu.engine.value import Json

        while True:
            got = False
            for msg in self.runner.sync(self._state):
                mtype = msg.get("type")
                if mtype == "RECORD":
                    rec = msg["record"]
                    if self.streams and rec.get("stream") not in self.streams:
                        continue
                    self.next(data=Json(rec.get("data", {})))
                    got = True
                elif mtype == "STATE":
                    self._state = msg.get("state")
            if got:
                self.commit()
            if self.mode == "static" or self._state is None:
                return  # full-refresh source: one sync per run
            time_mod.sleep(self.refresh_interval)

    def on_stop(self) -> None:
        self.runner.cleanup()

    def _persisted_state(self):
        return {"state": self._state}

    def _restore_persisted_state(self, state) -> None:
        if state:
            self._state = state.get("state")


def read(
    config_file_path: str | None = None,
    streams: List[str] | None = None,
    *,
    mode: str = "streaming",
    refresh_interval_ms: int = 60_000,
    execution_type: str = "local",
    gcp_region: str = "europe-west1",
    gcp_job_name: str | None = None,
    name: str | None = None,
    _runner: AirbyteSourceRunner | None = None,
    **kwargs,
):
    """Read records from an Airbyte connector (reference: io/airbyte
    read:345). The connector config yaml is produced by
    `pathway airbyte create-source` (cli.py:311)."""
    if _runner is None:
        with open(config_file_path) as fh:
            config = yaml.safe_load(fh)
        source = config.get("source", config)
        image = source.get("docker_image") or source.get("image")
        conf = source.get("config", {})
        if execution_type == "remote":
            _runner = CloudRunAirbyteSource(
                image,
                conf,
                streams or [],
                region=gcp_region,
                job_name=gcp_job_name,
            )
        else:
            _runner = DockerAirbyteSource(image, conf, streams or [])
    schema = schema_from_columns(
        {"data": ColumnSchema(name="data", dtype=dt.JSON)}, name="AirbyteSchema"
    )

    def factory():
        return _AirbyteSubject(
            _runner, streams, mode, refresh_interval_ms / 1000.0
        )

    return connector_table(schema, factory, mode=mode, name=name)


def _sample_config_from_spec(image: str) -> dict:
    """Derive a sample config from the connector's `spec` command; empty
    template when docker is unavailable (reference: the airbyte_serverless
    template renders the spec's properties)."""
    try:
        out = subprocess.run(
            ["docker", "run", "--rm", image, "spec"],
            capture_output=True,
            text=True,
            timeout=120,
            check=True,
        ).stdout
    except Exception:  # noqa: BLE001 — docker missing/unpullable
        return {}
    for msg in AirbyteSourceRunner._parse_protocol(out.splitlines()):
        if msg.get("type") == "SPEC":
            props = (
                msg.get("spec", {})
                .get("connectionSpecification", {})
                .get("properties", {})
            )
            return {
                k: v.get("default", f"<{v.get('type', 'value')}>")
                for k, v in props.items()
            }
    return {}


def create_connection_config(
    name: str, image: str, *, folder: str = "connections"
) -> str:
    """Backend of `pathway airbyte create-source` (reference: cli.py:311,
    third_party/airbyte_serverless/connections.py ConnectionFromFile):
    writes `connections/<name>.yaml` in the shape `pw.io.airbyte.read`
    consumes, with a sample config from the connector spec when docker is
    available."""
    path = os.path.join(folder, f"{name}.yaml")
    if os.path.exists(path):
        raise FileExistsError(
            f"Connection {name!r} already exists. "
            f"Delete `{path}` and run this command again to re-init it."
        )
    sample = _sample_config_from_spec(image)
    os.makedirs(folder, exist_ok=True)
    doc = {
        "source": {"docker_image": image, "config": sample, "streams": []}
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        yaml.safe_dump(doc, fh, sort_keys=False)
    os.replace(tmp, path)
    return path
