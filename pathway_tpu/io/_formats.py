"""Shared object-payload parsing for file-like connectors (fs, s3, minio,
gdrive-adjacent). One implementation of the reference's parser dispatch
(src/connectors/data_format.rs: DsvParser:522, JsonLinesParser:1630,
IdentityParser:894) over whole-object byte payloads.
"""

from __future__ import annotations

import csv as csv_mod
import io as io_mod
import json
from typing import Any, Dict, Iterator

from pathway_tpu.internals import dtype as dt


def parse_csv_value(text, dtype: dt.DType):
    if text is None:
        return None
    core = dt.unoptionalize(dtype)
    try:
        if core is dt.INT:
            return int(text)
        if core is dt.FLOAT:
            return float(text)
        if core is dt.BOOL:
            return text.strip().lower() in ("true", "1", "yes", "on")
    except ValueError:
        return None
    return text


def coerce_json_value(v, dtype: dt.DType):
    core = dt.unoptionalize(dtype)
    if core is dt.JSON:
        from pathway_tpu.engine.value import Json

        return Json(v)
    if core is dt.FLOAT and isinstance(v, int):
        return float(v)
    if isinstance(v, (dict, list)):
        from pathway_tpu.engine.value import Json

        return Json(v)
    return v



def _comment_filter(lines, cs):
    """Drop comment lines, but never inside an open quoted field: quote
    parity tracks whether a record spans lines (doubled quotes cancel,
    keeping parity correct for the doublequote escape style)."""
    in_quote = False
    for ln in lines:
        if (
            not in_quote
            and cs.comment_character
            and ln.startswith(cs.comment_character)
        ):
            continue
        if cs.enable_quoting and cs.quote:
            if ln.count(cs.quote) % 2 == 1:
                in_quote = not in_quote
        yield ln


def build_csv_reader(lines, csv_settings):
    """DictReader honoring CsvParserSettings; plain reader when None.
    Shared by the fs and object-store connectors so the settings mean the
    same thing everywhere (reference: io/_utils.py CsvParserSettings)."""
    if csv_settings is None:
        return csv_mod.DictReader(lines)
    cs = csv_settings
    return csv_mod.DictReader(
        _comment_filter(lines, cs),
        delimiter=cs.delimiter,
        quotechar=cs.quote if cs.enable_quoting else None,
        escapechar=cs.escape,
        doublequote=cs.enable_double_quote_escapes,
        quoting=(
            csv_mod.QUOTE_MINIMAL if cs.enable_quoting else csv_mod.QUOTE_NONE
        ),
    )


def parse_object(
    payload: bytes, format: str, schema, csv_settings=None
) -> Iterator[Dict[str, Any]]:
    """Parse one object's bytes into rows.

    formats: binary (one row, raw bytes), plaintext (row per line),
    plaintext_by_object (one row, whole text), json/jsonlines (row per JSON
    line), csv (header row + DictReader).
    """
    if format == "binary":
        yield {"data": payload}
        return
    if format in ("plaintext", "plaintext_by_object", "plaintext_by_file"):
        text = payload.decode(errors="replace")
        if format == "plaintext":
            for line in text.splitlines():
                yield {"data": line}
        else:
            yield {"data": text}
        return
    if format in ("json", "jsonlines"):
        names = set(schema.keys())
        for line in payload.decode(errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            yield {
                k: coerce_json_value(v, schema[k].dtype)
                for k, v in obj.items()
                if k in names
            }
        return
    if format == "csv":
        names = set(schema.keys())
        reader = build_csv_reader(
            io_mod.StringIO(payload.decode(errors="replace")), csv_settings
        )
        for rec in reader:
            yield {
                k: parse_csv_value(v, schema[k].dtype)
                for k, v in rec.items()
                if k in names
            }
        return
    raise ValueError(f"unknown format {format!r}")
