"""Shared object-payload parsing for file-like connectors (fs, s3, minio,
gdrive-adjacent). One implementation of the reference's parser dispatch
(src/connectors/data_format.rs: DsvParser:522, JsonLinesParser:1630,
IdentityParser:894) over whole-object byte payloads.
"""

from __future__ import annotations

import csv as csv_mod
import io as io_mod
import json
from typing import Any, Dict, Iterator

from pathway_tpu.internals import dtype as dt


def parse_csv_value(text, dtype: dt.DType):
    if text is None:
        return None
    core = dt.unoptionalize(dtype)
    try:
        if core is dt.INT:
            return int(text)
        if core is dt.FLOAT:
            return float(text)
        if core is dt.BOOL:
            return text.strip().lower() in ("true", "1", "yes", "on")
    except ValueError:
        return None
    return text


def coerce_json_value(v, dtype: dt.DType):
    core = dt.unoptionalize(dtype)
    if core is dt.JSON:
        from pathway_tpu.engine.value import Json

        return Json(v)
    if core is dt.FLOAT and isinstance(v, int):
        return float(v)
    if isinstance(v, (dict, list)):
        from pathway_tpu.engine.value import Json

        return Json(v)
    return v



def _comment_filter(lines, cs):
    """Drop comment lines, but never inside an open quoted field: quote
    parity tracks whether a record spans lines (doubled quotes cancel,
    keeping parity correct for the doublequote escape style)."""
    in_quote = False
    for ln in lines:
        if (
            not in_quote
            and cs.comment_character
            and ln.startswith(cs.comment_character)
        ):
            continue
        if cs.enable_quoting and cs.quote:
            if ln.count(cs.quote) % 2 == 1:
                in_quote = not in_quote
        yield ln


def build_csv_reader(lines, csv_settings):
    """DictReader honoring CsvParserSettings; plain reader when None.
    Shared by the fs and object-store connectors so the settings mean the
    same thing everywhere (reference: io/_utils.py CsvParserSettings)."""
    if csv_settings is None:
        return csv_mod.DictReader(lines)
    cs = csv_settings
    return csv_mod.DictReader(
        _comment_filter(lines, cs),
        delimiter=cs.delimiter,
        quotechar=cs.quote if cs.enable_quoting else None,
        escapechar=cs.escape,
        doublequote=cs.enable_double_quote_escapes,
        quoting=(
            csv_mod.QUOTE_MINIMAL if cs.enable_quoting else csv_mod.QUOTE_NONE
        ),
    )


def schema_defaults(schema) -> Dict[str, Any]:
    """{column: default_value} for columns declaring one — computed ONCE
    per parse, not per row (reference: test_io.py test_csv_default_values
    / test_json_default_values)."""
    return {
        name: schema[name].default_value
        for name in schema.keys()
        if getattr(schema[name], "has_default_value", False)
    }


def json_row(
    obj: dict, schema, names, field_paths, defaults
) -> Dict[str, Any]:
    """One parsed JSON document -> one row: schema projection, field-path
    extraction, then default filling. The SINGLE implementation shared by
    the fs and s3 connectors. A field path that resolves to nothing
    leaves the column ABSENT so its schema default (if any) applies."""
    row = {
        k: coerce_json_value(v, schema[k].dtype)
        for k, v in obj.items()
        if k in names
    }
    if field_paths:
        for col, path in field_paths.items():
            if col not in names:
                continue
            val = _json_pointer(obj, path)
            if val is None:
                row.pop(col, None)
            else:
                row[col] = coerce_json_value(val, schema[col].dtype)
    for k, dflt in defaults.items():
        if k not in row:
            row[k] = dflt
    return row


def _json_pointer(obj, path: str):
    """Minimal JSON-pointer resolution for json_field_paths ("/a/b")."""
    cur = obj
    for part in path.strip("/").split("/"):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
        if cur is None:
            return None
    return cur


def parse_object(
    payload: bytes, format: str, schema, csv_settings=None,
    json_field_paths=None,
) -> Iterator[Dict[str, Any]]:
    """Parse one object's bytes into rows.

    formats: binary (one row, raw bytes), plaintext (row per line),
    plaintext_by_object (one row, whole text), json/jsonlines (row per JSON
    line; ``json_field_paths`` maps columns to JSON pointers inside each
    document), csv (header row + DictReader).
    """
    if format == "binary":
        yield {"data": payload}
        return
    if format in ("plaintext", "plaintext_by_object", "plaintext_by_file"):
        text = payload.decode(errors="replace")
        if format == "plaintext":
            for line in text.splitlines():
                yield {"data": line}
        else:
            yield {"data": text}
        return
    if format in ("json", "jsonlines"):
        names = set(schema.keys())
        defaults = schema_defaults(schema)
        for line in payload.decode(errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            yield json_row(
                json.loads(line), schema, names, json_field_paths, defaults
            )
        return
    if format == "csv":
        names = set(schema.keys())
        defaults = schema_defaults(schema)
        reader = build_csv_reader(
            io_mod.StringIO(payload.decode(errors="replace")), csv_settings
        )
        for rec in reader:
            row = {
                k: parse_csv_value(v, schema[k].dtype)
                for k, v in rec.items()
                if k in names
            }
            for k, dflt in defaults.items():
                if k not in row:
                    row[k] = dflt
            yield row
        return
    raise ValueError(f"unknown format {format!r}")
