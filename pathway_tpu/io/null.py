"""pw.io.null — sink that discards output (reference: io/null)."""

from __future__ import annotations

from pathway_tpu.internals.parse_graph import G


def write(table, *, name: str | None = None) -> None:
    def attach(ctx, nodes):
        from pathway_tpu.engine.engine import CaptureNode

        (node,) = nodes
        CaptureNode(ctx.engine, node)

    G.add_sink([table], attach)
