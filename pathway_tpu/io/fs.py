"""pw.io.fs — filesystem connector (reference: python/pathway/io/fs,
src/connectors/posix_like.rs, scanner/filesystem.rs: glob-pattern polling
scanner with modify/delete detection).
"""

from __future__ import annotations

import csv as csv_mod
import glob as glob_mod
import json
import os
import time as time_mod
import zlib
from typing import Any, Dict, List, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import (
    ColumnSchema,
    Schema,
    schema_from_columns,
)
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


def _plaintext_schema():
    return schema_from_columns(
        {"data": ColumnSchema(name="data", dtype=dt.STR)}, name="PlaintextSchema"
    )


def _binary_schema():
    return schema_from_columns(
        {"data": ColumnSchema(name="data", dtype=dt.BYTES)}, name="BinarySchema"
    )


def _with_metadata(schema):
    cols = dict(schema.columns().items())
    cols["_metadata"] = ColumnSchema(name="_metadata", dtype=dt.JSON)
    return schema_from_columns(cols, name=schema.__name__ + "Meta")


class CsvParserSettings:
    """CSV parser settings (reference: io/_utils.py CsvParserSettings:146).
    ``delimiter``/``quote``/``escape`` map onto the csv module; the
    remaining flags are accepted for config parity."""

    def __init__(
        self,
        delimiter: str = ",",
        quote: str = '"',
        escape: str | None = None,
        enable_double_quote_escapes: bool = True,
        enable_quoting: bool = True,
        comment_character: str | None = None,
    ):
        self.delimiter = delimiter
        self.quote = quote
        self.escape = escape
        self.enable_double_quote_escapes = enable_double_quote_escapes
        self.enable_quoting = enable_quoting
        self.comment_character = comment_character


class _FsSubject(ConnectorSubjectBase):
    def __init__(
        self,
        path: str,
        format: str,
        schema,
        mode: str,
        with_metadata: bool,
        refresh_interval: float = 1.0,
        object_pattern: str = "*",
        batch_per_file: bool = False,
        csv_settings: "CsvParserSettings | None" = None,
        partitioned: bool = False,
        json_field_paths=None,
    ):
        super().__init__()
        self.path = path
        self.format = format
        self.schema = schema
        self.mode = mode
        self.with_metadata = with_metadata
        self.refresh_interval = refresh_interval
        self.object_pattern = object_pattern
        self.batch_per_file = batch_per_file
        self.csv_settings = csv_settings
        self.partitioned = partitioned
        self.json_field_paths = dict(json_field_paths or {})
        from pathway_tpu.io._formats import schema_defaults

        # schema defaults fill columns the payload does not carry
        self._defaults = schema_defaults(schema)
        self._seen: Dict[str, float] = {}

    def _owns(self, f: str) -> bool:
        """Partitioned reads: files are divided among workers by a stable
        name hash, so each worker PARSES a disjoint subset (reference:
        partitioned source mode — kafka consumer groups; for files this
        removes the replicated-parse bottleneck of the default mode)."""
        if not self.partitioned:
            return True
        wc = getattr(self, "_worker_count", 1)
        if wc <= 1:
            return True
        wid = getattr(self, "_worker_id", 0)
        return zlib.crc32(os.path.basename(f).encode()) % wc == wid

    def _list_files(self) -> List[str]:
        p = self.path
        if os.path.isdir(p):
            pattern = os.path.join(p, "**", self.object_pattern)
            files = glob_mod.glob(pattern, recursive=True)
        else:
            files = glob_mod.glob(p, recursive=True)
        return sorted(
            f for f in files if os.path.isfile(f) and self._owns(f)
        )

    def _metadata(self, f: str):
        from pathway_tpu.engine.value import Json

        st = os.stat(f)
        return Json(
            {
                "path": os.path.abspath(f),
                "size": st.st_size,
                "modified_at": int(st.st_mtime),
                "seen_at": int(time_mod.time()),
            }
        )

    def _emit_file(self, f: str) -> None:
        meta = {"_metadata": self._metadata(f)} if self.with_metadata else {}
        if self.format == "binary":
            with open(f, "rb") as fh:
                self.next(data=fh.read(), **meta)
        elif self.format in ("plaintext", "plaintext_by_file"):
            with open(f, "r", errors="replace") as fh:
                if self.format == "plaintext_by_file":
                    self.next(data=fh.read(), **meta)
                else:
                    chunk = [
                        {"data": line.rstrip("\n"), **meta} for line in fh
                    ]
                    if chunk:
                        self.next_batch(chunk)
        elif self.format in ("json", "jsonlines"):
            names = set(self.schema.keys())
            loads = json.loads
            schema = self.schema
            # STR/INT/BOOL json values need no per-value coercion; FLOAT
            # (int -> float promotion) and ANY/Json (dict/list wrapping)
            # must go through coerce_json_value
            plain = all(
                schema[k].dtype in (dt.STR, dt.INT, dt.BOOL) for k in names
            )
            from itertools import islice

            with open(f, "r", errors="replace") as fh:
                while True:
                    lines = list(islice(fh, 65536))
                    if not lines:
                        break
                    try:
                        # one C-level parse for the whole chunk beats
                        # per-line loads() by the per-call scanner setup;
                        # blank lines break the join and fall back below
                        text = ",".join(lines)
                        objs = loads("[%s]" % text)
                    except ValueError:
                        block = [ln for ln in lines if ln.strip()]
                        if not block:
                            continue
                        text = ",".join(block)
                        try:
                            objs = loads("[%s]" % text)
                        except ValueError:
                            objs = [loads(ln) for ln in block]
                            text = None
                    # chunk-level nested-value scan: values contain a
                    # dict/list iff the chunk text holds more '{' than
                    # one per row, or any '[' — two C string passes
                    flat_chunk = text is not None and (
                        text.count("{") == len(objs) and "[" not in text
                    )
                    self._emit_json_objs(
                        objs, names, meta, plain, flat_chunk
                    )
        elif self.format == "csv":
            names = set(self.schema.keys())
            with open(f, "r", newline="", errors="replace") as fh:
                from pathway_tpu.io._formats import build_csv_reader

                reader = build_csv_reader(fh, self.csv_settings)
                chunk = []
                for rec in reader:
                    row = {
                        k: _parse_csv_value(v, self.schema[k].dtype)
                        for k, v in rec.items()
                        if k in names
                    }
                    for k, dflt in self._defaults.items():
                        if k not in row:
                            row[k] = dflt
                    row.update(meta)
                    chunk.append(row)
                    if len(chunk) >= 65536:
                        self.next_batch(chunk)
                        chunk = []
                if chunk:
                    self.next_batch(chunk)
        else:
            raise ValueError(f"unknown format {self.format!r}")


    _TUPLE_COLS = 3  # specialize the no-dict path up to this width

    def _plain_tuples(self, objs, ordered):
        """Schema-ordered tuples straight from parsed flat objects —
        C-speed zip over itemgetter columns; None when any row misses a
        schema field (the row-dict path fills None and filters extras)."""
        from operator import itemgetter

        try:
            cols = [list(map(itemgetter(k), objs)) for k in ordered]
        except KeyError:
            return None
        return list(zip(*cols))

    def _emit_json_objs(self, objs, names, meta, plain, flat_chunk=False):
        schema = self.schema
        coerce = _coerce_json_value
        if self.json_field_paths:
            # field-path extraction: the shared row builder (defaults-only
            # schemas stay on the fast paths below — missing keys fall
            # through to the dict-row path, which default-fills)
            from pathway_tpu.io._formats import json_row

            rows = []
            for obj in objs:
                row = json_row(
                    obj, schema, names, self.json_field_paths,
                    self._defaults,
                )
                row.update(meta)
                rows.append(row)
            if rows:
                self.next_batch(rows)
            return
        if plain and not meta and flat_chunk:
            # fastest path: schema-ordered tuples, no row dicts at all
            # (flat_chunk proves no value anywhere in the chunk is nested)
            ordered = [k for k in schema.keys() if k in names]
            if len(ordered) <= self._TUPLE_COLS:
                vals = self._plain_tuples(objs, ordered)
                if vals is not None:
                    self.next_batch_tuples(vals, ordered)
                    return
        if plain:
            # drop fields outside the schema (incl. _pw_key, which the
            # sink would honor as a raw engine key); schema-violating
            # nested values (dict/list under a scalar dtype) still go
            # through coercion so they reach the engine as hashable Json,
            # as on the non-plain path
            rows = []
            rows_append = rows.append
            for obj in objs:
                if any(
                    type(v) is dict or type(v) is list
                    for v in obj.values()
                ):
                    rows_append(
                        {
                            k: coerce(v, schema[k].dtype)
                            for k, v in obj.items()
                            if k in names
                        }
                    )
                elif obj.keys() == names:
                    rows_append(obj)
                else:
                    rows_append(
                        {k: v for k, v in obj.items() if k in names}
                    )
            if self._defaults:
                for row in rows:
                    for k, dflt in self._defaults.items():
                        if k not in row:
                            row[k] = dflt
            if meta:
                for row in rows:
                    row.update(meta)
            self.next_batch(rows)
        else:
            rows = [
                {
                    k: coerce(v, schema[k].dtype)
                    for k, v in obj.items()
                    if k in names
                }
                for obj in objs
            ]
            if self._defaults:
                for row in rows:
                    for k, dflt in self._defaults.items():
                        if k not in row:
                            row[k] = dflt
            if meta:
                for row in rows:
                    row.update(meta)
            self.next_batch(rows)

    def run(self) -> None:
        while True:
            emitted_any = False
            for f in self._list_files():
                try:
                    mtime = os.stat(f).st_mtime
                except OSError:
                    continue
                if self._seen.get(f) == mtime:
                    continue
                self._seen[f] = mtime
                self._emit_file(f)
                # commit per file: downstream batches pipeline host-side
                # parsing of file N+1 against the (async-dispatched) device
                # work of file N; as a barrier, the batch boundary is
                # deterministic regardless of reader/engine relative speed
                self.commit(barrier=self.batch_per_file)
                emitted_any = True
            if not emitted_any:
                self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        return {"seen": dict(self._seen)}

    def _restore_persisted_state(self, state) -> None:
        if state and "seen" in state:
            self._seen.update(state["seen"])


# single shared implementation in _formats (also used by s3/minio)
from pathway_tpu.io._formats import (  # noqa: E402
    coerce_json_value as _coerce_json_value,
    parse_csv_value as _parse_csv_value,
)


def read(
    path: str,
    *,
    format: str = "csv",
    schema=None,
    mode: str = "streaming",
    csv_settings=None,
    json_field_paths=None,
    object_pattern: str = "*",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    refresh_interval: float = 1.0,
    batch_per_file: bool = False,
    partitioned: bool = False,
    **kwargs,
):
    """Read files as a table (reference: io/fs read; StorageType PosixLike /
    CsvFilesystem, data_storage.rs:359).

    ``batch_per_file=True`` (streaming mode, single-worker) makes every
    file its own engine batch — a barrier commit per file, so downstream
    host work on file N+1 pipelines against the async device work of
    file N with deterministic batch shapes. Multi-worker runs keep the
    shared timer ticks (the lockstep agreement cadence must stay
    identical on every worker), so there the flag only gates rows to
    whole-file prefixes without pinning one file per batch."""
    if schema is None:
        if format in ("plaintext", "plaintext_by_file"):
            schema = _plaintext_schema()
        elif format == "binary":
            schema = _binary_schema()
        else:
            raise ValueError(f"schema required for format {format!r}")
    out_schema = _with_metadata(schema) if with_metadata else schema

    def factory():
        return _FsSubject(
            path,
            format,
            schema,
            mode,
            with_metadata,
            refresh_interval=refresh_interval,
            object_pattern=object_pattern,
            batch_per_file=batch_per_file,
            csv_settings=csv_settings,
            partitioned=partitioned,
            json_field_paths=json_field_paths,
        )

    return connector_table(
        out_schema,
        factory,
        mode=mode,
        name=name,
        partitioned=partitioned,
        gated_commits=batch_per_file,
    )


def worker_output_path(filename: str, engine) -> str:
    """Per-worker part file: worker 0 keeps `filename`, worker w>0 writes
    `filename.w` — each worker emits only the rows it owns, so the union of
    part files equals the single-worker output exactly (no duplicates)."""
    if engine.worker_count <= 1 or engine.worker_id == 0:
        return filename
    return f"{filename}.{engine.worker_id}"


class _TxnFileSink:
    """Transactional wrapper around one worker's output file.

    Exactly-once by offset truncation: at every snapshot the driver calls
    `prepare(F)` BEFORE the manifest (fsync + record the byte length of
    everything <= F in the sink commit log) and `commit(F)` after it.  On
    recovery at restore frontier M the file is truncated back to the
    length recorded for M — the entry always exists, because the sink
    record of frontier F precedes the manifest of the same F — and the
    replayed epochs regenerate the tail.  `recover(-1)` (full replay)
    truncates to zero: the whole stream is rewritten, still exactly once.
    """

    transactional = True

    def __init__(self, path: str, commit_log, write_header=None):
        self.path = path
        self.log = commit_log
        self._write_header = write_header
        self.fh = open(path, "a+", newline="")
        self.fh.seek(0, os.SEEK_END)
        if self.fh.tell() == 0 and write_header is not None:
            write_header()

    def prepare(self, frontier: int) -> None:
        self.fh.flush()
        os.fsync(self.fh.fileno())
        self.log.record_offset(frontier, self.fh.tell())

    def commit(self, frontier: int) -> None:
        self.log.mark_committed(frontier)

    def recover(self, frontier: int) -> None:
        offset = self.log.offset_for(frontier) if frontier >= 0 else 0
        if offset is None:
            offset = 0
        self.log.rollback_to(frontier)
        self.fh.flush()
        self.fh.truncate(offset)
        self.fh.seek(offset)
        if offset == 0 and self._write_header is not None:
            self._write_header()

    def committed_frontier(self) -> int:
        return self.log.committed_frontier()


def write(table, filename: str, *, format: str = "json", name: str | None = None, **kwargs) -> None:
    """Write a table's change stream to a file (reference: io/fs write).

    Under a persistent run with operator snapshots enabled the sink is
    exactly-once across crash/failover (see _TxnFileSink); otherwise the
    file is truncated at open and written through, as before."""
    column_names = table.column_names()

    def attach(ctx, nodes):
        from pathway_tpu.engine.engine import SubscribeNode

        engine = ctx.engine
        (node,) = nodes
        path = worker_output_path(filename, engine)
        pcfg = getattr(engine, "_persistence_config", None)
        txn = (
            pcfg is not None
            and getattr(pcfg, "snapshot_interval_ms", 0) > 0
        )
        if txn:
            from pathway_tpu.persistence import SinkCommitLog

            sink_name = name or f"fs:{filename}"
            sink = _TxnFileSink(
                path,
                SinkCommitLog(
                    pcfg.backend._backend, sink_name, engine.worker_id
                ),
                write_header=None,  # bound below for csv
            )
            fh = sink.fh
            engine.register_txn_sink(sink)
        else:
            sink = None
            fh = open(path, "w", newline="")
        if format == "csv":
            writer = csv_mod.writer(fh)
            header_row = column_names + ["time", "diff"]

            def header():
                writer.writerow(header_row)

            if sink is not None:
                sink._write_header = header
                if fh.tell() == 0:
                    header()
            else:
                header()

            def on_change(key, row, time, is_addition):
                writer.writerow(
                    [row[c] for c in column_names] + [time, 1 if is_addition else -1]
                )

        else:

            def on_change(key, row, time, is_addition):
                obj = {c: _jsonable(row[c]) for c in column_names}
                obj["time"] = time
                obj["diff"] = 1 if is_addition else -1
                fh.write(json.dumps(obj) + "\n")

        def on_end():
            fh.flush()
            fh.close()

        SubscribeNode(
            ctx.engine,
            node,
            on_change=on_change,
            on_end=on_end,
            column_names=column_names,
        )

    G.add_sink([table], attach)


def _jsonable(v):
    import numpy as np

    from pathway_tpu.engine.value import Json, Pointer

    if isinstance(v, Json):
        return v.value
    if isinstance(v, Pointer):
        return repr(v)
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    if isinstance(v, np.ndarray):
        return v.tolist()
    import datetime

    if isinstance(v, (datetime.datetime,)):
        return v.isoformat()
    if isinstance(v, datetime.timedelta):
        return v.total_seconds()
    return v
