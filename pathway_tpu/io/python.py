"""pw.io.python — custom python connectors (reference:
python/pathway/io/python/__init__.py:47 ConnectorSubject)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase as ConnectorSubject,
)
from pathway_tpu.io._connector_runtime import connector_table


def read(
    subject: ConnectorSubject | type,
    *,
    schema,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs,
):
    """Read from a user ConnectorSubject."""
    if isinstance(subject, type) or (
        callable(subject) and not isinstance(subject, ConnectorSubject)
    ):
        factory = subject
    else:
        # a subject instance can be consumed once
        used = [False]

        def factory():
            if used[0]:
                raise RuntimeError("ConnectorSubject instance already consumed")
            used[0] = True
            return subject

    return connector_table(
        schema, factory, mode=mode, name=name, exclusive=True
    )
