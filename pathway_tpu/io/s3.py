"""pw.io.s3 — S3 object-store connector (reference: python/pathway/io/s3 —
AwsS3Settings, read:95, read_from_digital_ocean:320, read_from_wasabi:459;
Rust scanner src/connectors/scanner/s3.rs, StorageType S3Csv/S3Lines).

Object listing/fetching goes through an `S3Client` interface: boto3 if
installed, or any injected client (tests use an in-memory fake). Parsing
mirrors the fs connector: csv / json / plaintext / plaintext_by_object /
binary.
"""

from __future__ import annotations

import csv as csv_mod
import io as io_mod
import json
import time as time_mod
from typing import Dict, Iterable, List, Optional, Tuple

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)
from pathway_tpu.io._formats import parse_object
from pathway_tpu.io.fs import (
    _binary_schema,
    _plaintext_schema,
    _with_metadata,
)


class AwsS3Settings:
    """Connection settings (reference: io/s3 AwsS3Settings)."""

    def __init__(
        self,
        *,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        with_path_style: bool = False,
        region: str | None = None,
        endpoint: str | None = None,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region
        self.endpoint = endpoint

    def boto3_kwargs(self) -> dict:
        """boto3.client("s3", ...) keyword mapping — the single place the
        settings-to-boto3 translation lives (shared with the lake
        connectors' resolve_lake_fs)."""
        return {
            "aws_access_key_id": self.access_key,
            "aws_secret_access_key": self.secret_access_key,
            "region_name": self.region,
            "endpoint_url": self.endpoint,
        }

    def create_client(self):
        try:
            import boto3  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.s3 requires boto3; install it or inject a client via "
                "_client_factory"
            )
        return _Boto3Client(
            boto3.client("s3", **self.boto3_kwargs()),
            self.bucket_name,
        )


class DigitalOceanS3Settings(AwsS3Settings):
    """DigitalOcean Spaces (reference: io/s3 DigitalOceanS3Settings:23)."""


class WasabiS3Settings(AwsS3Settings):
    """Wasabi (reference: io/s3 WasabiS3Settings:58)."""


class S3Client:
    """list_objects(prefix) -> [(key, etag/mtime)]; get_object(key) -> bytes."""

    def list_objects(self, prefix: str) -> List[Tuple[str, str]]:
        raise NotImplementedError

    def get_object(self, key: str) -> bytes:
        raise NotImplementedError


class _Boto3Client(S3Client):
    def __init__(self, client, bucket: str):
        self.client = client
        self.bucket = bucket

    def list_objects(self, prefix: str):
        out = []
        paginator = self.client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                out.append((obj["Key"], obj.get("ETag", str(obj.get("LastModified", "")))))
        return out

    def get_object(self, key: str) -> bytes:
        resp = self.client.get_object(Bucket=self.bucket, Key=key)
        return resp["Body"].read()


class _S3Subject(ConnectorSubjectBase):
    def __init__(self, client_factory, prefix, format, schema, mode, with_metadata, refresh_interval=1.0, csv_settings=None, json_field_paths=None):
        super().__init__()
        self.client_factory = client_factory
        self.prefix = prefix
        self.format = format
        self.schema = schema
        self.mode = mode
        self.with_metadata = with_metadata
        self.refresh_interval = refresh_interval
        self.csv_settings = csv_settings
        self.json_field_paths = json_field_paths
        self._seen: Dict[str, str] = {}

    def _emit_object(self, key: str, payload: bytes) -> None:
        meta = {}
        if self.with_metadata:
            from pathway_tpu.engine.value import Json

            meta = {
                "_metadata": Json(
                    {"path": key, "size": len(payload), "seen_at": int(time_mod.time())}
                )
            }
        for row in parse_object(
            payload, self.format, self.schema,
            csv_settings=self.csv_settings,
            json_field_paths=self.json_field_paths,
        ):
            self.next(**row, **meta)

    def run(self) -> None:
        client = self.client_factory()
        while True:
            for key, version in client.list_objects(self.prefix):
                if self._seen.get(key) == version:
                    continue
                self._seen[key] = version
                self._emit_object(key, client.get_object(key))
            self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        return {"seen": dict(self._seen)}

    def _restore_persisted_state(self, state) -> None:
        if state and "seen" in state:
            self._seen.update(state["seen"])


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "csv",
    schema=None,
    mode: str = "streaming",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    refresh_interval: float = 1.0,
    csv_settings=None,
    json_field_paths=None,
    _client_factory=None,
    **kwargs,
):
    """Read objects under an S3 path as a table (reference: io/s3 read:95).

    `path` may be "s3://bucket/prefix" or a bare prefix when the bucket is
    set in the settings.
    """
    prefix = path
    if path.startswith("s3://"):
        rest = path[len("s3://") :]
        bucket, _, prefix = rest.partition("/")
        if aws_s3_settings is None:
            aws_s3_settings = AwsS3Settings(bucket_name=bucket)
        elif aws_s3_settings.bucket_name is None:
            aws_s3_settings.bucket_name = bucket
    if schema is None:
        if format in ("plaintext", "plaintext_by_object"):
            schema = _plaintext_schema()
        elif format == "binary":
            schema = _binary_schema()
        else:
            raise ValueError(f"schema required for format {format!r}")
    out_schema = _with_metadata(schema) if with_metadata else schema
    if _client_factory is None:
        settings = aws_s3_settings or AwsS3Settings()

        def _client_factory():
            return settings.create_client()

    def factory():
        return _S3Subject(
            _client_factory,
            prefix,
            format,
            schema,
            mode,
            with_metadata,
            refresh_interval=refresh_interval,
            csv_settings=csv_settings,
            json_field_paths=json_field_paths,
        )

    return connector_table(out_schema, factory, mode=mode, name=name)


def read_from_digital_ocean(
    path: str,
    do_s3_settings: DigitalOceanS3Settings,
    *,
    format: str = "csv",
    schema=None,
    mode: str = "streaming",
    **kwargs,
):
    """(reference: io/s3 read_from_digital_ocean:320)"""
    return read(
        path, aws_s3_settings=do_s3_settings, format=format, schema=schema, mode=mode, **kwargs
    )


def read_from_wasabi(
    path: str,
    wasabi_s3_settings: WasabiS3Settings,
    *,
    format: str = "csv",
    schema=None,
    mode: str = "streaming",
    **kwargs,
):
    """(reference: io/s3 read_from_wasabi:459)"""
    return read(
        path, aws_s3_settings=wasabi_s3_settings, format=format, schema=schema, mode=mode, **kwargs
    )
