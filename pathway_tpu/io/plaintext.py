"""pw.io.plaintext — lines of text files (reference: io/plaintext)."""

from __future__ import annotations

from pathway_tpu.io import fs


def read(path: str, *, mode: str = "streaming", **kwargs):
    return fs.read(path, format="plaintext", mode=mode, **kwargs)


def write(table, filename: str, **kwargs) -> None:
    fs.write(table, filename, format="plaintext", **kwargs)
