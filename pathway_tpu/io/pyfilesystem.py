"""pw.io.pyfilesystem — read any PyFilesystem FS object (reference:
python/pathway/io/pyfilesystem — _PyFilesystemSubject:29, read:143; polls an
fs.base.FS for files, emitting payload + metadata, with modification and
deletion tracking)."""

from __future__ import annotations

import time as time_mod
from typing import Dict

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class _PyFilesystemSubject(ConnectorSubjectBase):
    def __init__(self, source, path, mode, refresh_interval, with_metadata):
        super().__init__()
        self.source = source
        self.path = path
        self.mode = mode
        self.refresh_interval = refresh_interval
        self.with_metadata = with_metadata
        self._seen: Dict[str, tuple] = {}

    def _row(self, path: str, payload: bytes, info) -> dict:
        row = {"data": payload}
        if self.with_metadata:
            from pathway_tpu.engine.value import Json

            row["_metadata"] = Json(
                {
                    "path": path,
                    "size": len(payload),
                    "modified_at": (
                        info.modified.timestamp()
                        if getattr(info, "modified", None)
                        else None
                    ),
                    "seen_at": int(time_mod.time()),
                }
            )
        return row

    def run(self) -> None:
        while True:
            changed = False
            current = set()
            for path in self.source.walk.files(self.path or "/"):
                info = self.source.getinfo(path, namespaces=["details"])
                modified = getattr(info, "modified", None)
                stamp = (modified.timestamp() if modified else None,)
                current.add(path)
                old = self._seen.get(path)
                if old is not None and old[0] == stamp:
                    continue
                payload = self.source.readbytes(path)
                if old is not None:
                    # retract the exact previously-emitted row
                    self._remove(old[1])
                row = self._row(path, payload, info)
                self._seen[path] = (stamp, row)
                self.next(**row)
                changed = True
            for path in list(self._seen):
                if path not in current:
                    stamp, row = self._seen.pop(path)
                    self._remove(row)
                    changed = True
            if changed:
                self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)


def read(
    source,
    *,
    path: str | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    refresh_interval: float = 30.0,
    name: str | None = None,
    **kwargs,
):
    """Read a PyFilesystem FS as a binary-file table (reference:
    io/pyfilesystem read:143). `source` is an fs.base.FS (install the `fs`
    package) or any object with `walk.files`, `getinfo`, `readbytes`."""
    cols = {"data": ColumnSchema(name="data", dtype=dt.BYTES)}
    if with_metadata:
        cols["_metadata"] = ColumnSchema(name="_metadata", dtype=dt.JSON)
    schema = schema_from_columns(cols, name="PyFilesystemSchema")

    def factory():
        return _PyFilesystemSubject(source, path, mode, refresh_interval, with_metadata)

    return connector_table(schema, factory, mode=mode, name=name)
