"""pw.io.slack — Slack alert sink (reference: python/pathway/io/slack
send_alerts:11 — posts each new value of a column to a Slack channel via
chat.postMessage). Functional via `requests`.
"""

from __future__ import annotations

from typing import Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable

_SLACK_URL = "https://slack.com/api/chat.postMessage"


class _SlackWriter(OutputWriter):
    def __init__(self, column: str, channel_id: str, token: str, *, _post=None):
        self.column = column
        self.channel_id = channel_id
        self.token = token
        if _post is None:
            import requests

            _post = requests.post
        self._post = _post

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        for ev in events:
            if ev.diff <= 0:
                continue  # alerts fire on additions only
            self._post(
                _SLACK_URL,
                json={
                    "channel": self.channel_id,
                    "text": str(jsonable(ev.values[self.column])),
                },
                headers={"Authorization": f"Bearer {self.token}"},
            )


def send_alerts(
    alerts, slack_channel_id: str, slack_token: str, *, _post=None
) -> None:
    """Post each new value of `alerts` (a ColumnReference) to Slack
    (reference: io/slack send_alerts:11)."""
    table = alerts.table.select(**{alerts.name: alerts})
    attach_writer(
        table,
        _SlackWriter(alerts.name, slack_channel_id, slack_token, _post=_post),
    )
