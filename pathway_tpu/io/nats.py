"""pw.io.nats — NATS connector (reference: python/pathway/io/nats read:24,
write:158; Rust side async-nats in src/connectors/data_storage.rs).

The nats-py client is optional/gated; tests inject `_client_factory`.
"""

from __future__ import annotations

import queue as queue_mod
import threading

from pathway_tpu.io import _mq


class _NatsClient(_mq.MessageQueueClient):
    """Adapter over nats-py run in a private event-loop thread."""

    def __init__(self, uri: str, topic: str, *, for_read: bool):
        try:
            import asyncio

            import nats  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.nats requires the nats-py package; install it or "
                "inject a client via _client_factory"
            )
        self._asyncio = asyncio
        self._nats = nats
        self.uri = uri
        self.topic = topic
        self._messages: queue_mod.SimpleQueue = queue_mod.SimpleQueue()
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)
        self._thread.start()
        self._conn = self._call(nats.connect(uri))
        if for_read:
            async def _sub():
                async def handler(msg):
                    self._messages.put((None, msg.data, {"subject": msg.subject}))

                await self._conn.subscribe(topic, cb=handler)

            self._call(_sub())

    def _call(self, coro):
        fut = self._asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout=30)

    def poll(self, timeout: float):
        out = []
        try:
            out.append(self._messages.get(timeout=timeout))
            while True:
                out.append(self._messages.get_nowait())
        except queue_mod.Empty:
            pass
        return out

    def produce(self, topic, key, payload):
        self._call(self._conn.publish(topic, payload))

    def commit(self):
        self._call(self._conn.flush())

    def close(self):
        try:
            self._call(self._conn.drain())
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)


def read(
    uri: str,
    topic: str,
    *,
    schema=None,
    format: str = "raw",
    mode: str = "streaming",
    name: str | None = None,
    _client_factory=None,
    **kwargs,
):
    """Read a NATS subject as a streaming table (reference: io/nats read:24)."""
    if _client_factory is None:

        def _client_factory():
            return _NatsClient(uri, topic, for_read=True)

    return _mq.mq_read(
        _client_factory, schema=schema, format=format, mode=mode, name=name
    )


def write(
    table,
    uri: str,
    topic: str,
    *,
    format: str = "json",
    name: str | None = None,
    _client=None,
    **kwargs,
) -> None:
    """Publish the table's change stream to a NATS subject (reference:
    io/nats write:158)."""
    if _client is None:
        _client = _NatsClient(uri, topic, for_read=False)
    _mq.mq_write(table, _client, topic, format=format, name=name)
