"""pw.io.jsonlines — sugar over fs with jsonlines format (reference:
io/jsonlines)."""

from __future__ import annotations

from pathway_tpu.io import fs


def read(path: str, *, schema=None, mode: str = "streaming", **kwargs):
    return fs.read(path, format="json", schema=schema, mode=mode, **kwargs)


def write(table, filename: str, **kwargs) -> None:
    fs.write(table, filename, format="json", **kwargs)
