"""pw.io.postgres — PostgreSQL writers (reference: python/pathway/io/postgres
write:22, write_snapshot:141; Rust formatters PsqlUpdates / PsqlSnapshot,
src/connectors/data_format.rs:1821,1880).

SQL statement generation is pure and unit-testable; execution needs a DBAPI
connection — psycopg/psycopg2 if installed, or any connection injected via
`_connection` (e.g. sqlite3 in tests, modulo placeholder style).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


def _connection_string_from_settings(settings: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in settings.items())


def _connect(postgres_settings: dict):
    try:
        import psycopg  # type: ignore

        return psycopg.connect(_connection_string_from_settings(postgres_settings))
    except ImportError:
        pass
    try:
        import psycopg2  # type: ignore

        return psycopg2.connect(**postgres_settings)
    except ImportError:
        raise ImportError(
            "pw.io.postgres requires psycopg or psycopg2; install one or "
            "inject a DBAPI connection via _connection"
        )


def build_insert_statement(
    table_name: str, columns: Sequence[str], *, placeholder: str = "%s"
) -> str:
    """INSERT used by the updates writer (reference: PsqlUpdatesFormatter,
    data_format.rs:1821 — appends time/diff columns)."""
    cols = ", ".join(list(columns) + ["time", "diff"])
    ph = ", ".join([placeholder] * (len(columns) + 2))
    return f"INSERT INTO {table_name} ({cols}) VALUES ({ph})"


def build_snapshot_statements(
    table_name: str,
    columns: Sequence[str],
    primary_key: Sequence[str],
    *,
    placeholder: str = "%s",
) -> Tuple[str, str]:
    """(upsert, delete) used by the snapshot writer (reference:
    PsqlSnapshotFormatter, data_format.rs:1880)."""
    cols = ", ".join(columns)
    ph = ", ".join([placeholder] * len(columns))
    pk = ", ".join(primary_key)
    updates = ", ".join(
        f"{c}=EXCLUDED.{c}" for c in columns if c not in primary_key
    )
    upsert = (
        f"INSERT INTO {table_name} ({cols}) VALUES ({ph}) "
        f"ON CONFLICT ({pk}) DO UPDATE SET {updates}"
    )
    where = " AND ".join(f"{c}={placeholder}" for c in primary_key)
    delete = f"DELETE FROM {table_name} WHERE {where}"
    return upsert, delete


class PostgresUpdatesWriter(OutputWriter):
    def __init__(self, connection, table_name: str, columns: Sequence[str], *, placeholder: str = "%s"):
        self.conn = connection
        self.columns = list(columns)
        self.stmt = build_insert_statement(table_name, columns, placeholder=placeholder)

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        cur = self.conn.cursor()
        for ev in events:
            vals = [jsonable(ev.values[c]) for c in self.columns]
            cur.execute(self.stmt, vals + [ev.time, ev.diff])
        self.conn.commit()

    def close(self) -> None:
        self.conn.close()


class PostgresSnapshotWriter(OutputWriter):
    def __init__(self, connection, table_name: str, columns: Sequence[str], primary_key: Sequence[str], *, placeholder: str = "%s"):
        self.conn = connection
        self.columns = list(columns)
        self.primary_key = list(primary_key)
        self.upsert, self.delete = build_snapshot_statements(
            table_name, columns, primary_key, placeholder=placeholder
        )

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        cur = self.conn.cursor()
        # within one time, deletions before insertions so upserts win
        for ev in sorted(events, key=lambda e: e.diff):
            if ev.diff > 0:
                cur.execute(
                    self.upsert, [jsonable(ev.values[c]) for c in self.columns]
                )
            else:
                cur.execute(
                    self.delete,
                    [jsonable(ev.values[c]) for c in self.primary_key],
                )
        self.conn.commit()

    def close(self) -> None:
        self.conn.close()


def write(
    table,
    postgres_settings: dict,
    table_name: str,
    *,
    max_batch_size: int | None = None,
    init_mode: str = "default",
    name: str | None = None,
    _connection=None,
    _placeholder: str = "%s",
    **kwargs,
) -> None:
    """Append the change stream (with time/diff columns) to a Postgres table
    (reference: io/postgres write:22)."""
    conn = _connection if _connection is not None else _connect(postgres_settings)
    attach_writer(
        table,
        PostgresUpdatesWriter(
            conn, table_name, table.column_names(), placeholder=_placeholder
        ),
        name=name,
    )


def write_snapshot(
    table,
    postgres_settings: dict,
    table_name: str,
    primary_key: list[str],
    *,
    max_batch_size: int | None = None,
    init_mode: str = "default",
    name: str | None = None,
    _connection=None,
    _placeholder: str = "%s",
    **kwargs,
) -> None:
    """Maintain the table as an up-to-date Postgres snapshot keyed by
    primary_key (reference: io/postgres write_snapshot:141)."""
    conn = _connection if _connection is not None else _connect(postgres_settings)
    attach_writer(
        table,
        PostgresSnapshotWriter(
            conn,
            table_name,
            table.column_names(),
            primary_key,
            placeholder=_placeholder,
        ),
        name=name,
    )
