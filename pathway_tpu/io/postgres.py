"""pw.io.postgres — PostgreSQL writers (reference: python/pathway/io/postgres
write:22, write_snapshot:141; Rust formatters PsqlUpdates / PsqlSnapshot,
src/connectors/data_format.rs:1821,1880).

SQL statement generation is pure and unit-testable; execution needs a DBAPI
connection — psycopg/psycopg2 if installed, or any connection injected via
`_connection` (e.g. sqlite3 in tests, modulo placeholder style).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


def _connection_string_from_settings(settings: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in settings.items())


def _connect(postgres_settings: dict):
    try:
        import psycopg  # type: ignore

        return psycopg.connect(_connection_string_from_settings(postgres_settings))
    except ImportError:
        pass
    try:
        import psycopg2  # type: ignore

        return psycopg2.connect(**postgres_settings)
    except ImportError:
        raise ImportError(
            "pw.io.postgres requires psycopg or psycopg2; install one or "
            "inject a DBAPI connection via _connection"
        )


def build_insert_statement(
    table_name: str, columns: Sequence[str], *, placeholder: str = "%s"
) -> str:
    """INSERT used by the updates writer (reference: PsqlUpdatesFormatter,
    data_format.rs:1821 — appends time/diff columns)."""
    cols = ", ".join(list(columns) + ["time", "diff"])
    ph = ", ".join([placeholder] * (len(columns) + 2))
    return f"INSERT INTO {table_name} ({cols}) VALUES ({ph})"


def build_snapshot_statements(
    table_name: str,
    columns: Sequence[str],
    primary_key: Sequence[str],
    *,
    placeholder: str = "%s",
) -> Tuple[str, str]:
    """(upsert, delete) used by the snapshot writer (reference:
    PsqlSnapshotFormatter, data_format.rs:1880)."""
    cols = ", ".join(columns)
    ph = ", ".join([placeholder] * len(columns))
    pk = ", ".join(primary_key)
    updates = ", ".join(
        f"{c}=EXCLUDED.{c}" for c in columns if c not in primary_key
    )
    upsert = (
        f"INSERT INTO {table_name} ({cols}) VALUES ({ph}) "
        f"ON CONFLICT ({pk}) DO UPDATE SET {updates}"
    )
    where = " AND ".join(f"{c}={placeholder}" for c in primary_key)
    delete = f"DELETE FROM {table_name} WHERE {where}"
    return upsert, delete


_COMMIT_TABLE_DDL = (
    "CREATE TABLE IF NOT EXISTS __pathway_commit "
    "(sink TEXT PRIMARY KEY, frontier BIGINT)"
)


class PostgresUpdatesWriter(OutputWriter):
    """Append-updates writer, exactly-once under a persistent run.

    Without persistence it writes through per batch, as before.  With a
    bound SinkCommitLog, epochs buffer in memory; `prepare(F)` durably
    stages everything <= F in the commit log BEFORE the snapshot
    manifest, and `commit(F)` finalizes: in one DB transaction it applies
    every staged epoch past the `__pathway_commit` frontier row and
    advances that row to F.  The conditional apply makes finalize
    idempotent — `recover(M)` after a crash simply re-runs it — so rows
    land exactly once however the run dies.

    `connection` may be a live DBAPI connection or a zero-arg factory;
    multi-worker runs must pass a factory so each worker's fork opens its
    own connection.
    """

    transactional = True

    def __init__(self, connection, table_name: str, columns: Sequence[str], *, placeholder: str = "%s"):
        self._conn_src = connection
        # DBAPI connections can themselves be callable (sqlite3.Connection
        # has a __call__), so "factory" means callable AND not a connection.
        self._is_factory = callable(connection) and not hasattr(
            connection, "cursor"
        )
        self._conn = None if self._is_factory else connection
        self.table_name = table_name
        self.columns = list(columns)
        self.placeholder = placeholder
        self.stmt = build_insert_statement(table_name, columns, placeholder=placeholder)
        self.log = None
        self._worker_id = 0
        self._epochs: List[Tuple[int, List[list]]] = []

    # a live injected connection is shared (single-worker tests); a
    # factory gives every worker its own session
    def fork(self, worker_id: int) -> "PostgresUpdatesWriter":
        if self._is_factory:
            w = PostgresUpdatesWriter(
                self._conn_src,
                self.table_name,
                self.columns,
                placeholder=self.placeholder,
            )
        else:
            w = self
        w._worker_id = worker_id
        return w

    @property
    def conn(self):
        if self._conn is None:
            self._conn = self._conn_src()
        return self._conn

    def bind_commit_log(self, log) -> None:
        self.log = log

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        rows = [
            [jsonable(ev.values[c]) for c in self.columns] + [ev.time, ev.diff]
            for ev in events
        ]
        if self.log is None:
            cur = self.conn.cursor()
            for row in rows:
                cur.execute(self.stmt, row)
            self.conn.commit()
            return
        self._epochs.append((events[0].time, rows))

    # -- transactional protocol ------------------------------------------

    def _sink_key(self) -> str:
        return f"{self.table_name}/{self._worker_id}"

    def _ensure_commit_table(self, cur) -> None:
        cur.execute(_COMMIT_TABLE_DDL)

    def _db_frontier(self, cur) -> int:
        ph = self.placeholder
        cur.execute(
            f"SELECT frontier FROM __pathway_commit WHERE sink={ph}",
            [self._sink_key()],
        )
        row = cur.fetchone()
        return int(row[0]) if row else -1

    def prepare(self, frontier: int) -> None:
        import pickle

        ready = [(t, rows) for t, rows in self._epochs if t <= frontier]
        self._epochs = [(t, rows) for t, rows in self._epochs if t > frontier]
        self.log.stage(frontier, pickle.dumps(ready))

    def commit(self, frontier: int) -> None:
        self._finalize(frontier)

    def _finalize(self, frontier: int) -> None:
        import pickle

        cur = self.conn.cursor()
        self._ensure_commit_table(cur)
        db_frontier = self._db_frontier(cur)
        if db_frontier < frontier:
            # one transaction: staged epochs + the frontier row — atomic
            # with respect to any crash, conditional so re-runs are no-ops
            for _f, blob in self.log.read_staged(db_frontier, frontier):
                for _t, rows in pickle.loads(blob):
                    for row in rows:
                        cur.execute(self.stmt, row)
            ph = self.placeholder
            cur.execute(
                f"INSERT INTO __pathway_commit (sink, frontier) "
                f"VALUES ({ph}, {ph}) "
                f"ON CONFLICT (sink) DO UPDATE SET frontier=EXCLUDED.frontier",
                [self._sink_key(), frontier],
            )
            self.conn.commit()
        self.log.mark_committed(frontier)

    def recover(self, frontier: int) -> None:
        self._epochs.clear()
        if self.log is None:
            return
        self.log.rollback_to(frontier)
        if frontier >= 0:
            # re-run any finalize the crash interrupted (idempotent)
            self._finalize(frontier)

    def committed_frontier(self) -> int:
        return -1 if self.log is None else self.log.committed_frontier()

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()


class PostgresSnapshotWriter(OutputWriter):
    def __init__(self, connection, table_name: str, columns: Sequence[str], primary_key: Sequence[str], *, placeholder: str = "%s"):
        self.conn = connection
        self.columns = list(columns)
        self.primary_key = list(primary_key)
        self.upsert, self.delete = build_snapshot_statements(
            table_name, columns, primary_key, placeholder=placeholder
        )

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        cur = self.conn.cursor()
        # within one time, deletions before insertions so upserts win
        for ev in sorted(events, key=lambda e: e.diff):
            if ev.diff > 0:
                cur.execute(
                    self.upsert, [jsonable(ev.values[c]) for c in self.columns]
                )
            else:
                cur.execute(
                    self.delete,
                    [jsonable(ev.values[c]) for c in self.primary_key],
                )
        self.conn.commit()

    def close(self) -> None:
        self.conn.close()


def write(
    table,
    postgres_settings: dict,
    table_name: str,
    *,
    max_batch_size: int | None = None,
    init_mode: str = "default",
    name: str | None = None,
    _connection=None,
    _placeholder: str = "%s",
    **kwargs,
) -> None:
    """Append the change stream (with time/diff columns) to a Postgres table
    (reference: io/postgres write:22). Exactly-once when the run is
    persistent with operator snapshots enabled (see PostgresUpdatesWriter)."""
    conn = (
        _connection
        if _connection is not None
        else (lambda: _connect(postgres_settings))
    )
    attach_writer(
        table,
        PostgresUpdatesWriter(
            conn, table_name, table.column_names(), placeholder=_placeholder
        ),
        name=name,
    )


def write_snapshot(
    table,
    postgres_settings: dict,
    table_name: str,
    primary_key: list[str],
    *,
    max_batch_size: int | None = None,
    init_mode: str = "default",
    name: str | None = None,
    _connection=None,
    _placeholder: str = "%s",
    **kwargs,
) -> None:
    """Maintain the table as an up-to-date Postgres snapshot keyed by
    primary_key (reference: io/postgres write_snapshot:141)."""
    conn = _connection if _connection is not None else _connect(postgres_settings)
    attach_writer(
        table,
        PostgresSnapshotWriter(
            conn,
            table_name,
            table.column_names(),
            primary_key,
            placeholder=_placeholder,
        ),
        name=name,
    )
