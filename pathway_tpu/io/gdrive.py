"""pw.io.gdrive — Google Drive connector (reference:
python/pathway/io/gdrive — _GDriveClient:73, _GDriveTree:237,
_GDriveSubject:261; polls a folder tree, emits file payloads with metadata,
detects modifications and deletions).

The google-api-python-client is optional/gated; tests may inject a client
implementing `tree(root_id) -> {file_id: meta}` and `download(meta) -> bytes`
via `_client_factory`.
"""

from __future__ import annotations

import time as time_mod
from typing import Any, Dict, Optional

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)

_DEFAULT_MIME_TYPE_MAPPING = {
    "application/vnd.google-apps.document": (
        "application/vnd.openxmlformats-officedocument.wordprocessingml.document"
    ),
    "application/vnd.google-apps.spreadsheet": (
        "application/vnd.openxmlformats-officedocument.spreadsheetml.sheet"
    ),
    "application/vnd.google-apps.presentation": (
        "application/vnd.openxmlformats-officedocument.presentationml.presentation"
    ),
}


class _GDriveApiClient:
    """Thin adapter over googleapiclient (reference: _GDriveClient:73)."""

    def __init__(self, credentials_file: str):
        try:
            from google.oauth2.service_account import Credentials  # type: ignore
            from googleapiclient.discovery import build  # type: ignore
        except ImportError:
            raise ImportError(
                "pw.io.gdrive requires google-api-python-client and "
                "google-auth; install them or inject _client_factory"
            )
        creds = Credentials.from_service_account_file(
            credentials_file, scopes=["https://www.googleapis.com/auth/drive.readonly"]
        )
        self.service = build("drive", "v3", credentials=creds)

    def tree(self, root_id: str) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        stack = [root_id]
        while stack:
            parent = stack.pop()
            page_token = None
            while True:
                resp = (
                    self.service.files()
                    .list(
                        q=f"'{parent}' in parents and trashed = false",
                        fields="nextPageToken, files(id, name, mimeType, modifiedTime, size)",
                        pageToken=page_token,
                    )
                    .execute()
                )
                for f in resp.get("files", []):
                    if f["mimeType"] == "application/vnd.google-apps.folder":
                        stack.append(f["id"])
                    else:
                        out[f["id"]] = f
                page_token = resp.get("nextPageToken")
                if page_token is None:
                    break
        return out

    def download(self, meta: dict) -> bytes:
        mime = meta.get("mimeType", "")
        if mime in _DEFAULT_MIME_TYPE_MAPPING:
            req = self.service.files().export_media(
                fileId=meta["id"], mimeType=_DEFAULT_MIME_TYPE_MAPPING[mime]
            )
        else:
            req = self.service.files().get_media(fileId=meta["id"])
        return req.execute()


class _GDriveSubject(ConnectorSubjectBase):
    """(reference: _GDriveSubject:261 — poll loop with deletions)"""

    def __init__(self, client_factory, object_id, mode, refresh_interval, with_metadata):
        super().__init__()
        self.client_factory = client_factory
        self.object_id = object_id
        self.mode = mode
        self.refresh_interval = refresh_interval
        self.with_metadata = with_metadata
        self._seen: Dict[str, dict] = {}

    def _row(self, meta: dict, payload: bytes) -> dict:
        row = {"data": payload}
        if self.with_metadata:
            from pathway_tpu.engine.value import Json

            row["_metadata"] = Json(
                {
                    "id": meta.get("id"),
                    "name": meta.get("name"),
                    "mimeType": meta.get("mimeType"),
                    "modifiedTime": meta.get("modifiedTime"),
                    "seen_at": int(time_mod.time()),
                    "url": f"https://drive.google.com/file/d/{meta.get('id')}/view",
                    "status": "loaded",
                }
            )
        return row

    def run(self) -> None:
        client = self.client_factory()
        first_poll = True
        while True:
            tree = client.tree(self.object_id)
            changed = False
            cache = self._object_cache
            if first_poll and cache is not None:
                # files deleted while the pipeline was down never enter
                # _seen — reconcile the persistent cache against the
                # remote listing once so stale blobs don't accumulate
                for stale_id in set(cache.list_objects()) - set(tree):
                    cache.evict(stale_id)
            first_poll = False
            for fid, meta in tree.items():
                old = self._seen.get(fid)
                version = meta.get("modifiedTime")
                if old is not None and old["meta"].get("modifiedTime") == version:
                    continue
                # persistence-backed object cache: a restart re-serves
                # unchanged files without re-downloading (reference:
                # cached_object_storage.rs)
                payload = cache.get(fid, version) if cache is not None else None
                if payload is None:
                    payload = client.download(meta)
                    if cache is not None:
                        cache.put(fid, version, payload)
                if old is not None:
                    # retract the exact row emitted earlier (same seen_at)
                    self._remove(old["row"])
                row = self._row(meta, payload)
                self._seen[fid] = {"meta": meta, "row": row}
                self.next(**row)
                changed = True
            for fid in list(self._seen):
                if fid not in tree:
                    old = self._seen.pop(fid)
                    self._remove(old["row"])
                    if cache is not None:
                        cache.evict(fid)
                    changed = True
            if changed:
                self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    object_size_limit: int | None = None,
    service_user_credentials_file: str | None = None,
    with_metadata: bool = False,
    refresh_interval: float = 30.0,
    name: str | None = None,
    _client_factory=None,
    **kwargs,
):
    """Read files from a Drive folder/file id (reference: io/gdrive read)."""
    cols = {"data": ColumnSchema(name="data", dtype=dt.BYTES)}
    if with_metadata:
        cols["_metadata"] = ColumnSchema(name="_metadata", dtype=dt.JSON)
    schema = schema_from_columns(cols, name="GDriveSchema")
    if _client_factory is None:

        def _client_factory():
            return _GDriveApiClient(service_user_credentials_file)

    def factory():
        return _GDriveSubject(
            _client_factory, object_id, mode, refresh_interval, with_metadata
        )

    # stable default name: persistence scopes (input snapshots, the
    # source-object cache) must survive restarts, and the global
    # source_<n> counter does not
    return connector_table(
        schema, factory, mode=mode, name=name or f"gdrive_{object_id}"
    )
