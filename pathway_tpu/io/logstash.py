"""pw.io.logstash — Logstash sink (reference: python/pathway/io/logstash
write:17 — posts change-stream events to a Logstash HTTP input plugin).

Functional via `requests` (available in this image).
"""

from __future__ import annotations

import json
from typing import Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


class LogstashWriter(OutputWriter):
    def __init__(self, endpoint: str, *, _post=None):
        self.endpoint = endpoint
        if _post is None:
            import requests

            _post = requests.post
        self._post = _post

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        for ev in events:
            obj = {k: jsonable(v) for k, v in ev.values.items()}
            obj["time"] = ev.time
            obj["diff"] = ev.diff
            self._post(
                self.endpoint,
                data=json.dumps(obj),
                headers={"Content-Type": "application/json"},
            )


def write(table, endpoint: str, *, name: str | None = None, _post=None, **kwargs) -> None:
    """Send each delta as a JSON document to a Logstash HTTP input
    (reference: io/logstash write:17)."""
    attach_writer(table, LogstashWriter(endpoint, _post=_post), name=name)
