"""Minimal pure-python Avro Object Container File codec.

Supports the subset of Avro needed for spec-compliant Iceberg manifest /
manifest-list files (reference: src/connectors/data_lake/iceberg.rs writes
these through the iceberg-rust crate): null/boolean/int/long/float/double/
string/bytes primitives, records, unions, arrays and maps, with the
``null`` codec. Schema-driven generic encode/decode — field properties
such as Iceberg's ``field-id`` ride along untouched in the embedded
schema JSON.

Avro spec: https://avro.apache.org/docs/current/specification/ (binary
encoding + object container files). No third-party avro library ships in
this image, hence the self-contained implementation.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, List, Tuple

_MAGIC = b"Obj\x01"


# -- binary primitives -----------------------------------------------------


def _zigzag_encode(n: int) -> bytes:
    z = (n << 1) ^ (n >> 63)  # arithmetic shift: -1 mask for negatives
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_decode(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_bytes(out: bytearray, data: bytes) -> None:
    out += _zigzag_encode(len(data))
    out += data


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _zigzag_decode(buf)
    return buf.read(n)


# -- schema-driven generic encode/decode -----------------------------------


class _Types:
    """Resolves named-type references within one schema."""

    def __init__(self):
        self.named: Dict[str, Any] = {}

    def register(self, schema: Any) -> None:
        if isinstance(schema, dict) and schema.get("type") == "record":
            self.named[schema["name"]] = schema


def _encode(out: bytearray, schema: Any, value: Any, types: _Types) -> None:
    if isinstance(schema, str) and schema in types.named:
        schema = types.named[schema]
    if isinstance(schema, list):  # union
        for idx, branch in enumerate(schema):
            bname = branch if isinstance(branch, str) else branch.get("type")
            if value is None and bname == "null":
                out += _zigzag_encode(idx)
                return
            if value is not None and bname != "null":
                out += _zigzag_encode(idx)
                _encode(out, branch, value, types)
                return
        raise ValueError(f"value {value!r} fits no union branch {schema!r}")
    stype = schema if isinstance(schema, str) else schema["type"]
    if stype == "null":
        return
    if stype == "boolean":
        out.append(1 if value else 0)
    elif stype in ("int", "long"):
        out += _zigzag_encode(int(value))
    elif stype == "float":
        out += struct.pack("<f", float(value))
    elif stype == "double":
        out += struct.pack("<d", float(value))
    elif stype == "string":
        _write_bytes(out, str(value).encode("utf-8"))
    elif stype == "bytes":
        _write_bytes(out, bytes(value))
    elif stype == "record":
        types.register(schema)
        for field in schema["fields"]:
            fval = value.get(field["name"]) if isinstance(value, dict) else None
            if fval is None and "default" in field:
                fval = field["default"]
            _encode(out, field["type"], fval, types)
    elif stype == "array":
        items = list(value or [])
        if items:
            out += _zigzag_encode(len(items))
            for item in items:
                _encode(out, schema["items"], item, types)
        out += _zigzag_encode(0)
    elif stype == "map":
        entries = dict(value or {})
        if entries:
            out += _zigzag_encode(len(entries))
            for k, v in entries.items():
                _write_bytes(out, str(k).encode("utf-8"))
                _encode(out, schema["values"], v, types)
        out += _zigzag_encode(0)
    else:
        raise ValueError(f"unsupported Avro type {stype!r}")


def _decode(buf: io.BytesIO, schema: Any, types: _Types) -> Any:
    if isinstance(schema, str) and schema in types.named:
        schema = types.named[schema]
    if isinstance(schema, list):  # union
        idx = _zigzag_decode(buf)
        return _decode(buf, schema[idx], types)
    stype = schema if isinstance(schema, str) else schema["type"]
    if stype == "null":
        return None
    if stype == "boolean":
        return buf.read(1) != b"\x00"
    if stype in ("int", "long"):
        return _zigzag_decode(buf)
    if stype == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if stype == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if stype == "string":
        return _read_bytes(buf).decode("utf-8")
    if stype == "bytes":
        return _read_bytes(buf)
    if stype == "record":
        types.register(schema)
        return {
            field["name"]: _decode(buf, field["type"], types)
            for field in schema["fields"]
        }
    if stype == "array":
        items = []
        while True:
            n = _zigzag_decode(buf)
            if n == 0:
                break
            if n < 0:  # block with byte size prefix
                _zigzag_decode(buf)
                n = -n
            for _ in range(n):
                items.append(_decode(buf, schema["items"], types))
        return items
    if stype == "map":
        entries = {}
        while True:
            n = _zigzag_decode(buf)
            if n == 0:
                break
            if n < 0:
                _zigzag_decode(buf)
                n = -n
            for _ in range(n):
                k = _read_bytes(buf).decode("utf-8")
                entries[k] = _decode(buf, schema["values"], types)
        return entries
    raise ValueError(f"unsupported Avro type {stype!r}")


# -- object container files ------------------------------------------------


def write_ocf(
    path: str,
    schema: dict,
    records: List[dict],
    *,
    metadata: Dict[str, str] | None = None,
) -> None:
    """Write an Avro Object Container File with the null codec."""
    sync = os.urandom(16)
    out = bytearray()
    out += _MAGIC
    meta = {
        "avro.schema": json.dumps(schema),
        "avro.codec": "null",
        **(metadata or {}),
    }
    out += _zigzag_encode(len(meta))
    for k, v in meta.items():
        _write_bytes(out, k.encode("utf-8"))
        _write_bytes(out, v.encode("utf-8"))
    out += _zigzag_encode(0)
    out += sync
    if records:
        types = _Types()
        block = bytearray()
        for rec in records:
            _encode(block, schema, rec, types)
        out += _zigzag_encode(len(records))
        out += _zigzag_encode(len(block))
        out += block
        out += sync
    if hasattr(path, "write"):  # file-like sink (object-store lakes)
        path.write(bytes(out))
        return
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(bytes(out))
    os.rename(tmp, path)


def read_ocf(path) -> Tuple[dict, List[dict]]:
    """Read an Avro Object Container File (by path, bytes, or file-like);
    returns (schema, records)."""
    if isinstance(path, (bytes, bytearray)):
        buf = io.BytesIO(bytes(path))
        path = "<bytes>"
    elif hasattr(path, "read"):
        buf = io.BytesIO(path.read())
        path = "<stream>"
    else:
        with open(path, "rb") as fh:
            buf = io.BytesIO(fh.read())
    if buf.read(4) != _MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = _zigzag_decode(buf)
        if n == 0:
            break
        if n < 0:
            _zigzag_decode(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode("utf-8")
            meta[k] = _read_bytes(buf)
    codec = meta.get("avro.codec", b"null").decode()
    if codec != "null":
        raise ValueError(f"{path}: unsupported Avro codec {codec!r}")
    schema = json.loads(meta["avro.schema"])
    sync = buf.read(16)
    types = _Types()
    records: List[dict] = []
    while True:
        head = buf.read(1)
        if not head:
            break
        buf.seek(-1, io.SEEK_CUR)
        count = _zigzag_decode(buf)
        size = _zigzag_decode(buf)
        block = io.BytesIO(buf.read(size))
        for _ in range(count):
            records.append(_decode(block, schema, types))
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch")
    return schema, records
