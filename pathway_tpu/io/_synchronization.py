"""Input synchronization groups (reference:
python/pathway/io/_synchronization.py:17
register_input_synchronization_group; Rust side
src/connectors/synchronization.rs:499 — readers are throttled so that the
tracked column's values never diverge by more than `max_difference` across
the group's sources).

A source thread about to emit a row whose tracked value runs too far ahead
of the slowest source blocks until the others catch up — the same
backpressure the reference applies inside the Rust connector runtime.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class SynchronizationGroup:
    def __init__(self, max_difference):
        self.max_difference = max_difference
        self._cond = threading.Condition()
        self._frontier: Dict[Any, Any] = {}  # source -> max value emitted
        self._pending: Dict[Any, Any] = {}  # source -> value it waits to emit
        self._active: set = set()

    def add_source(self, source, column_name: str) -> None:
        with self._cond:
            self._active.add(source)
            self._frontier.setdefault(source, None)
        source.sync_group = self
        source.sync_column = column_name

    def source_closed(self, source) -> None:
        with self._cond:
            self._active.discard(source)
            self._cond.notify_all()

    def _may_emit(self, source, value) -> bool:
        if self._frontier.get(source) is None:
            # every source may deliver its first value unconditionally
            return True
        others = [
            f
            for s, f in self._frontier.items()
            if s is not source and s in self._active
        ]
        if any(f is None for f in others):
            # an active source hasn't delivered yet: hold the group back
            # until it does (reference: synchronization.rs waits for all
            # sources' first values before advancing the window)
            return False
        if not others:
            return True
        return value <= min(others) + self.max_difference

    def _all_blocked_and_i_am_min(self, source, value) -> bool:
        # every active source is parked in wait_for: nobody can catch up, so
        # the window must advance — release the smallest pending value first
        # (reference: synchronization.rs advances the group window when all
        # readers are waiting)
        others = self._active - {source}
        if not all(s in self._pending for s in others):
            return False
        pendings = [self._pending[s] for s in others if self._pending[s] is not None]
        return not pendings or value <= min(pendings)

    def wait_for(self, source, value) -> None:
        """Block the reader thread until emitting `value` keeps the group
        within max_difference (reference: synchronization.rs throttling)."""
        if value is None:
            return
        with self._cond:
            self._pending[source] = value
            try:
                while (
                    not self._may_emit(source, value)
                    and not self._all_blocked_and_i_am_min(source, value)
                    and self._active - {source}
                ):
                    self._cond.wait(timeout=0.5)
            finally:
                self._pending.pop(source, None)
            prev = self._frontier.get(source)
            if prev is None or value > prev:
                self._frontier[source] = value
            self._cond.notify_all()


def register_input_synchronization_group(
    *columns, max_difference, name: str | None = None
) -> SynchronizationGroup:
    """Align several input connectors on a shared column, e.g. event time
    (reference: io/_synchronization.py:17). Each argument is a
    ColumnReference on a connector-backed table; sources are throttled so
    the column's values across sources stay within `max_difference`.
    """
    group = SynchronizationGroup(max_difference)
    for column in columns:
        table = column.table
        live = getattr(table, "_live_source", None)
        if live is None:
            raise ValueError(
                "synchronization groups require connector-backed tables "
                "(pw.io.* read with streaming mode)"
            )
        group.add_source(live, column.name)
    return group
