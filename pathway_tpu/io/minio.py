"""pw.io.minio — MinIO connector (reference: python/pathway/io/minio
MinIOSettings:15, read:59 — S3-compatible endpoint routed through the S3
scanner)."""

from __future__ import annotations

from pathway_tpu.io.s3 import AwsS3Settings
from pathway_tpu.io.s3 import read as _s3_read


class MinIOSettings:
    """(reference: io/minio MinIOSettings:15)"""

    def __init__(
        self,
        endpoint: str,
        bucket_name: str,
        access_key: str,
        secret_access_key: str,
        *,
        with_path_style: bool = True,
        region: str | None = None,
    ):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region

    def create_aws_settings(self) -> AwsS3Settings:
        endpoint = self.endpoint
        if not endpoint.startswith("http"):
            endpoint = f"https://{endpoint}"
        return AwsS3Settings(
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            with_path_style=self.with_path_style,
            region=self.region,
            endpoint=endpoint,
        )


def read(
    path: str,
    minio_settings: MinIOSettings,
    *,
    format: str = "csv",
    schema=None,
    mode: str = "streaming",
    **kwargs,
):
    """Read from a MinIO bucket (reference: io/minio read:59)."""
    return _s3_read(
        path,
        aws_s3_settings=minio_settings.create_aws_settings(),
        format=format,
        schema=schema,
        mode=mode,
        **kwargs,
    )
