"""Shared message-queue connector machinery (kafka / redpanda / nats / mqtt).

TPU-native equivalent of the reference's Rust MQ reader/writer layer
(reference: src/connectors/data_storage.rs — Kafka via rdkafka, NATS via
async-nats, MQTT via rumqttc; topic routing at data_storage.rs:193). The
broker client is abstracted behind `MessageQueueClient`, so each backend
module supplies a thin adapter over its (optional, gated) client library,
and unit tests inject an in-memory fake broker.

Message payload parsing follows the reference's Parser taxonomy
(src/connectors/data_format.rs): raw (bytes), plaintext (utf-8 line),
json (JsonLinesParser:1630), dsv (DsvParser:522).
"""

from __future__ import annotations

import csv as csv_mod
import io as io_mod
import json
import time as time_mod
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import ColumnSchema, schema_from_columns
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)
from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


class MessageQueueClient:
    """Minimal broker-client interface.

    poll() -> iterable of (key: bytes|None, payload: bytes, meta: dict)
    messages available now (may block briefly); None when the stream is
    finished (static mode / closed broker).
    """

    def poll(self, timeout: float) -> Optional[Iterable[Tuple[Optional[bytes], bytes, dict]]]:
        raise NotImplementedError

    def produce(self, topic: str, key: Optional[bytes], payload: bytes) -> None:
        raise NotImplementedError

    def commit(self) -> None:
        pass

    def close(self) -> None:
        pass

    # persistence hooks: opaque resume cursor
    def position(self):
        return None

    def seek(self, position) -> None:
        pass


def raw_schema():
    return schema_from_columns(
        {"data": ColumnSchema(name="data", dtype=dt.BYTES)}, name="MQRawSchema"
    )


def plaintext_schema():
    return schema_from_columns(
        {"data": ColumnSchema(name="data", dtype=dt.STR)}, name="MQPlaintextSchema"
    )


def _coerce(v, dtype):
    core = dt.unoptionalize(dtype)
    if core is dt.JSON:
        from pathway_tpu.engine.value import Json

        return v if isinstance(v, Json) else Json(v)
    if core is dt.FLOAT and isinstance(v, int):
        return float(v)
    if isinstance(v, (dict, list)):
        from pathway_tpu.engine.value import Json

        return Json(v)
    return v


def parse_payload(
    payload: bytes,
    format: str,
    schema,
    *,
    delimiter: str = ",",
) -> Iterable[Dict[str, Any]]:
    """Parse one message payload into zero-or-more rows (reference parser
    dispatch: data_format.rs JsonLinesParser:1630 / DsvParser:522 /
    IdentityParser:894)."""
    if format == "raw":
        yield {"data": payload}
        return
    if format == "plaintext":
        yield {"data": payload.decode(errors="replace").rstrip("\n")}
        return
    if format == "json":
        names = set(schema.keys())
        for line in payload.decode(errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            yield {
                k: _coerce(v, schema[k].dtype) for k, v in obj.items() if k in names
            }
        return
    if format in ("dsv", "csv"):
        names = list(schema.keys())
        text = payload.decode(errors="replace")
        reader = csv_mod.reader(io_mod.StringIO(text), delimiter=delimiter)
        for rec in reader:
            if not rec:
                continue
            yield {
                k: _parse_text(v, schema[k].dtype)
                for k, v in zip(names, rec)
            }
        return
    raise ValueError(f"unknown message format {format!r}")


def _parse_text(text, dtype):
    core = dt.unoptionalize(dtype)
    try:
        if core is dt.INT:
            return int(text)
        if core is dt.FLOAT:
            return float(text)
        if core is dt.BOOL:
            return text.strip().lower() in ("true", "1", "yes", "on")
    except ValueError:
        return None
    return text


class MessageQueueSubject(ConnectorSubjectBase):
    """Reader thread: polls the broker client, parses, pushes rows
    (reference: Connector::run reader loop, src/connectors/mod.rs:523)."""

    def __init__(
        self,
        client_factory,
        format: str,
        schema,
        mode: str = "streaming",
        poll_timeout: float = 0.2,
        delimiter: str = ",",
    ):
        super().__init__()
        self.client_factory = client_factory
        self.format = format
        self.schema = schema
        self.mode = mode
        self.poll_timeout = poll_timeout
        self.delimiter = delimiter
        self._client = None
        self._resume_position = None

    def run(self) -> None:
        self._client = self.client_factory()
        if self._resume_position is not None:
            # resume from the persisted cursor instead of replaying the
            # stream (reference: Reader::seek, data_storage.rs:398)
            self._client.seek(self._resume_position)
        from pathway_tpu.internals.backoff import Backoff

        try:
            # transient broker hiccups: shared capped-exponential backoff
            # (surfaced as pathway_connector_retries / _backoff_seconds)
            # before a persistent failure kills the reader.  Full jitter
            # with a per-worker seed decorrelates workers that lost the
            # same broker (no thundering-herd reconnect); max_elapsed
            # bounds the total stall a flapping broker can cause.
            backoff = Backoff(
                base=0.05,
                cap=1.0,
                full_jitter=True,
                max_elapsed=5.0,
                seed=self._worker_id,
            )
            while True:
                try:
                    batch = self._client.poll(self.poll_timeout)
                except Exception:
                    if backoff.exhausted():
                        self.report_retry(0.0)
                        raise
                    delay = backoff.next_delay()
                    self.report_retry(delay)
                    time_mod.sleep(delay)
                    continue
                backoff.reset()
                if batch is None:
                    return  # stream finished
                got = False
                for key, payload, meta in batch:
                    got = True
                    for row in parse_payload(
                        payload,
                        self.format,
                        self.schema,
                        delimiter=self.delimiter,
                    ):
                        self.next(**row)
                if got:
                    self.commit()
                    self._client.commit()
                elif self.mode == "static":
                    return
        finally:
            self._client.close()

    def _persisted_state(self):
        if self._client is None:
            return None
        return {"position": self._client.position()}

    def _restore_persisted_state(self, state) -> None:
        if state and state.get("position") is not None:
            # applied when the client is created
            self._resume_position = state["position"]


def mq_read(
    client_factory,
    *,
    schema=None,
    format: str = "raw",
    mode: str = "streaming",
    name: str | None = None,
    delimiter: str = ",",
    partitioned: bool = False,
):
    if schema is None:
        schema = plaintext_schema() if format == "plaintext" else raw_schema()

    def factory():
        return MessageQueueSubject(
            client_factory, format, schema, mode=mode, delimiter=delimiter
        )

    # partitioned (kafka/redpanda consumer groups): each worker reads a
    # disjoint partition subset and rows are scatter-exchanged to owners.
    # Broadcast subscriptions (nats/mqtt) stay replicated: every worker sees
    # every message and keeps only its key shard.
    return connector_table(
        schema, factory, mode=mode, name=name, partitioned=partitioned
    )


class MessageQueueOutputWriter(OutputWriter):
    """Formats each delta as a message and produces to a topic (reference:
    Kafka/NATS/MQTT writers in data_storage.rs; JsonLines formatter
    data_format.rs:2059).

    Under a persistent run with snapshots enabled, epochs buffer until
    the snapshot-aligned commit: `prepare(F)` durably stages messages
    <= F in the SinkCommitLog before the manifest, `commit(F)` produces
    every staged epoch past the log's committed frontier and then
    advances the marker.  Replayed epochs <= the committed frontier are
    suppressed on resume (they are never re-staged).  Brokers without
    transactions leave one race — a crash between the final produce and
    the marker write re-produces that window on recovery — so the MQ
    sink is exactly-once up to that documented at-least-once edge.
    """

    def __init__(self, client, topic: str, *, format: str = "json", key_column: str | None = None):
        self.client = client
        self.topic = topic
        self.format = format
        self.key_column = key_column
        self.log = None
        self._epochs: list = []

    transactional = True

    def bind_commit_log(self, log) -> None:
        self.log = log

    def _messages(self, events: Sequence[RowEvent]) -> list:
        msgs = []
        for ev in events:
            obj = {k: jsonable(v) for k, v in ev.values.items()}
            obj["time"] = ev.time
            obj["diff"] = ev.diff
            payload = json.dumps(obj).encode()
            key = None
            if self.key_column is not None:
                kv = ev.values.get(self.key_column)
                key = str(jsonable(kv)).encode() if kv is not None else None
            msgs.append((key, payload))
        return msgs

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        msgs = self._messages(events)
        if self.log is None:
            for key, payload in msgs:
                self.client.produce(self.topic, key, payload)
            return
        self._epochs.append((events[0].time, msgs))

    def prepare(self, frontier: int) -> None:
        import pickle

        ready = [(t, m) for t, m in self._epochs if t <= frontier]
        self._epochs = [(t, m) for t, m in self._epochs if t > frontier]
        self.log.stage(frontier, pickle.dumps(ready))

    def commit(self, frontier: int) -> None:
        self._finalize(frontier)

    def _finalize(self, frontier: int) -> None:
        import pickle

        committed = self.log.committed_frontier()
        for _f, blob in self.log.read_staged(committed, frontier):
            for _t, msgs in pickle.loads(blob):
                for key, payload in msgs:
                    self.client.produce(self.topic, key, payload)
        self.client.commit()
        self.log.mark_committed(frontier)

    def recover(self, frontier: int) -> None:
        self._epochs.clear()
        if self.log is None:
            return
        self.log.rollback_to(frontier)
        if frontier >= 0:
            self._finalize(frontier)

    def committed_frontier(self) -> int:
        return -1 if self.log is None else self.log.committed_frontier()

    def flush(self) -> None:
        if self.log is None:
            self.client.commit()

    def close(self) -> None:
        self.client.close()


def mq_write(table, client, topic: str, *, format: str = "json", key_column: str | None = None, name: str | None = None) -> None:
    attach_writer(
        table,
        MessageQueueOutputWriter(client, topic, format=format, key_column=key_column),
        name=name,
    )
