"""pw.io.subscribe — change callbacks (reference:
python/pathway/io/_subscribe.py:16, engine subscribe_table)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.parse_graph import G

# callback type aliases (reference: io/_subscribe.py OnChangeCallback /
# OnFinishCallback — used in signatures and exported for user typing)
OnChangeCallback = Callable[[Any, dict, int, bool], Any]
OnFinishCallback = Callable[[], Any]


def subscribe(
    table,
    on_change: Callable | None = None,
    on_end: Callable | None = None,
    on_time_end: Callable | None = None,
    *,
    skip_persisted_batch: bool = True,
    name: str | None = None,
    sort_by=None,
    on_worker: int | None = None,
) -> None:
    """Register callbacks on table changes. on_change(key, row, time,
    is_addition) fires per delta; on_time_end(time) per closed batch;
    on_end() at end of stream.

    ``on_worker``: multi-worker runs gather the stream onto that worker and
    fire the callbacks only there (REST responders must complete pending
    futures in the process that holds them); default fires per-shard on
    every worker."""
    column_names = table.column_names()

    def attach(ctx, nodes):
        from pathway_tpu.engine.engine import SubscribeNode

        (node,) = nodes
        if on_worker is not None and ctx.engine.worker_count > 1:
            from pathway_tpu.engine.exchange import exchange_to_worker

            node = exchange_to_worker(ctx.engine, node, on_worker)
        SubscribeNode(
            ctx.engine,
            node,
            on_change=on_change,
            on_time_end=on_time_end,
            on_end=on_end,
            column_names=column_names,
            sink_name=name,
        )

    G.add_sink([table], attach)
