"""pw.io.questdb — QuestDB sink (reference: python/pathway/io/questdb
write:17; Rust QuestDB writer in src/connectors/data_storage.rs).

Implemented over QuestDB's InfluxDB line protocol (ILP) on a plain TCP
socket — no client library needed, so this sink is fully functional with
the stdlib and unit-testable against a local socket server.
"""

from __future__ import annotations

import socket
from typing import Any, Sequence

from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable


def _escape_tag(s: str) -> str:
    return s.replace("\\", "\\\\").replace(",", "\\,").replace(" ", "\\ ").replace("=", "\\=")


def _field_value(v: Any) -> str:
    if isinstance(v, bool):
        return "t" if v else "f"
    if isinstance(v, int):
        return f"{v}i"
    if isinstance(v, float):
        return repr(v)
    s = str(v).replace("\\", "\\\\").replace('"', '\\"')
    return f'"{s}"'


def format_ilp_line(
    table_name: str, values: dict, time: int, diff: int, *, designated_ts: str | None = None
) -> str:
    """One ILP line: measurement fields [timestamp] (QuestDB ILP docs;
    reference writer behavior: appends time/diff columns)."""
    fields = {k: v for k, v in values.items() if v is not None}
    ts = None
    if designated_ts is not None and designated_ts in fields:
        ts = fields.pop(designated_ts)
    parts = [
        f"{k}={_field_value(jsonable(v))}" for k, v in fields.items()
    ]
    parts.append(f"time={time}i")
    parts.append(f"diff={diff}i")
    line = f"{_escape_tag(table_name)} {','.join(parts)}"
    if ts is not None:
        line += f" {int(ts)}"
    return line


class QuestDBWriter(OutputWriter):
    def __init__(self, host: str, port: int, table_name: str, *, designated_ts: str | None = None, _sock=None):
        self.table_name = table_name
        self.designated_ts = designated_ts
        self._sock = _sock or socket.create_connection((host, port))

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        lines = [
            format_ilp_line(
                self.table_name,
                ev.values,
                ev.time,
                ev.diff,
                designated_ts=self.designated_ts,
            )
            for ev in events
        ]
        self._sock.sendall(("\n".join(lines) + "\n").encode())

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def write(
    table,
    connection_string_or_host: str | None = None,
    table_name: str | None = None,
    *,
    host: str | None = None,
    port: int = 9009,
    designated_timestamp=None,
    name: str | None = None,
    _sock=None,
    **kwargs,
) -> None:
    """Stream the change stream into QuestDB over ILP/TCP (reference:
    io/questdb write:17)."""
    host = host or connection_string_or_host or "localhost"
    ts = getattr(designated_timestamp, "name", designated_timestamp)
    attach_writer(
        table,
        QuestDBWriter(host, port, table_name, designated_ts=ts, _sock=_sock),
        name=name,
    )
