"""pw.io.deltalake — Delta Lake connector (reference:
python/pathway/io/deltalake read:290, write:466; Rust implementation
src/connectors/data_lake/delta.rs — CDC-style snapshot maintenance, Arrow
conversion, column buffering in data_lake/buffering.rs).

Implemented natively over pyarrow.parquet + the Delta transaction-log
protocol (`_delta_log/<version>.json` with protocol/metaData/add/remove
actions), so tables round-trip without the deltalake crate and simple
append-only tables interoperate with other Delta readers. The change stream
is written with the reference's extra columns `time` and `diff`.
"""

from __future__ import annotations

import json
import time as time_mod
from typing import Any, Dict, List, Optional, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)
from pathway_tpu.io._lake_fs import (
    LakeFS,
    as_fs as _as_fs,
    read_parquet as _read_parquet,
    resolve_lake_fs,
    write_parquet as _write_parquet,
)
from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable

_LOG_DIR = "_delta_log"

_DELTA_TYPES = {
    dt.INT: "long",
    dt.FLOAT: "double",
    dt.STR: "string",
    dt.BOOL: "boolean",
    dt.BYTES: "binary",
}


def _delta_type(dtype) -> str:
    core = dt.unoptionalize(dtype)
    return _DELTA_TYPES.get(core, "string")


def _schema_string(column_types: Dict[str, Any]) -> str:
    return json.dumps(
        {
            "type": "struct",
            "fields": [
                {
                    "name": name,
                    "type": _delta_type(dtype),
                    "nullable": True,
                    "metadata": {},
                }
                for name, dtype in column_types.items()
            ],
        }
    )


def _log_path(version: int) -> str:
    return f"{_LOG_DIR}/{version:020d}.json"


def _list_versions(fs: LakeFS) -> List[int]:
    fs = _as_fs(fs)
    out = []
    for f in fs.listdir(_LOG_DIR):
        if f.endswith(".json"):
            try:
                out.append(int(f[: -len(".json")]))
            except ValueError:
                continue
    return sorted(out)


def _read_actions(fs: LakeFS, version: int) -> List[dict]:
    fs = _as_fs(fs)
    text = fs.read_bytes(_log_path(version)).decode("utf-8")
    return [json.loads(line) for line in text.splitlines() if line.strip()]


def _write_commit(fs: LakeFS, actions: List[dict]) -> int:
    versions = _list_versions(fs)
    version = (versions[-1] + 1) if versions else 0
    # every commit carries a timestamp so readers can seek by time
    # (reference: delta.rs:720-733 version_timestamp)
    stamped = [{"commitInfo": {"timestamp": int(time_mod.time() * 1000)}}]
    stamped += [a for a in actions if "commitInfo" not in a]
    payload = "".join(json.dumps(a) + "\n" for a in stamped)
    fs.write_bytes(_log_path(version), payload.encode("utf-8"))
    return version


def _version_timestamp_ms(fs: LakeFS, version: int) -> int | None:
    """Commit timestamp of a version: commitInfo when present, file mtime
    otherwise (reference: snapshot.version_timestamp, delta.rs:708).
    Returns None when the backend has neither (foreign-written table on
    an object store) — callers must NOT treat unknown as epoch 0."""
    try:
        for action in _read_actions(fs, version):
            info = action.get("commitInfo")
            if info and "timestamp" in info:
                return int(info["timestamp"])
    except (OSError, FileNotFoundError):
        pass
    m = fs.mtime(_log_path(version))
    return None if m is None else int(m * 1000)


def _live_files(fs: LakeFS, up_to_version: int | None = None) -> List[str]:
    """Replay the log: the add-minus-remove file set at a version."""
    fs = _as_fs(fs)
    live: Dict[str, bool] = {}
    for v in _list_versions(fs):
        if up_to_version is not None and v > up_to_version:
            break
        for action in _read_actions(fs, v):
            if "add" in action:
                live[action["add"]["path"]] = True
            elif "remove" in action:
                live.pop(action["remove"]["path"], None)
    return list(live)


def _create_table_if_absent(
    fs: LakeFS, column_types: Dict[str, Any], extra_cols: List[tuple]
) -> bool:
    """Version-0 protocol/metaData commit for a fresh table. Returns True
    when the table already existed."""
    fs.makedirs("")
    if _list_versions(fs):
        return True
    _write_commit(
        fs,
        [
            {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
            {
                "metaData": {
                    "id": f"pathway-tpu-{int(time_mod.time() * 1000)}",
                    "format": {"provider": "parquet", "options": {}},
                    "schemaString": _schema_string(
                        dict(list(column_types.items()) + extra_cols)
                    ),
                    "partitionColumns": [],
                    "configuration": {},
                    "createdTime": int(time_mod.time() * 1000),
                }
            },
        ],
    )
    return False


class DeltaTableWriter(OutputWriter):
    """Appends one parquet file + one Delta commit per closed engine time
    (reference: data_lake/writer.rs + buffering.rs)."""

    def __init__(
        self,
        uri: str | LakeFS,
        column_types: Dict[str, Any],
        *,
        min_commit_frequency=None,
    ):
        import pyarrow  # noqa: F401  (hard requirement for the lake writers)

        self.fs = uri if isinstance(uri, LakeFS) else resolve_lake_fs(uri)
        self.column_types = dict(column_types)
        _create_table_if_absent(
            self.fs, self.column_types, [("time", dt.INT), ("diff", dt.INT)]
        )
        self._file_counter = 0

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        import pyarrow as pa

        cols: Dict[str, list] = {name: [] for name in self.column_types}
        cols["time"] = []
        cols["diff"] = []
        for ev in events:
            for name in self.column_types:
                cols[name].append(jsonable(ev.values.get(name)))
            cols["time"].append(ev.time)
            cols["diff"].append(ev.diff)
        table = pa.table(cols)
        self._file_counter += 1
        fname = f"part-{int(time_mod.time() * 1e6)}-{self._file_counter:05d}.parquet"
        size = _write_parquet(self.fs, fname, table)
        _write_commit(
            self.fs,
            [
                {
                    "add": {
                        "path": fname,
                        "partitionValues": {},
                        "size": size,
                        "modificationTime": int(time_mod.time() * 1000),
                        "dataChange": True,
                    }
                }
            ],
        )


class DeltaSnapshotWriter(OutputWriter):
    """CDC-style snapshot maintenance: the table always holds the current
    state keyed by ``_id`` (reference: buffering.rs SnapshotColumnBuffer:86,
    delta.rs — append-only batches append a parquet file; any batch with a
    deletion rewrites the full snapshot, removing all prior files in the
    same commit)."""

    def __init__(self, uri: str | LakeFS, column_types: Dict[str, Any]):
        import pyarrow  # noqa: F401

        self.fs = uri if isinstance(uri, LakeFS) else resolve_lake_fs(uri)
        self.column_types = dict(column_types)
        self._file_counter = 0
        # key -> row dict (current table state)
        self.state: Dict[Any, Dict[str, Any]] = {}
        # live parquet files, tracked in memory so a rewrite commit does
        # not replay the whole transaction log (one replay at startup)
        self._live: List[str] = []
        existed = _create_table_if_absent(
            self.fs, self.column_types, [("_id", dt.STR)]
        )
        if existed:
            self._restore_state()

    def _restore_state(self) -> None:
        """Resume onto an existing table: its current content is the
        initial snapshot (reference: buffering.rs new_for_delta_table)."""
        self._live = _live_files(self.fs)
        for fname in self._live:
            try:
                table = _read_parquet(self.fs, fname)
            except FileNotFoundError:
                continue
            for rec in table.to_pylist():
                key = rec.get("_id")
                if key is not None:
                    self.state[key] = rec

    def _new_file(self, rows: List[Dict[str, Any]]) -> tuple[str, int]:
        import pyarrow as pa

        cols: Dict[str, list] = {name: [] for name in self.column_types}
        cols["_id"] = []
        for row in rows:
            for name in self.column_types:
                cols[name].append(jsonable(row.get(name)))
            cols["_id"].append(row["_id"])
        self._file_counter += 1
        fname = (
            f"part-{int(time_mod.time() * 1e6)}-{self._file_counter:05d}"
            ".parquet"
        )
        size = _write_parquet(self.fs, fname, pa.table(cols))
        return fname, size

    @staticmethod
    def _add_action(fname: str, size: int) -> dict:
        return {
            "add": {
                "path": fname,
                "partitionValues": {},
                "size": size,
                "modificationTime": int(time_mod.time() * 1000),
                "dataChange": True,
            }
        }

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        appended: List[Dict[str, Any]] = []
        only_appends = True
        for ev in events:
            key = str(ev.key)
            if ev.diff > 0:
                row = dict(ev.values)
                row["_id"] = key
                self.state[key] = row
                appended.append(row)
            else:
                only_appends = False
                self.state.pop(key, None)
        if not events:
            return
        if only_appends:
            if not appended:
                return
            fname, size = self._new_file(appended)
            self._live.append(fname)
            _write_commit(self.fs, [self._add_action(fname, size)])
            return
        # a deletion occurred: rewrite the whole snapshot in one commit
        actions = [
            {
                "remove": {
                    "path": f,
                    "deletionTimestamp": int(time_mod.time() * 1000),
                    "dataChange": True,
                }
            }
            for f in self._live
        ]
        fname, size = self._new_file(list(self.state.values()))
        self._live = [fname]
        actions.append(self._add_action(fname, size))
        _write_commit(self.fs, actions)


def write(
    table,
    uri: str,
    *,
    schema=None,
    partition_columns=None,
    min_commit_frequency: int | None = 60_000,
    output_table_type: str = "stream_of_changes",
    s3_connection_settings=None,
    name: str | None = None,
    _object_client=None,
    **kwargs,
) -> None:
    """Write to a Delta table (reference: io/deltalake write:466).

    ``output_table_type="stream_of_changes"`` appends the change stream
    with ``time``/``diff`` columns; ``"snapshot"`` maintains the current
    table state keyed by ``_id`` (reference: deltalake/__init__.py:477,
    snapshot_maintenance_on_output). ``uri`` may be a local path or an
    ``s3://`` / ``az://`` object-store location; S3 credentials come from
    ``s3_connection_settings`` (an ``pw.io.s3.AwsS3Settings``), matching
    the reference's storage-options plumbing (delta.rs:215,273)."""
    fs = resolve_lake_fs(
        uri,
        s3_connection_settings=s3_connection_settings,
        _object_client=_object_client,
    )
    column_types = {
        c: table.schema[c].dtype if c in table.schema.keys() else dt.ANY
        for c in table.column_names()
    }
    if output_table_type == "snapshot":
        writer: OutputWriter = DeltaSnapshotWriter(fs, column_types)
    elif output_table_type == "stream_of_changes":
        writer = DeltaTableWriter(
            fs, column_types, min_commit_frequency=min_commit_frequency
        )
    else:
        raise ValueError(
            "output_table_type must be 'stream_of_changes' or 'snapshot', "
            f"got {output_table_type!r}"
        )
    attach_writer(table, writer, name=name)


class _DeltaSubject(ConnectorSubjectBase):
    """Replays the transaction log, then polls for new versions (reference:
    io/deltalake read:290 — streaming mode follows appends)."""

    def __init__(
        self,
        uri,
        schema,
        mode,
        refresh_interval,
        has_diff: bool,
        start_from_timestamp_ms: int | None = None,
    ):
        super().__init__()
        self.fs = uri if isinstance(uri, LakeFS) else resolve_lake_fs(uri)
        self.schema = schema
        self.mode = mode
        self.refresh_interval = refresh_interval
        self.has_diff = has_diff
        self.start_from_timestamp_ms = start_from_timestamp_ms
        self._next_version = 0
        self._seeked = False

    def _seek_to_timestamp(self) -> None:
        """Skip every version at or before the requested timestamp
        (reference: delta.rs:707-741 — load last version below threshold,
        clear the file queue, stream only later changes)."""
        if self.start_from_timestamp_ms is None:
            return
        last_below = None
        for v in _list_versions(self.fs):
            ts = _version_timestamp_ms(self.fs, v)
            # unknown timestamp: conservatively treat the version as
            # after the threshold (re-reading beats silent data loss)
            if ts is not None and ts <= self.start_from_timestamp_ms:
                last_below = v
            else:
                break
        if last_below is not None:
            self._next_version = last_below + 1

    def _emit_file(self, fname: str, sign: int) -> None:
        names = list(self.schema.keys())
        table = _read_parquet(self.fs, fname)
        data = table.to_pylist()
        for rec in data:
            row = {
                k: _coerce_delta(rec.get(k), self.schema[k].dtype)
                for k in names
                if k in rec
            }
            diff = rec.get("diff", 1) if self.has_diff else 1
            if diff * sign > 0:
                self.next(**row)
            else:
                self._remove(row)

    def _apply_new_versions(self) -> bool:
        versions = [v for v in _list_versions(self.fs) if v >= self._next_version]
        changed = False
        for v in versions:
            for action in _read_actions(self.fs, v):
                if "add" in action:
                    self._emit_file(action["add"]["path"], 1)
                    changed = True
                elif "remove" in action:
                    fname = action["remove"]["path"]
                    try:
                        self._emit_file(fname, -1)
                        changed = True
                    except FileNotFoundError:
                        pass  # data file already vacuumed
            self._next_version = v + 1
        return changed

    def run(self) -> None:
        if not self._seeked:
            # persisted state wins over the timestamp seek on resume
            if self._next_version == 0:
                self._seek_to_timestamp()
            self._seeked = True
        while True:
            if self._apply_new_versions():
                self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        return {"next_version": self._next_version}

    def _restore_persisted_state(self, state) -> None:
        if state:
            self._next_version = state.get("next_version", 0)


def _coerce_delta(v, dtype):
    core = dt.unoptionalize(dtype)
    if v is None:
        return None
    if core is dt.FLOAT and isinstance(v, int):
        return float(v)
    return v


def read(
    uri: str,
    schema,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 0.5,
    start_from_timestamp_ms: int | None = None,
    s3_connection_settings=None,
    name: str | None = None,
    _has_diff_column: bool = True,
    _object_client=None,
    **kwargs,
):
    """Read a Delta table as a (streaming) table (reference: io/deltalake
    read:290). Rows carrying a `diff` column are interpreted as a change
    stream; otherwise every row is an insertion. With
    ``start_from_timestamp_ms``, only changes committed after the given
    timestamp are read (reference: deltalake/__init__.py:298,
    delta.rs:707). ``uri`` may be local or ``s3://`` / ``az://`` with
    credentials via ``s3_connection_settings``."""
    fs = resolve_lake_fs(
        uri,
        s3_connection_settings=s3_connection_settings,
        _object_client=_object_client,
    )

    def factory():
        return _DeltaSubject(
            fs,
            schema,
            mode,
            refresh_interval,
            _has_diff_column,
            start_from_timestamp_ms=start_from_timestamp_ms,
        )

    return connector_table(schema, factory, mode=mode, name=name)
