"""pw.io.deltalake — Delta Lake connector (reference:
python/pathway/io/deltalake read:290, write:466; Rust implementation
src/connectors/data_lake/delta.rs — CDC-style snapshot maintenance, Arrow
conversion, column buffering in data_lake/buffering.rs).

Implemented natively over pyarrow.parquet + the Delta transaction-log
protocol (`_delta_log/<version>.json` with protocol/metaData/add/remove
actions), so tables round-trip without the deltalake crate and simple
append-only tables interoperate with other Delta readers. The change stream
is written with the reference's extra columns `time` and `diff`.
"""

from __future__ import annotations

import json
import os
import time as time_mod
from typing import Any, Dict, List, Optional, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)
from pathway_tpu.io._writer import OutputWriter, RowEvent, attach_writer, jsonable

_LOG_DIR = "_delta_log"

_DELTA_TYPES = {
    dt.INT: "long",
    dt.FLOAT: "double",
    dt.STR: "string",
    dt.BOOL: "boolean",
    dt.BYTES: "binary",
}


def _delta_type(dtype) -> str:
    core = dt.unoptionalize(dtype)
    return _DELTA_TYPES.get(core, "string")


def _schema_string(column_types: Dict[str, Any]) -> str:
    return json.dumps(
        {
            "type": "struct",
            "fields": [
                {
                    "name": name,
                    "type": _delta_type(dtype),
                    "nullable": True,
                    "metadata": {},
                }
                for name, dtype in column_types.items()
            ],
        }
    )


def _log_path(uri: str, version: int) -> str:
    return os.path.join(uri, _LOG_DIR, f"{version:020d}.json")


def _list_versions(uri: str) -> List[int]:
    log_dir = os.path.join(uri, _LOG_DIR)
    if not os.path.isdir(log_dir):
        return []
    out = []
    for f in os.listdir(log_dir):
        if f.endswith(".json"):
            try:
                out.append(int(f[: -len(".json")]))
            except ValueError:
                continue
    return sorted(out)


def _read_actions(uri: str, version: int) -> List[dict]:
    with open(_log_path(uri, version)) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _write_commit(uri: str, actions: List[dict]) -> int:
    os.makedirs(os.path.join(uri, _LOG_DIR), exist_ok=True)
    versions = _list_versions(uri)
    version = (versions[-1] + 1) if versions else 0
    path = _log_path(uri, version)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        for action in actions:
            fh.write(json.dumps(action) + "\n")
    os.rename(tmp, path)  # atomic publish of the commit
    return version


class DeltaTableWriter(OutputWriter):
    """Appends one parquet file + one Delta commit per closed engine time
    (reference: data_lake/writer.rs + buffering.rs)."""

    def __init__(self, uri: str, column_types: Dict[str, Any], *, min_commit_frequency=None):
        import pyarrow  # noqa: F401  (hard requirement for the lake writers)

        self.uri = uri
        self.column_types = dict(column_types)
        os.makedirs(uri, exist_ok=True)
        if not _list_versions(uri):
            _write_commit(
                uri,
                [
                    {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
                    {
                        "metaData": {
                            "id": f"pathway-tpu-{int(time_mod.time() * 1000)}",
                            "format": {"provider": "parquet", "options": {}},
                            "schemaString": _schema_string(
                                dict(
                                    list(self.column_types.items())
                                    + [("time", dt.INT), ("diff", dt.INT)]
                                )
                            ),
                            "partitionColumns": [],
                            "configuration": {},
                            "createdTime": int(time_mod.time() * 1000),
                        }
                    },
                ],
            )
        self._file_counter = 0

    def write_batch(self, events: Sequence[RowEvent]) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols: Dict[str, list] = {name: [] for name in self.column_types}
        cols["time"] = []
        cols["diff"] = []
        for ev in events:
            for name in self.column_types:
                cols[name].append(jsonable(ev.values.get(name)))
            cols["time"].append(ev.time)
            cols["diff"].append(ev.diff)
        table = pa.table(cols)
        self._file_counter += 1
        fname = f"part-{int(time_mod.time() * 1e6)}-{self._file_counter:05d}.parquet"
        fpath = os.path.join(self.uri, fname)
        pq.write_table(table, fpath)
        _write_commit(
            self.uri,
            [
                {
                    "add": {
                        "path": fname,
                        "partitionValues": {},
                        "size": os.path.getsize(fpath),
                        "modificationTime": int(time_mod.time() * 1000),
                        "dataChange": True,
                    }
                }
            ],
        )


def write(
    table,
    uri: str,
    *,
    schema=None,
    partition_columns=None,
    min_commit_frequency: int | None = 60_000,
    name: str | None = None,
    **kwargs,
) -> None:
    """Write the change stream to a Delta table (reference: io/deltalake
    write:466)."""
    column_types = {
        c: table.schema[c].dtype if c in table.schema.keys() else dt.ANY
        for c in table.column_names()
    }
    attach_writer(
        table,
        DeltaTableWriter(uri, column_types, min_commit_frequency=min_commit_frequency),
        name=name,
    )


class _DeltaSubject(ConnectorSubjectBase):
    """Replays the transaction log, then polls for new versions (reference:
    io/deltalake read:290 — streaming mode follows appends)."""

    def __init__(self, uri, schema, mode, refresh_interval, has_diff: bool):
        super().__init__()
        self.uri = uri
        self.schema = schema
        self.mode = mode
        self.refresh_interval = refresh_interval
        self.has_diff = has_diff
        self._next_version = 0

    def _emit_file(self, fname: str, sign: int) -> None:
        import pyarrow.parquet as pq

        names = list(self.schema.keys())
        table = pq.read_table(os.path.join(self.uri, fname))
        data = table.to_pylist()
        for rec in data:
            row = {
                k: _coerce_delta(rec.get(k), self.schema[k].dtype)
                for k in names
                if k in rec
            }
            diff = rec.get("diff", 1) if self.has_diff else 1
            if diff * sign > 0:
                self.next(**row)
            else:
                self._remove(row)

    def _apply_new_versions(self) -> bool:
        versions = [v for v in _list_versions(self.uri) if v >= self._next_version]
        changed = False
        for v in versions:
            for action in _read_actions(self.uri, v):
                if "add" in action:
                    self._emit_file(action["add"]["path"], 1)
                    changed = True
                elif "remove" in action:
                    fname = action["remove"]["path"]
                    if os.path.exists(os.path.join(self.uri, fname)):
                        self._emit_file(fname, -1)
                        changed = True
            self._next_version = v + 1
        return changed

    def run(self) -> None:
        while True:
            if self._apply_new_versions():
                self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        return {"next_version": self._next_version}

    def _restore_persisted_state(self, state) -> None:
        if state:
            self._next_version = state.get("next_version", 0)


def _coerce_delta(v, dtype):
    core = dt.unoptionalize(dtype)
    if v is None:
        return None
    if core is dt.FLOAT and isinstance(v, int):
        return float(v)
    return v


def read(
    uri: str,
    schema,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval: float = 0.5,
    name: str | None = None,
    _has_diff_column: bool = True,
    **kwargs,
):
    """Read a Delta table as a (streaming) table (reference: io/deltalake
    read:290). Rows carrying a `diff` column are interpreted as a change
    stream; otherwise every row is an insertion."""

    def factory():
        return _DeltaSubject(uri, schema, mode, refresh_interval, _has_diff_column)

    return connector_table(schema, factory, mode=mode, name=name)
