"""pw.io.kafka — Kafka connector (reference: python/pathway/io/kafka —
read:29, simple_read:261, write:360; Rust side: rdkafka-backed
StorageType::Kafka, src/connectors/data_storage.rs).

The broker client library (confluent_kafka / kafka-python) is optional and
gated; tests and embedded uses may inject any `MessageQueueClient` via the
private `_client_factory` / `_client` parameters.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

from pathway_tpu.io import _mq


def _load_confluent():
    try:
        import confluent_kafka  # type: ignore

        return confluent_kafka
    except ImportError:
        return None


def _load_kafka_python():
    try:
        import kafka  # type: ignore

        return kafka
    except ImportError:
        return None


class _ConfluentClient(_mq.MessageQueueClient):
    """Adapter over confluent_kafka Consumer/Producer."""

    def __init__(self, rdkafka_settings: dict, topics, *, for_read: bool):
        ck = _load_confluent()
        if ck is None:
            raise ImportError(
                "pw.io.kafka requires the confluent_kafka (or kafka-python) "
                "package; install one, or inject a client via _client_factory"
            )
        self._ck = ck
        self.topics = [topics] if isinstance(topics, str) else list(topics or [])
        if for_read:
            self.consumer = ck.Consumer(rdkafka_settings)
            self.consumer.subscribe(self.topics)
            self.producer = None
        else:
            self.consumer = None
            self.producer = ck.Producer(rdkafka_settings)

    def poll(self, timeout: float):
        msg = self.consumer.poll(timeout)
        if msg is None:
            return []
        err = msg.error()
        if err is not None:
            if err.code() == self._ck.KafkaError._PARTITION_EOF:
                return []  # benign end-of-partition event
            raise RuntimeError(f"kafka consumer error: {err}")
        return [(msg.key(), msg.value(), {"partition": msg.partition(), "offset": msg.offset()})]

    def produce(self, topic, key, payload):
        self.producer.produce(topic, value=payload, key=key)

    def commit(self):
        if self.producer is not None:
            self.producer.flush()

    def close(self):
        if self.consumer is not None:
            self.consumer.close()
        if self.producer is not None:
            self.producer.flush()


def read(
    rdkafka_settings: dict,
    topic: str | list[str] | None = None,
    *,
    schema=None,
    format: str = "raw",
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    topic_names: list[str] | None = None,
    _client_factory=None,
    **kwargs,
):
    """Read a Kafka topic as a streaming table (reference: io/kafka read:29).

    format: "raw" (bytes in `data`), "plaintext", "json", "dsv".
    """
    topics = topic if topic is not None else topic_names
    if isinstance(topics, str):
        topics = [topics]
    if _client_factory is None:

        def _client_factory():
            return _ConfluentClient(rdkafka_settings, topics, for_read=True)

    return _mq.mq_read(
        _client_factory,
        schema=schema,
        format=format,
        mode=mode,
        name=name,
        partitioned=True,
    )


def simple_read(
    server: str,
    topic: str,
    *,
    read_only_new: bool = False,
    schema=None,
    format: str = "raw",
    mode: str = "streaming",
    name: str | None = None,
    _client_factory=None,
    **kwargs,
):
    """Read with minimal config (reference: io/kafka simple_read:261)."""
    settings = {
        "bootstrap.servers": server,
        "group.id": "$GROUP_NAME",
        "session.timeout.ms": "6000",
        "auto.offset.reset": "latest" if read_only_new else "earliest",
    }
    return read(
        settings,
        topic,
        schema=schema,
        format=format,
        mode=mode,
        name=name,
        _client_factory=_client_factory,
    )


def write(
    table,
    rdkafka_settings: dict,
    topic_name: str,
    *,
    format: str = "json",
    key=None,
    name: str | None = None,
    _client=None,
    **kwargs,
) -> None:
    """Write the table's change stream to a Kafka topic (reference:
    io/kafka write:360; JsonLines formatter data_format.rs:2059)."""
    if _client is None:
        _client = _ConfluentClient(rdkafka_settings, topic_name, for_read=False)
    key_column = getattr(key, "name", key) if key is not None else None
    # default sink name carries the topic: the exactly-once commit log is
    # keyed on it, and two unnamed sinks must not share a log
    _mq.mq_write(
        table,
        _client,
        topic_name,
        format=format,
        key_column=key_column,
        name=name or f"kafka:{topic_name}",
    )
