"""Connector runtime: reader threads, commit ticks, the streaming driver.

TPU-native rebuild of the reference connector machinery (reference:
src/connectors/mod.rs Connector::run:523 — reader thread per source, commit
ticks advancing engine time; even timestamps mark batch boundaries,
src/engine/timestamp.rs). Here each live source runs a python thread pushing
events into the driver's queue; the driver groups them into engine times and
steps the dataflow.
"""

from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import time as time_mod
from typing import Any, Callable, Dict, List, Optional, Tuple

from pathway_tpu.engine.value import Pointer, ref_scalar
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import sanitizer as _sanitizer
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

_source_ids = itertools.count()


def _hashable(values: tuple) -> tuple:
    """Values tuple -> dict key (Json and arrays are unhashable)."""
    out = []
    for v in values:
        try:
            hash(v)
            out.append(v)
        except TypeError:
            out.append(repr(v))
    return tuple(out)


def _values_tuples(rows: List[dict], names: List[str]) -> List[tuple]:
    """Row dicts -> values tuples; specialized for narrow schemas (the
    per-row genexpr inside tuple() dominates otherwise)."""
    if len(names) == 1:
        n0 = names[0]
        return [(r.get(n0),) for r in rows]
    if len(names) == 2:
        n0, n1 = names
        return [(r.get(n0), r.get(n1)) for r in rows]
    if len(names) == 3:
        n0, n1, n2 = names
        return [(r.get(n0), r.get(n1), r.get(n2)) for r in rows]
    return [tuple(r.get(c) for c in names) for r in rows]


class LiveSource:
    """One streaming input: a subject factory + the engine node it feeds.

    `exclusive` sources (REST ingress, stateful custom subjects) run their
    reader on exactly one worker; a scatter exchange after the source node
    routes rows to shard owners (reference: non-partitioned sources are read
    by one worker and forwarded, worker-architecture doc :41-42)."""

    def __init__(
        self,
        subject_factory,
        schema,
        name: str,
        *,
        exclusive: bool = False,
        exclusive_worker: int = 0,
        partitioned: bool = False,
    ):
        self.subject_factory = subject_factory
        self.schema = schema
        self.name = name
        self.partitioned = partitioned
        self.node = None  # set at build time
        self.sync_group = None  # set by register_input_synchronization_group
        self.sync_column = None
        self.exclusive = exclusive
        self.exclusive_worker = exclusive_worker
        # barrier-commit sources: rows flush only up to the last commit, so
        # batch shapes are exactly the subject's commit units regardless of
        # timer alignment or reader/engine relative speed
        self.gated_commits = False


def connector_table(
    schema,
    subject_factory: Callable[[], "ConnectorSubjectBase"],
    *,
    mode: str = "streaming",
    name: str | None = None,
    exclusive: bool = False,
    exclusive_worker: int = 0,
    partitioned: bool = False,
    gated_commits: bool = False,
) -> Table:
    """Create a table fed by a connector subject (reference:
    Graph::connector_table, dataflow.rs:3880).

    Multi-worker source modes:
    - default (replicated): every worker runs the reader over the full
      input and keeps only its key shard — right for local files, demo
      streams, anything cheap and deterministic to re-read.
    - ``exclusive``: one worker reads (REST ingress binding a port,
      stateful custom subjects); rows are scatter-exchanged to owners.
    - ``partitioned``: every worker reads a disjoint partition subset
      (kafka consumer groups); rows are scatter-exchanged, nothing is
      filtered."""
    name = name or f"source_{next(_source_ids)}"
    live = LiveSource(
        subject_factory,
        schema,
        name,
        exclusive=exclusive,
        exclusive_worker=exclusive_worker,
        partitioned=partitioned,
    )
    live.gated_commits = gated_commits

    if mode == "static":

        def build_static(ctx):
            from pathway_tpu.engine.engine import StaticSource

            subject = subject_factory()
            collector = _StaticCollector(schema)
            subject._bind(collector)
            pcfg = getattr(ctx.engine, "_persistence_config", None)
            if pcfg is not None:
                from pathway_tpu.persistence import CachedObjectStorage

                subject._bind_object_cache(
                    CachedObjectStorage(pcfg.backend._backend, name)
                )
            subject.run()
            subject.on_stop()
            deltas = collector.all_deltas()
            if deltas is not None:
                return StaticSource(ctx.engine, {}, deltas=deltas)
            return StaticSource(ctx.engine, collector.all_rows())

        return Table(schema=schema, universe=Universe(), build=build_static)

    def build_streaming(ctx):
        from pathway_tpu.engine.engine import InputQueueSource

        node = InputQueueSource(
            ctx.engine, shard_filter=not (exclusive or partitioned)
        )
        live.node = node
        # thread workers build one engine per thread from the same parse
        # graph: the driver must resolve the node for ITS engine, not the
        # last-built one
        nodes = getattr(ctx.engine, "_live_nodes", None)
        if nodes is None:
            nodes = ctx.engine._live_nodes = {}
        nodes[live] = node
        if live not in G.sources:
            G.add_source(live)
        if (exclusive or partitioned) and ctx.engine.worker_count > 1:
            from pathway_tpu.engine.exchange import exchange_by_key

            return exchange_by_key(ctx.engine, node)
        return node

    table = Table(schema=schema, universe=Universe(), build=build_streaming)
    table._live_source = live  # for input synchronization groups
    return table


class _StaticCollector:
    """Synchronously drains a subject in static mode."""

    def __init__(self, schema):
        from pathway_tpu.engine.value import seq_key_seed

        self.schema = schema
        self.names = list(schema.keys())
        self.pk = schema.primary_key_columns()
        self.rows: Dict[Pointer, tuple] = {}
        self._counter = 0
        self._seed = seq_key_seed("static", schema.__name__)
        # keyless retraction bookkeeping is lazy: bulk loads log batches
        # and the values->keys dict materializes on the first retraction
        self._keys_by_values: Dict[tuple, List] = {}
        self._kv_log: List[tuple] = []  # (values_list, keys_list)

    def _materialize_kv(self) -> Dict[tuple, List]:
        kv = self._keys_by_values
        if self._kv_log:
            rows = self.rows
            for values_list, keys_list in self._kv_log:
                rows.update(zip(keys_list, values_list))
                for v, k in zip(values_list, keys_list):
                    kv.setdefault(_hashable(v), []).append(k)
            self._kv_log.clear()
        return kv

    def push_row(self, row: dict, diff: int = 1) -> None:
        from pathway_tpu.engine.value import seq_key

        values = tuple(row.get(c) for c in self.names)
        if self.pk:
            key = ref_scalar(*(row.get(c) for c in self.pk))
        elif diff > 0:
            self._counter += 1
            key = seq_key(self._seed, self._counter)
            if self._kv_log:
                self._materialize_kv()
            self._keys_by_values.setdefault(_hashable(values), []).append(key)
        else:
            # retraction without a primary key: cancel the key assigned to
            # an earlier insert of the same values
            stack = self._materialize_kv().get(_hashable(values))
            if not stack:
                return
            key = stack.pop()
        if diff > 0:
            self.rows[key] = values
        else:
            self.rows.pop(key, None)

    def push_rows(self, rows: List[dict]) -> None:
        """Bulk insert: one pass over the batch instead of per-row calls.
        Keyless batches skip the dict entirely (seq keys cannot collide);
        `all_rows()` folds the logged batches back in."""
        self.push_tuples(_values_tuples(rows, self.names))

    def push_tuples(self, values_list: List[tuple]) -> None:
        """Bulk insert of pre-ordered values tuples — the readers' fastest
        path: no row dicts anywhere between the parser and the engine."""
        from pathway_tpu.engine.value import seq_keys_batch

        if self.pk:
            idxs = [self.names.index(c) for c in self.pk]
            keys = [
                ref_scalar(*(v[i] for i in idxs)) for v in values_list
            ]
            self.rows.update(zip(keys, values_list))
        else:
            keys = seq_keys_batch(
                self._seed, self._counter, len(values_list)
            )
            self._counter += len(values_list)
            self._kv_log.append((values_list, keys))

    def all_rows(self) -> Dict[Pointer, tuple]:
        """Final key -> values map (push_row inserts + logged batches)."""
        if self._kv_log:
            rows = self.rows
            for values_list, keys_list in self._kv_log:
                rows.update(zip(keys_list, values_list))
            self._kv_log.clear()
        return self.rows

    def all_deltas(self):
        """Prebuilt consolidated delta list for the pure bulk-ingest shape
        (only logged batches, seq keys, no per-row inserts/retractions) —
        C-speed zip, no dict materialization. None when the per-row path
        was used (all_rows() handles the general case)."""
        if self.rows or not self._kv_log:
            return None
        from itertools import repeat

        out: List = []
        for values_list, keys_list in self._kv_log:
            out.extend(zip(keys_list, values_list, repeat(1)))
        return out

    def commit(self) -> None:
        pass

    def close(self) -> None:
        pass


class ConnectorSubjectBase:
    """Base for python connector subjects (reference:
    io/python/__init__.py:47 ConnectorSubject): background thread calling
    next()/commit()/close()."""

    _worker_id = 0
    _worker_count = 1
    # class-level defaults so report_retry works even when a subclass
    # forgets to call super().__init__()
    _retries = 0
    _backoff_s = 0.0

    def __init__(self):
        self._sink = None
        self._closed = False
        self._retries = 0
        self._backoff_s = 0.0
        self._object_cache = None  # CachedObjectStorage under persistence

    def _bind(self, sink) -> None:
        self._sink = sink

    def _bind_object_cache(self, cache) -> None:
        """Persistence-backed source-object cache (reference:
        cached_object_storage.rs): downloading connectors consult it to
        skip re-fetching unchanged objects after a restart."""
        self._object_cache = cache

    # -- API used by subclasses ------------------------------------------
    def next(self, **kwargs) -> None:
        self._sink.push_row(kwargs)

    def next_batch(self, rows: List[dict]) -> None:
        """Bulk insert of row dicts — one sink call for the whole chunk
        (the readers' bulk-ingest fast path)."""
        push_rows = getattr(self._sink, "push_rows", None)
        if push_rows is not None:
            push_rows(rows)
        else:
            for r in rows:
                self._sink.push_row(r)

    def next_batch_tuples(self, values_list: List[tuple], names: List[str]) -> None:
        """Bulk insert of schema-ordered values tuples — skips row dicts
        entirely when the sink supports it."""
        push_tuples = getattr(self._sink, "push_tuples", None)
        if push_tuples is not None:
            push_tuples(values_list)
        else:
            self.next_batch([dict(zip(names, v)) for v in values_list])

    def report_retry(self, delay: float = 0.0) -> None:
        """Count a transient read failure that the subject retried
        (network hiccup, rate limit) and the backoff it cost.  Retry
        sites compute ``delay`` with internals/backoff.Backoff (capped
        exponential + jitter) and pass it here so every connector
        surfaces uniform ``retries`` / ``backoff_s`` stats
        (``pathway_connector_retries`` / ``_backoff_seconds``)."""
        self._retries += 1
        self._backoff_s += delay

    def next_json(self, message: dict) -> None:
        self.next(**message)

    def next_bytes(self, payload: bytes) -> None:
        self.next(data=payload)

    def next_str(self, message: str) -> None:
        self.next(data=message)

    def _remove(self, row: dict) -> None:
        self._sink.push_row(row, diff=-1)

    def commit(self, barrier: bool = False) -> None:
        """Mark a consistent point in the stream. With persistence, a
        commit seals the batch + cursor that recovery replays. Without
        persistence it is a flush hint only: under load the driver may
        coalesce rows from after a commit into the same engine minibatch
        (server-side micro-batching). ``barrier=True`` additionally makes
        the commit a batch BOUNDARY (single-worker): rows after it never
        coalesce into the same engine tick — deterministic batch shapes
        that pipeline host parsing of batch N+1 against the device work of
        batch N (bulk-ingest host/device overlap)."""
        # capability probe once per sink: catching TypeError around the
        # live call would retry (double-commit) and mask real errors
        accepts = getattr(self._sink, "_commit_accepts_barrier", None)
        if accepts is None:
            import inspect

            try:
                params = inspect.signature(self._sink.commit).parameters
                accepts = "barrier" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()
                )
            except (TypeError, ValueError):
                accepts = False
            try:
                self._sink._commit_accepts_barrier = accepts
            except AttributeError:
                pass
        if accepts:
            self._sink.commit(barrier=barrier)
        else:
            self._sink.commit()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._sink.close()

    # -- persistence hooks (reference: ConnectorSubject seek/snapshot,
    # io/python/__init__.py:47) -------------------------------------------
    def _persisted_state(self):
        """Cursor state saved at each commit; restored on resume."""
        return None

    def _restore_persisted_state(self, state) -> None:
        pass

    # -- to override ------------------------------------------------------
    def run(self) -> None:
        raise NotImplementedError

    def on_stop(self) -> None:
        pass

    @property
    def _deletions_enabled(self) -> bool:
        return True


class _QueueSink:
    """Routes a live subject's rows into the driver queue."""

    def __init__(self, driver_queue, live: LiveSource):
        from pathway_tpu.engine.value import seq_key_seed

        self.queue = driver_queue
        self.live = live
        self.names = list(live.schema.keys())
        self.pk = live.schema.primary_key_columns()
        self._counter = 0
        self._seed = seq_key_seed("live", live.name)
        self._keys_by_values: Dict[tuple, List] = {}
        self.subject = None  # bound by the driver

    persistence_enabled = False

    def push_row(self, row: dict, diff: int = 1) -> None:
        from pathway_tpu.engine.value import seq_key

        if self.live.sync_group is not None and diff > 0:
            # throttle until the group's other sources catch up (reference:
            # src/connectors/synchronization.rs)
            self.live.sync_group.wait_for(
                self.live, row.get(self.live.sync_column)
            )
        values = tuple(row.get(c) for c in self.names)
        if "_pw_key" in row:
            key = row["_pw_key"]
        elif self.pk:
            key = ref_scalar(*(row.get(c) for c in self.pk))
        elif diff > 0:
            self._counter += 1
            key = seq_key(self._seed, self._counter)
            self._keys_by_values.setdefault(_hashable(values), []).append(key)
        else:
            # retraction on a keyless schema must reuse the insert's key,
            # or it never cancels anything (negative multiplicity)
            stack = self._keys_by_values.get(_hashable(values))
            if not stack:
                return
            key = stack.pop()
        # the counter rides every data message so autocommit-flushed
        # batches persist a correct resume point even without commit()
        self.queue.put(("data", self.live, (key, values, diff), self._counter))

    def push_rows(self, rows: List[dict]) -> None:
        """Bulk inserts: one queue message for the whole batch.  Falls
        back to push_row when per-row handling is needed (sync groups,
        explicit keys).  Contract: batches are homogeneous w.r.t.
        `_pw_key` — either every row carries one or none does (the
        readers guarantee this; schema-filtered rows never carry it)."""
        from pathway_tpu.engine.value import seq_keys_batch

        if self.live.sync_group is not None or (
            rows and "_pw_key" in rows[0]
        ):
            for r in rows:
                self.push_row(r)
            return
        self.push_tuples(_values_tuples(rows, self.names))

    def push_tuples(self, values_list: List[tuple]) -> None:
        """Bulk insert of pre-ordered values tuples (no row dicts)."""
        from pathway_tpu.engine.value import seq_keys_batch

        if self.live.sync_group is not None:
            for v in values_list:
                self.push_row(dict(zip(self.names, v)))
            return
        if self.pk:
            idxs = [self.names.index(c) for c in self.pk]
            keys = [
                ref_scalar(*(v[i] for i in idxs)) for v in values_list
            ]
        else:
            keys = seq_keys_batch(
                self._seed, self._counter, len(values_list)
            )
            self._counter += len(values_list)
            kv = self._keys_by_values
            for v, k in zip(values_list, keys):
                kv.setdefault(_hashable(v), []).append(k)
        deltas = [(k, v, 1) for k, v in zip(keys, values_list)]
        self.queue.put(("data_batch", self.live, deltas, self._counter))

    def commit(self, barrier: bool = False) -> None:
        state = None
        if self.persistence_enabled and self.subject is not None:
            state = {"subject": self.subject._persisted_state()}
        kind = "commit_b" if barrier else "commit"
        self.queue.put((kind, self.live, state, self._counter))

    def close(self) -> None:
        if self.live.sync_group is not None:
            self.live.sync_group.source_closed(self.live)
        self.queue.put(("close", self.live, None, self._counter))


class StreamingDriver:
    """Main streaming loop: collects source events, advances engine time
    (reference: worker main loop, dataflow.rs:6552-6620)."""

    def __init__(
        self,
        engine,
        ctx,
        *,
        autocommit_ms: float = 100.0,
        persistence_config=None,
    ):
        self.engine = engine
        self.ctx = ctx
        self.autocommit_s = autocommit_ms / 1000.0
        self.queue: queue_mod.SimpleQueue = queue_mod.SimpleQueue()
        self.persistence_config = persistence_config
        self._writers: Dict[LiveSource, Any] = {}

    def _snapshot_writer(self, live: LiveSource):
        if self.persistence_config is None:
            return None
        from pathway_tpu.persistence import InputSnapshotWriter

        writer = self._writers.get(live)
        if writer is None:
            writer = InputSnapshotWriter(
                self.persistence_config.backend._backend,
                live.name,
                self.engine.worker_id,
            )
            self._writers[live] = writer
        return writer

    def run(self, sources: List[LiveSource]) -> None:
        try:
            self._run(sources)
        finally:
            # finish() unfreezes on the success path; this also covers
            # exceptions mid-stream (engine._gc_pulse freezes the gc)
            self.engine._gc_unfreeze()

    def _run(self, sources: List[LiveSource]) -> None:
        import os

        from pathway_tpu.engine.engine import EngineError, FailoverRequired
        from pathway_tpu.internals import faults, health
        from pathway_tpu.internals import qtrace as _qtrace

        threads = []
        active = 0
        replayed: Dict[LiveSource, List] = {}
        my_worker = self.engine.worker_id
        sinks: Dict[LiveSource, _QueueSink] = {}
        last_event: Dict[LiveSource, float] = {}

        # operator snapshots (reference: dataflow/persist.rs): restore node
        # state at the persisted frontier, then replay only the log tail
        # appended after the last compaction
        op_mgr = None
        snap_interval = 0.0
        manifest = None
        snap_ms = (
            getattr(self.persistence_config, "snapshot_interval_ms", 0)
            if self.persistence_config is not None
            else 0
        )
        # operator snapshots are opt-in via snapshot_interval_ms > 0
        # (reference: PersistenceMode / operator persisting); the default
        # input-snapshot mode replays the full event log instead
        if self.persistence_config is not None and snap_ms > 0:
            from pathway_tpu.persistence import OperatorSnapshotManager

            op_mgr = OperatorSnapshotManager(
                self.persistence_config.backend._backend,
                self.engine.worker_id,
            )
            snap_interval = snap_ms / 1000.0
            if _sanitizer.ACTIVE:
                # replay-divergence hashing only means something when
                # operator snapshots exist to replay against
                _sanitizer.tracker().enable_replay_hashing()

        def restore_states():
            """Load + apply the newest commonly-restorable operator
            snapshot; returns the restored frontier or None.  Runs once at
            startup and again after each failover rollback."""
            nonlocal manifest
            if op_mgr is None:
                return None
            manifest = op_mgr.load_manifest()
            # phase 1 loads blobs without mutating; phase 2 applies only if
            # EVERY worker can restore the same frontier — a one-sided
            # restore would desync the lockstep clock, and a partial apply
            # would double-count replayed events
            states = (
                op_mgr.load_states(self.engine, manifest)
                if manifest is not None
                else None
            )
            local_time = manifest["time"] if states is not None else -1
            if self.engine.worker_count > 1:
                votes = self.engine.coord.agree(local_time)
                agreed = (
                    votes[0]
                    if all(v == votes[0] for v in votes) and votes[0] >= 0
                    else -1
                )
            else:
                agreed = local_time
            if agreed >= 0:
                op_mgr.apply_states(self.engine, states)
                if _sanitizer.ACTIVE:
                    # rewind this thread's UDF hash accumulators to the
                    # manifest baseline; whatever was accumulated beyond
                    # it (the pre-crash tail) becomes the replay target
                    _sanitizer.tracker().on_restore(manifest)
                return agreed
            return None

        restored_time = restore_states()
        # exactly-once sinks: truncate/roll back anything staged past the
        # restored frontier (post-restore epochs renumber and would
        # collide) and idempotently re-run any commit the previous run's
        # crash interrupted
        for w in self.engine._txn_sinks:
            w.recover(restored_time if restored_time is not None else -1)

        engine_nodes = getattr(self.engine, "_live_nodes", {})

        def node_of(live):
            return engine_nodes.get(live, live.node)

        def compute_replay() -> Dict[LiveSource, List]:
            """(Re-)read the event-log tail each source must replay on top
            of the restored state.  Called at startup and again after a
            failover rollback — the log is written BEFORE batches are
            pushed into the engine (see flush), so it is complete for any
            frontier the group rolls back to."""
            out: Dict[LiveSource, List] = {}
            for live in sinks:
                writer = self._snapshot_writer(live)
                if writer is None:
                    continue
                if restored_time is not None:
                    # operator state restored: replay only the segments
                    # appended after the manifest's folded frontier
                    folded = (manifest or {}).get("folded_through", {})
                    events = writer.read_events(
                        after_segment=folded.get(live.name, -1)
                    )
                elif op_mgr is not None:
                    # restore refused (fresh run, graph change, diverged
                    # workers): consolidated base + every later segment is
                    # the complete history
                    base, base_seg = op_mgr.read_base(live.name)
                    events = base + writer.read_events(
                        after_segment=base_seg
                    )
                else:
                    events = writer.read_events()
                if events:
                    out[live] = events
            return out

        for live in sources:
            if node_of(live) is None:
                continue  # source never built (tree-shaken)
            if live.exclusive and my_worker != live.exclusive_worker:
                # exclusive sources (REST ingress, stateful custom subjects)
                # read on one worker only; a scatter ExchangeNode after the
                # source routes rows to their shard owners
                continue
            subject = live.subject_factory()
            # partitioned subjects divide the input among workers by
            # these coordinates (fs partitioned file ownership, kafka
            # consumer-group analogue)
            subject._worker_id = my_worker
            subject._worker_count = self.engine.worker_count
            sink = _QueueSink(self.queue, live)
            if live.partitioned and self.engine.worker_count > 1:
                # each worker reads DIFFERENT rows, so generated sequence
                # keys must be globally unique — salt the seed per worker
                # (replicated sources need the OPPOSITE: identical seeds,
                # because every worker re-reads the same rows)
                from pathway_tpu.engine.value import seq_key_seed

                sink._seed = seq_key_seed(
                    "live", f"{live.name}@w{my_worker}"
                )
            sink.subject = subject
            sinks[live] = sink
            sink.persistence_enabled = self.persistence_config is not None
            subject._bind(sink)
            if self.persistence_config is not None:
                from pathway_tpu.persistence import CachedObjectStorage

                subject._bind_object_cache(
                    CachedObjectStorage(
                        self.persistence_config.backend._backend, live.name
                    )
                )
            writer = self._snapshot_writer(live)
            if writer is not None:
                state = writer.read_state()
                if state is not None:
                    sink._counter = state.get("counter", 0)
                    subject._restore_persisted_state(state.get("subject"))

            def runner(subject=subject):
                try:
                    subject.run()
                finally:
                    subject.on_stop()
                    subject.close()

            t = threading.Thread(target=runner, daemon=True, name=live.name)
            threads.append(t)
            active += 1
        replayed = compute_replay()
        time = 2  # set per attempt in the run loop below
        started = False
        # chaos directives bind to runs STARTED while they are armed: a
        # driver from before the arming (e.g. a never-terminating
        # webserver pipeline left on a daemon thread) must not tick the
        # harness with its own frozen logical time — it would overwrite
        # the mem-pressure gauge and could even consume one-shot
        # directives meant for the armed run
        chaos_gen = faults.generation()

        pending: Dict[LiveSource, List] = {}
        states: Dict[LiveSource, Any] = {}
        counters: Dict[LiveSource, int] = {}
        last_flush = time_mod.monotonic()
        last_snapshot = time_mod.monotonic()
        # sink freshness: when the oldest event of the batch being
        # accumulated entered the process (None = nothing buffered yet)
        batch_arrival: Optional[float] = None
        dirty_since_snapshot = False
        snapshot_writers = {
            live.name: self._snapshot_writer(live)
            for live in sources
            if node_of(live) is not None and self._snapshot_writer(live) is not None
        }
        multiworker = self.engine.worker_count > 1
        done = False
        # per-live commit bookkeeping: how much of `pending` the subject
        # has committed (flushable), and whether it ever commits at all.
        # The committed-prefix gating matters when a persisted cursor must
        # stay consistent with the logged batch, and for barrier-commit
        # sources whose batch shapes must equal their commit units.
        gate_commits = self.persistence_config is not None
        committed_upto: Dict[LiveSource, int] = {}
        ever_committed: set = set()

        def gated(live) -> bool:
            return gate_commits or live.gated_commits

        def flush():
            """One coordinated flush tick. Multi-worker: every worker makes
            the identical sequence of coordination calls per tick (one
            agree + the shared-scheduled-time loop), so agreement rounds
            align across workers; agree() itself blocks until the slowest
            worker reaches the same tick — that is the frontier protocol."""
            nonlocal time, last_flush, last_snapshot, done
            nonlocal dirty_since_snapshot, batch_arrival
            gen_ok = not faults.ACTIVE or faults.generation() == chaos_gen
            if faults.ACTIVE and gen_ok:
                # deterministic chaos: may raise WorkerKilled (this worker
                # dies at its scheduled epoch, BEFORE voting — peers see a
                # dead peer mid-agree, exactly like a real crash) or sever
                # a peer socket
                faults.on_epoch(my_worker, time, self.engine.coord)
            if health.ENABLED and gen_ok:
                # the closed-loop controller's tick: may drain/re-admit a
                # replica, adjust backpressure, or raise WorkerRestart
                # (rolling restart) — which the failover path absorbs
                # exactly like an injected kill.  Stale-generation runs
                # skip this too while a harness is armed, so an armed
                # chaos run's health actions stay a pure function of its
                # own directive schedule.
                health.on_epoch(my_worker, time, self.engine)
            self.engine.flush_ticks = getattr(self.engine, "flush_ticks", 0) + 1
            has_data = any(
                (committed_upto.get(live, 0) > 0 or not gated(live)
                 or live not in ever_committed)
                and bool(d)
                for live, d in pending.items()
            )
            local_done = active <= 0 and not has_data
            term = self.engine.terminate_flag.is_set()
            snap_due = op_mgr is not None and (
                time_mod.monotonic() - last_snapshot
            ) >= snap_interval
            if multiworker:
                # ONE agreement round per tick: termination, snapshot
                # cadence AND the earliest scheduled temporal time all ride
                # the same vote (a unilateral break would strand peers in
                # agree(); a unilateral snapshot would diverge manifests;
                # a separate global_next_time round would double the
                # coordination cost of every idle tick)
                votes = self.engine.coord.agree(
                    (
                        has_data,
                        local_done,
                        term,
                        snap_due,
                        self.engine.next_scheduled_time(),
                    )
                )
                any_data = any(v[0] for v in votes)
                done = all(v[1] for v in votes) or any(v[2] for v in votes)
                snap_due = any(v[3] for v in votes)
                nxt_votes = [v[4] for v in votes if v[4] is not None]
                agreed_next = min(nxt_votes) if nxt_votes else None
            else:
                any_data = has_data
                done = local_done or term
                agreed_next = None  # single-worker re-samples post-batch
            processed_batch = None
            if any_data:
                flush_started = time_mod.monotonic()
                for live in list(pending.keys()):
                    deltas = pending[live]
                    if not deltas:
                        continue
                    # exactly-once under persistence: only the prefix up to
                    # the subject's last commit flushes with the committed
                    # cursor state; the uncommitted tail waits for its own
                    # commit. Sources that never commit (autocommit-only)
                    # flush everything with the counter cursor, as before.
                    # Without persistence there is no cursor to keep
                    # consistent, so nothing is ever withheld.
                    if gated(live) and live in ever_committed:
                        cut = committed_upto.get(live, 0)
                        batch, tail = deltas[:cut], deltas[cut:]
                        pending[live] = tail
                        committed_upto[live] = 0
                    else:
                        batch, tail = deltas, []
                        pending[live] = []
                    if not batch:
                        continue
                    writer = self._snapshot_writer(live)
                    if writer is not None:
                        state = states.pop(live, None) or {}
                        state["counter"] = counters.get(live, 0)
                        writer.write_batch(batch, state)
                    node_of(live).push(time, batch)
                    if _qtrace.ENABLED:
                        # stamp queries leaving the connector buffer for the
                        # engine tick (no-op unless a query is in flight)
                        _qtrace.tracker().mark_batch(batch, "picked")
                # sink freshness: stamp when this epoch's data entered the
                # process (oldest buffered event, or now for commit-only
                # flushes) — SubscribeNode sinks close the interval at
                # on_time_end inside this process_time call
                m = self.engine.metrics
                if m is not None:
                    m.note_ingest(
                        time,
                        batch_arrival
                        if batch_arrival is not None
                        else flush_started,
                    )
                batch_arrival = None
                self.engine.process_time(time)
                # observability: batch latency + per-source read counters
                # (reference: src/connectors/monitoring.rs surfaces the
                # same per-connector numbers)
                self.engine.last_batch_latency_ms = (
                    time_mod.monotonic() - flush_started
                ) * 1000.0
                stats = getattr(self.engine, "connector_stats", None)
                if stats is None:
                    stats = self.engine.connector_stats = {}
                now_ = time_mod.monotonic()
                for live_, cnt in counters.items():
                    subj = getattr(sinks.get(live_), "subject", None)
                    stats[live_.name] = {
                        "rows_read": cnt,
                        "pending": len(pending.get(live_, ())),
                        "read_lag_s": now_ - last_event.get(live_, now_),
                        "retries": getattr(subj, "_retries", 0),
                        "backoff_s": round(
                            getattr(subj, "_backoff_s", 0.0), 6
                        ),
                    }
                dirty_since_snapshot = True
                processed_batch = time
                time += 2
            if snap_due and op_mgr is not None and dirty_since_snapshot:
                # quiescent frontier: the last time is fully processed and
                # queues are drained — checkpoint operator state + compact
                # logs (multi-worker: snap_due was agreed, and any_data is
                # agreed, so every worker saves the same frontier).
                # Exactly-once sinks ride the same commit point: staged
                # BEFORE the manifest, finalized only after the manifest
                # landed — a crash anywhere in between either replays the
                # epoch (pre-manifest) or idempotently re-finalizes
                # (post-manifest), never both.
                frontier = time - 2
                txn = self.engine._txn_sinks
                saved = False
                try:
                    for w in txn:
                        w.prepare(frontier)
                    saved = op_mgr.save(
                        self.engine, frontier, snapshot_writers
                    )
                    if saved:
                        for w in txn:
                            w.commit(frontier)
                        if txn:
                            self.engine.sink_txn_commits += 1
                except Exception as exc:  # noqa: BLE001 — store failure
                    # a failed stage/finalize never kills the job: staged
                    # blobs stay provisional, and the next successful
                    # snapshot (or recover on restart) finalizes or rolls
                    # them back idempotently
                    self.engine.warn_once(
                        f"sink-txn-{type(exc).__name__}",
                        "snapshot sink transaction at frontier %s failed "
                        "(%s: %s) — continuing, the next snapshot retries",
                        frontier,
                        type(exc).__name__,
                        exc,
                    )
                if saved:
                    dirty_since_snapshot = False
                # failed save: staged sink blobs stay; the next successful
                # commit (or recover on restart) finalizes them
                last_snapshot = time_mod.monotonic()
            # run scheduled times that are due.  Multi-worker: the first
            # due time came from the tick vote (no extra round) — times
            # scheduled DURING this tick surface on the next vote, one
            # autocommit later, which keeps the agreement sequence
            # identical on every worker.  Single-worker re-samples locally
            # (free), so cascades still flush immediately.
            nxt = (
                agreed_next
                if multiworker
                else self.engine.next_scheduled_time()
            )
            first = True
            while nxt is not None and nxt <= time:
                # the voted time was sampled pre-batch: on the FIRST
                # iteration it may equal the batch time just processed —
                # skip that one (processed_batch and the vote are agreed,
                # so every worker skips together).  Later iterations come
                # from global_next_time over genuinely scheduled times
                # (including cascades) and always process.
                if not (first and nxt == processed_batch):
                    self.engine.process_time(nxt)
                first = False
                nxt = self.engine.global_next_time()
            last_flush = time_mod.monotonic()

        # live failover: with snapshots on and a failover-capable
        # coordinator, a peer death surfaces as FailoverRequired out of a
        # coordination wait instead of a fatal error; survivors roll back
        # to the last persisted frontier and a replacement worker rejoins
        # the SAME run — the job never restarts.
        coord = self.engine.coord
        if (
            op_mgr is not None
            and self.engine.worker_count > 1
            and hasattr(coord, "enable_failover")
        ):
            coord.enable_failover()
        max_failovers = int(os.environ.get("PATHWAY_MAX_FAILOVERS", "3"))
        failovers = 0
        failover_started = 0.0
        while True:
            try:
                if failovers:
                    # roll back: drop in-flight engine state, re-restore the
                    # snapshot every worker (incl. the replacement) agrees
                    # on, re-read the event-log tail past that frontier.
                    # The driver's own pending/queues survive — they hold
                    # data never yet pushed into the engine.
                    self.engine.reset_for_rollback()
                    restored_time = restore_states()
                    if restored_time is None:
                        raise EngineError(
                            "failover rollback failed: no commonly "
                            "restorable operator snapshot"
                        )
                    for w in self.engine._txn_sinks:
                        w.recover(restored_time)
                    replayed = compute_replay()
                    done = False
                    dirty_since_snapshot = False
                    last_snapshot = time_mod.monotonic()
                # initial time 0 processes static parts of the graph (a
                # restored run re-runs it harmlessly: restored source state
                # marks static rows as already emitted)
                self.engine.process_time(0)
                # replay persisted input snapshots as the first batch
                # (reference: rewind_from_disk_snapshot,
                # connectors/mod.rs:256). After an operator-snapshot restore
                # the log holds only the tail appended since the last
                # compaction; it replays on top of restored state.
                # Multi-worker: the replay step happens on every worker if
                # it happens anywhere so the lockstep time sequence stays
                # identical.
                time = 2 if restored_time is None else restored_time + 2
                if self.engine.global_any(bool(replayed)):
                    for live, events in replayed.items():
                        node_of(live).push(time, events)
                    self.engine.process_time(time)
                    time += 2
                if failovers:
                    self.engine.failover_count = failovers
                    self.engine.last_failover_recovery_s = (
                        time_mod.monotonic() - failover_started
                    )
                if not started:
                    start_t = time_mod.monotonic()
                    for live in sinks:
                        last_event[live] = start_t
                    for t in threads:
                        t.start()
                    started = True
                while not done:
                    if health.ENABLED:
                        # adaptive backpressure: while the controller
                        # holds pressure, pace ingest with its
                        # Backoff-derived delay (0.0 otherwise)
                        throttle = health.controller().throttle_delay()
                        if throttle > 0.0:
                            time_mod.sleep(throttle)
                    timeout = max(
                        0.0,
                        self.autocommit_s
                        - (time_mod.monotonic() - last_flush),
                    )
                    if timeout == 0.0:
                        # autocommit deadline passed — flush even if the
                        # queue never drains (a hot source must not starve
                        # the global barrier that idle peers are blocked on)
                        flush()
                        continue
                    try:
                        events = [self.queue.get(timeout=timeout)]
                    except queue_mod.Empty:
                        flush()
                        continue
                    # drain whatever already queued up: events that arrived
                    # while the engine was busy coalesce into ONE batch —
                    # server-side micro-batching that amortizes the
                    # per-dispatch device round trip across concurrent
                    # requests (reference: commit ticks group per-duration;
                    # here load itself sets the batch size).  Bounded so a
                    # hot source cannot starve the autocommit deadline /
                    # multi-worker barrier.
                    drain_budget = 4096
                    if health.ENABLED:
                        # backpressure shrinks the micro-batch coalescing
                        # bound too: smaller engine batches while memory
                        # or the host is the bottleneck
                        drain_budget = health.controller().ingest_budget(4096)
                    while len(events) < drain_budget:
                        try:
                            ev = self.queue.get_nowait()
                        except queue_mod.Empty:
                            break
                        events.append(ev)
                        if ev[0] == "commit_b" and not multiworker:
                            # barrier commit: later rows must not coalesce
                            # into this tick — deterministic batch
                            # boundaries for the bulk-ingest pipeline
                            # (multi-worker keeps timer ticks so the
                            # agreement cadence stays identical everywhere)
                            break
                    needs_flush = False
                    now_ev = time_mod.monotonic()
                    for kind, live, payload, counter in events:
                        counters[live] = max(counters.get(live, 0), counter)
                        last_event[live] = now_ev
                        if kind == "data":
                            pending.setdefault(live, []).append(payload)
                            if batch_arrival is None:
                                batch_arrival = now_ev
                        elif kind == "data_batch":
                            pending.setdefault(live, []).extend(payload)
                            if batch_arrival is None:
                                batch_arrival = now_ev
                        elif kind in ("commit", "commit_b"):
                            if payload is not None:
                                states[live] = payload
                            committed_upto[live] = len(
                                pending.get(live, [])
                            )
                            ever_committed.add(live)
                            # multi-worker: commits buffer until the timer
                            # tick so every worker performs the same number
                            # of coordination rounds
                            needs_flush = True
                        elif kind == "close":
                            active -= 1
                            # close is an implicit final commit: the source
                            # is gone, its uncommitted tail is final data
                            committed_upto[live] = len(
                                pending.get(live, [])
                            )
                            needs_flush = True
                    if needs_flush and not multiworker:
                        flush()
                    if not multiworker and self.engine.terminate_flag.is_set():
                        break
                break
            except FailoverRequired as exc:
                failovers += 1
                if (
                    op_mgr is None
                    or failovers > max_failovers
                    or not hasattr(coord, "failover_rendezvous")
                ):
                    raise
                self.engine.warn_once(
                    f"failover{failovers}",
                    "worker failover %s/%s (%s) — rolling back to the "
                    "last snapshot and waiting for the replacement",
                    failovers,
                    max_failovers,
                    exc,
                )
                failover_started = time_mod.monotonic()
                coord.failover_rendezvous()
        self.engine.finish()
