"""pw.io.redpanda — Redpanda connector (reference: python/pathway/io/redpanda
— Kafka-protocol compatible; read:19, write:197 delegate to the Kafka
machinery)."""

from __future__ import annotations

from pathway_tpu.io.kafka import read, simple_read, write

__all__ = ["read", "simple_read", "write"]
