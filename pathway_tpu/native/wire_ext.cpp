// Native twin of pathway_tpu/engine/wire.py — the typed binary wire codec
// for the exchange protocol, plus a C-speed delta consolidation pass.
//
// Implements the identical frame format (see wire.py's module docstring,
// which is the spec); rare value types (datetimes, ndarrays, opaque
// objects) are delegated to the registered Python helpers so the two
// codecs cannot drift on the long tail. Built as a CPython extension
// module by native/__init__.py via the system toolchain (the reference
// keeps this layer in Rust: src/engine/dataflow/config.rs bincode
// transport; here C++ per the build environment).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

// value tags — must match engine/wire.py
enum Tag : uint8_t {
  TAG_NONE = 0,
  TAG_TRUE = 1,
  TAG_FALSE = 2,
  TAG_INT = 3,
  TAG_BIGINT = 4,
  TAG_FLOAT = 5,
  TAG_STR = 6,
  TAG_BYTES = 7,
  TAG_POINTER = 8,
  TAG_TUPLE = 9,
  TAG_LIST = 10,
  TAG_DICT = 11,
  TAG_JSON = 12,
  TAG_NDARRAY = 13,
  TAG_ERROR = 14,
  TAG_PENDING = 15,
};

enum MsgType : uint8_t {
  MSG_HELLO = 0x01,
  MSG_DATA = 0x02,
  MSG_PUNCT = 0x03,
  MSG_COORD = 0x04,
};

// registered Python objects (set once via register_types)
PyObject *g_pointer_cls = nullptr;   // engine.value.Pointer
PyObject *g_json_cls = nullptr;      // engine.value.Json
PyObject *g_error_obj = nullptr;     // engine.value.ERROR
PyObject *g_error_cls = nullptr;     // engine.value.Error
PyObject *g_pending_obj = nullptr;   // engine.value.Pending
PyObject *g_encode_rare = nullptr;   // wire._native_encode_rare(value)->bytes
PyObject *g_decode_rare = nullptr;   // wire._native_decode_rare(tag, bytes)
PyObject *g_wire_error = nullptr;    // wire.WireError

struct Buf {
  std::vector<uint8_t> d;
  int depth = 0;  // container-nesting recursion guard (mirrors wire.py)
  void put(uint8_t b) { d.push_back(b); }
  void put_raw(const void *p, size_t n) {
    const uint8_t *c = static_cast<const uint8_t *>(p);
    d.insert(d.end(), c, c + n);
  }
  void uvarint(uint64_t n) {
    while (true) {
      uint8_t b = n & 0x7f;
      n >>= 7;
      if (n) {
        put(b | 0x80);
      } else {
        put(b);
        return;
      }
    }
  }
  void zigzag(int64_t n) {
    uvarint((static_cast<uint64_t>(n) << 1) ^
            static_cast<uint64_t>(n >> 63));
  }
  void u32(uint32_t v) { put_raw(&v, 4); }
  void u64(uint64_t v) { put_raw(&v, 8); }
};

// Cap on container-nesting recursion in decode_value: a crafted frame of
// repeated 2-byte nested container headers would otherwise drive
// frame-length-deep C recursion and overflow the stack (must be a
// WireError, never a crash). Mirrors wire.py MAX_DECODE_DEPTH.
constexpr int kMaxDecodeDepth = 128;

struct Reader {
  const uint8_t *p;
  const uint8_t *end;
  PyObject *frame = nullptr;  // borrowed: the whole frame bytes object
  const uint8_t *base = nullptr;
  bool fail = false;
  int depth = 0;

  bool need(size_t n) {
    // fail is sticky: once any read failed, every later read fails too.
    // Otherwise a failed uvarint (returning 0) followed by take(0)
    // yields a valid empty slice and the decoder silently accepts a
    // truncated frame (fuzz-found: 5-byte hello → run_id "").
    if (fail || static_cast<size_t>(end - p) < n) {
      fail = true;
      return false;
    }
    return true;
  }
  uint8_t byte() {
    if (!need(1)) return 0;
    return *p++;
  }
  const uint8_t *take(size_t n) {
    if (!need(n)) return nullptr;
    const uint8_t *r = p;
    p += n;
    return r;
  }
  uint64_t uvarint() {
    // strict u64: a tenth byte may only contribute bit 63 (payload bits
    // above it would be silently shifted out) — mirrors wire.py exactly
    // so both decoders accept/reject the same byte strings
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      uint8_t b = byte();
      if (fail) return 0;
      if (shift == 63 && (b & 0x7e)) {
        fail = true;
        return 0;
      }
      acc |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return acc;
      shift += 7;
      if (shift > 63) {
        fail = true;
        return 0;
      }
    }
  }
  int64_t zigzag() {
    uint64_t z = uvarint();
    return static_cast<int64_t>((z >> 1) ^ (~(z & 1) + 1));
  }
};

void wire_err(const char *msg) {
  PyErr_SetString(g_wire_error ? g_wire_error : PyExc_ValueError, msg);
}

// 128-bit key <-> 16 bytes via Python int attr "value"
bool encode_key(Buf &out, PyObject *key) {
  PyObject *val = PyObject_GetAttrString(key, "value");
  if (!val) return false;
  uint8_t raw[16];
  if (_PyLong_AsByteArray(reinterpret_cast<PyLongObject *>(val), raw, 16, 1,
                          0) < 0) {
    Py_DECREF(val);
    return false;
  }
  Py_DECREF(val);
  out.put_raw(raw, 16);
  return true;
}

PyObject *decode_key(Reader &r) {
  const uint8_t *raw = r.take(16);
  if (!raw) {
    wire_err("truncated frame (key)");
    return nullptr;
  }
  PyObject *val = _PyLong_FromByteArray(raw, 16, 1, 0);
  if (!val) return nullptr;
  PyObject *ptr = PyObject_CallFunctionObjArgs(g_pointer_cls, val, nullptr);
  Py_DECREF(val);
  return ptr;
}

bool encode_value(Buf &out, PyObject *v);

bool encode_too_deep(Buf &) {
  // surface over-deep values at the producer (mirrors wire.py's
  // encode-side cap) instead of letting the peer die on decode
  wire_err("value nests too deeply; flatten it before sending");
  return false;
}

bool encode_rare(Buf &out, PyObject *v) {
  // python helper returns the already-tagged bytes for rare values
  PyObject *blob = PyObject_CallFunctionObjArgs(g_encode_rare, v, nullptr);
  if (!blob) return false;
  char *raw;
  Py_ssize_t n;
  if (PyBytes_AsStringAndSize(blob, &raw, &n) < 0) {
    Py_DECREF(blob);
    return false;
  }
  out.put_raw(raw, static_cast<size_t>(n));
  Py_DECREF(blob);
  return true;
}

bool encode_value(Buf &out, PyObject *v) {
  if (v == Py_None) {
    out.put(TAG_NONE);
  } else if (v == Py_True) {
    out.put(TAG_TRUE);
  } else if (v == Py_False) {
    out.put(TAG_FALSE);
  } else if (PyLong_CheckExact(v)) {
    int overflow = 0;
    int64_t n = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (!overflow) {
      out.put(TAG_INT);
      out.zigzag(n);
    } else {
      // arbitrary-precision escape
      size_t nbits = _PyLong_NumBits(v);
      size_t nbytes = nbits / 8 + 1;
      std::vector<uint8_t> raw(nbytes);
      if (_PyLong_AsByteArray(reinterpret_cast<PyLongObject *>(v), raw.data(),
                              nbytes, 1, 1) < 0)
        return false;
      out.put(TAG_BIGINT);
      out.uvarint(nbytes);
      out.put_raw(raw.data(), nbytes);
    }
  } else if (PyFloat_CheckExact(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    out.put(TAG_FLOAT);
    out.put_raw(&d, 8);
  } else if (PyUnicode_CheckExact(v)) {
    Py_ssize_t n;
    const char *raw = PyUnicode_AsUTF8AndSize(v, &n);
    if (!raw) return false;
    out.put(TAG_STR);
    out.uvarint(static_cast<uint64_t>(n));
    out.put_raw(raw, static_cast<size_t>(n));
  } else if (PyBytes_CheckExact(v)) {
    char *raw;
    Py_ssize_t n;
    PyBytes_AsStringAndSize(v, &raw, &n);
    out.put(TAG_BYTES);
    out.uvarint(static_cast<uint64_t>(n));
    out.put_raw(raw, static_cast<size_t>(n));
  } else if (Py_TYPE(v) == reinterpret_cast<PyTypeObject *>(g_pointer_cls)) {
    out.put(TAG_POINTER);
    if (!encode_key(out, v)) return false;
  } else if (PyTuple_CheckExact(v)) {
    if (++out.depth > kMaxDecodeDepth) return encode_too_deep(out);
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    out.put(TAG_TUPLE);
    out.uvarint(static_cast<uint64_t>(n));
    for (Py_ssize_t i = 0; i < n; i++)
      if (!encode_value(out, PyTuple_GET_ITEM(v, i))) return false;
    out.depth--;
  } else if (PyList_CheckExact(v)) {
    if (++out.depth > kMaxDecodeDepth) return encode_too_deep(out);
    Py_ssize_t n = PyList_GET_SIZE(v);
    out.put(TAG_LIST);
    out.uvarint(static_cast<uint64_t>(n));
    for (Py_ssize_t i = 0; i < n; i++)
      if (!encode_value(out, PyList_GET_ITEM(v, i))) return false;
    out.depth--;
  } else if (PyDict_CheckExact(v)) {
    if (++out.depth > kMaxDecodeDepth) return encode_too_deep(out);
    out.put(TAG_DICT);
    out.uvarint(static_cast<uint64_t>(PyDict_GET_SIZE(v)));
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &key, &value)) {
      if (!encode_value(out, key)) return false;
      if (!encode_value(out, value)) return false;
    }
    out.depth--;
  } else if (Py_TYPE(v) == reinterpret_cast<PyTypeObject *>(g_json_cls)) {
    if (++out.depth > kMaxDecodeDepth) return encode_too_deep(out);
    PyObject *inner = PyObject_GetAttrString(v, "value");
    if (!inner) return false;
    out.put(TAG_JSON);
    bool ok = encode_value(out, inner);
    Py_DECREF(inner);
    if (!ok) return false;
    out.depth--;
  } else if (v == g_error_obj) {
    out.put(TAG_ERROR);
    out.uvarint(0);  // plain singleton, no trace
  } else if (Py_TYPE(v) == reinterpret_cast<PyTypeObject *>(g_error_cls)) {
    // Error carrying a trace: python encoder writes the payload
    if (!encode_rare(out, v)) return false;
  } else if (v == g_pending_obj) {
    out.put(TAG_PENDING);
  } else {
    // datetimes, ndarrays, np scalars, opaque objects: python helper
    if (!encode_rare(out, v)) return false;
  }
  return true;
}

PyObject *decode_value(Reader &r);

struct DepthGuard {
  Reader &r;
  bool ok;
  explicit DepthGuard(Reader &rr) : r(rr), ok(++rr.depth <= kMaxDecodeDepth) {
    if (!ok) wire_err("frame nesting too deep");
  }
  ~DepthGuard() { r.depth--; }
};

PyObject *decode_rare(Reader &r, uint8_t tag) {
  // hand (tag, whole frame, offset) to python — zero-copy; it returns
  // (value, bytes_consumed_after_tag)
  if (!r.frame) {
    wire_err("rare value outside a frame context");
    return nullptr;
  }
  PyObject *res = PyObject_CallFunction(
      g_decode_rare, "iOn", (int)tag, r.frame,
      static_cast<Py_ssize_t>(r.p - r.base));
  if (!res) return nullptr;
  PyObject *value = PyTuple_GetItem(res, 0);
  PyObject *consumed = PyTuple_GetItem(res, 1);
  if (!value || !consumed) {
    Py_DECREF(res);
    return nullptr;
  }
  long n = PyLong_AsLong(consumed);
  if (n < 0 || n > (r.end - r.p)) {
    Py_DECREF(res);
    wire_err("rare decoder consumed out of range");
    return nullptr;
  }
  r.p += n;
  Py_INCREF(value);
  Py_DECREF(res);
  return value;
}

PyObject *decode_value(Reader &r) {
  uint8_t tag = r.byte();
  if (r.fail) {
    wire_err("truncated frame (tag)");
    return nullptr;
  }
  switch (tag) {
    case TAG_NONE:
      Py_RETURN_NONE;
    case TAG_TRUE:
      Py_RETURN_TRUE;
    case TAG_FALSE:
      Py_RETURN_FALSE;
    case TAG_INT: {
      int64_t n = r.zigzag();
      if (r.fail) {
        wire_err("truncated frame (int)");
        return nullptr;
      }
      return PyLong_FromLongLong(n);
    }
    case TAG_BIGINT: {
      uint64_t n = r.uvarint();
      const uint8_t *raw = r.take(n);
      if (!raw) {
        wire_err("truncated frame (bigint)");
        return nullptr;
      }
      return _PyLong_FromByteArray(raw, n, 1, 1);
    }
    case TAG_FLOAT: {
      const uint8_t *raw = r.take(8);
      if (!raw) {
        wire_err("truncated frame (float)");
        return nullptr;
      }
      double d;
      std::memcpy(&d, raw, 8);
      return PyFloat_FromDouble(d);
    }
    case TAG_STR: {
      uint64_t n = r.uvarint();
      const uint8_t *raw = r.take(n);
      if (!raw) {
        wire_err("truncated frame (str)");
        return nullptr;
      }
      return PyUnicode_DecodeUTF8(reinterpret_cast<const char *>(raw), n,
                                  nullptr);
    }
    case TAG_BYTES: {
      uint64_t n = r.uvarint();
      const uint8_t *raw = r.take(n);
      if (!raw) {
        wire_err("truncated frame (bytes)");
        return nullptr;
      }
      return PyBytes_FromStringAndSize(reinterpret_cast<const char *>(raw),
                                       n);
    }
    case TAG_POINTER:
      return decode_key(r);
    case TAG_TUPLE: {
      DepthGuard dg(r);
      if (!dg.ok) return nullptr;
      uint64_t n = r.uvarint();
      // each element is >= 1 byte
      if (r.fail || n > static_cast<uint64_t>(r.end - r.p)) {
        wire_err("truncated frame (tuple)");
        return nullptr;
      }
      PyObject *t = PyTuple_New(n);
      if (!t) return nullptr;
      for (uint64_t i = 0; i < n; i++) {
        PyObject *x = decode_value(r);
        if (!x) {
          Py_DECREF(t);
          return nullptr;
        }
        PyTuple_SET_ITEM(t, i, x);
      }
      return t;
    }
    case TAG_LIST: {
      DepthGuard dg(r);
      if (!dg.ok) return nullptr;
      uint64_t n = r.uvarint();
      if (r.fail || n > static_cast<uint64_t>(r.end - r.p)) {
        wire_err("truncated frame (list)");
        return nullptr;
      }
      PyObject *t = PyList_New(n);
      if (!t) return nullptr;
      for (uint64_t i = 0; i < n; i++) {
        PyObject *x = decode_value(r);
        if (!x) {
          Py_DECREF(t);
          return nullptr;
        }
        PyList_SET_ITEM(t, i, x);
      }
      return t;
    }
    case TAG_DICT: {
      DepthGuard dg(r);
      if (!dg.ok) return nullptr;
      uint64_t n = r.uvarint();
      // each entry is a key + value, >= 2 bytes
      if (r.fail || n > static_cast<uint64_t>(r.end - r.p) / 2) {
        wire_err("truncated frame (dict)");
        return nullptr;
      }
      PyObject *d = PyDict_New();
      if (!d) return nullptr;
      for (uint64_t i = 0; i < n; i++) {
        PyObject *k = decode_value(r);
        if (!k) {
          Py_DECREF(d);
          return nullptr;
        }
        PyObject *v = decode_value(r);
        if (!v) {
          Py_DECREF(k);
          Py_DECREF(d);
          return nullptr;
        }
        if (PyDict_SetItem(d, k, v) < 0) {
          Py_DECREF(k);
          Py_DECREF(v);
          Py_DECREF(d);
          if (PyErr_ExceptionMatches(PyExc_TypeError)) {
            // unhashable decoded key: a malformed frame, not a crash
            PyErr_Clear();
            wire_err("bad dict key in frame (unhashable)");
          }
          return nullptr;
        }
        Py_DECREF(k);
        Py_DECREF(v);
      }
      return d;
    }
    case TAG_JSON: {
      DepthGuard dg(r);
      if (!dg.ok) return nullptr;
      PyObject *inner = decode_value(r);
      if (!inner) return nullptr;
      PyObject *j =
          PyObject_CallFunctionObjArgs(g_json_cls, inner, nullptr);
      Py_DECREF(inner);
      return j;
    }
    case TAG_ERROR: {
      uint64_t n = r.uvarint();
      if (r.fail) {
        wire_err("truncated frame (error)");
        return nullptr;
      }
      if (n == 0) {
        Py_INCREF(g_error_obj);
        return g_error_obj;
      }
      const uint8_t *raw = r.take(n);
      if (!raw) {
        wire_err("truncated frame (error trace)");
        return nullptr;
      }
      PyObject *trace = PyUnicode_DecodeUTF8(
          reinterpret_cast<const char *>(raw), n, nullptr);
      if (!trace) {
        PyErr_Clear();
        wire_err("bad error trace (invalid utf-8)");
        return nullptr;
      }
      PyObject *err =
          PyObject_CallFunctionObjArgs(g_error_cls, trace, nullptr);
      Py_DECREF(trace);
      return err;
    }
    case TAG_PENDING:
      Py_INCREF(g_pending_obj);
      return g_pending_obj;
    default:
      return decode_rare(r, tag);
  }
}

// -- deltas -----------------------------------------------------------------

bool encode_deltas(Buf &out, PyObject *deltas) {
  if (!PyList_CheckExact(deltas)) {
    wire_err("deltas must be a list");
    return false;
  }
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  out.uvarint(static_cast<uint64_t>(n));
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PyList_GET_ITEM(deltas, i);
    if (!PyTuple_CheckExact(d) || PyTuple_GET_SIZE(d) != 3) {
      wire_err("delta must be a (key, values, diff) tuple");
      return false;
    }
    if (!encode_key(out, PyTuple_GET_ITEM(d, 0))) return false;
    PyObject *diff = PyTuple_GET_ITEM(d, 2);
    int64_t diff_n = PyLong_AsLongLong(diff);
    if (diff_n == -1 && PyErr_Occurred()) return false;
    out.zigzag(diff_n);
    PyObject *values = PyTuple_GET_ITEM(d, 1);
    if (!PyTuple_CheckExact(values)) {
      wire_err("delta values must be a tuple");
      return false;
    }
    Py_ssize_t ncols = PyTuple_GET_SIZE(values);
    out.uvarint(static_cast<uint64_t>(ncols));
    for (Py_ssize_t c = 0; c < ncols; c++)
      if (!encode_value(out, PyTuple_GET_ITEM(values, c))) return false;
  }
  return true;
}

PyObject *decode_deltas(Reader &r) {
  uint64_t n = r.uvarint();
  // each delta is at least key(16)+diff(1)+ncols(1) = 18 bytes
  if (r.fail || n > static_cast<uint64_t>(r.end - r.p) / 18) {
    wire_err("truncated frame (deltas)");
    return nullptr;
  }
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  for (uint64_t i = 0; i < n; i++) {
    PyObject *key = decode_key(r);
    if (!key) {
      Py_DECREF(out);
      return nullptr;
    }
    int64_t diff = r.zigzag();
    uint64_t ncols = r.uvarint();
    // each value is >= 1 byte: bound the tuple allocation by the bytes
    // actually present (a lying ncols would otherwise drive a huge
    // PyTuple_New)
    if (r.fail || ncols > static_cast<uint64_t>(r.end - r.p)) {
      wire_err("truncated frame (delta header)");
      Py_DECREF(key);
      Py_DECREF(out);
      return nullptr;
    }
    PyObject *values = PyTuple_New(ncols);
    if (!values) {
      Py_DECREF(key);
      Py_DECREF(out);
      return nullptr;
    }
    for (uint64_t c = 0; c < ncols; c++) {
      PyObject *v = decode_value(r);
      if (!v) {
        Py_DECREF(values);
        Py_DECREF(key);
        Py_DECREF(out);
        return nullptr;
      }
      PyTuple_SET_ITEM(values, c, v);
    }
    PyObject *delta = PyTuple_New(3);
    if (!delta) {
      Py_DECREF(values);
      Py_DECREF(key);
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(delta, 0, key);
    PyTuple_SET_ITEM(delta, 1, values);
    PyTuple_SET_ITEM(delta, 2, PyLong_FromLongLong(diff));
    PyList_SET_ITEM(out, i, delta);
  }
  return out;
}

// -- module functions -------------------------------------------------------

PyObject *py_register_types(PyObject *, PyObject *args) {
  PyObject *pointer_cls, *json_cls, *error_obj, *error_cls, *pending_obj,
      *encode_rare_fn, *decode_rare_fn, *wire_error;
  if (!PyArg_ParseTuple(args, "OOOOOOOO", &pointer_cls, &json_cls, &error_obj,
                        &error_cls, &pending_obj, &encode_rare_fn,
                        &decode_rare_fn, &wire_error))
    return nullptr;
#define SET(g, v) \
  Py_XDECREF(g);  \
  Py_INCREF(v);   \
  g = v;
  SET(g_pointer_cls, pointer_cls)
  SET(g_json_cls, json_cls)
  SET(g_error_obj, error_obj)
  SET(g_error_cls, error_cls)
  SET(g_pending_obj, pending_obj)
  SET(g_encode_rare, encode_rare_fn)
  SET(g_decode_rare, decode_rare_fn)
  SET(g_wire_error, wire_error)
#undef SET
  Py_RETURN_NONE;
}

// Message-body encoder shared by encode_message (bare blob) and
// encode_frame (length-prefixed); appends to whatever `out` already holds.
bool encode_message_body(Buf &out, PyObject *arg) {
  if (!PyTuple_Check(arg) || PyTuple_GET_SIZE(arg) < 1) {
    wire_err("message must be a tuple");
    return false;
  }
  PyObject *kind = PyTuple_GET_ITEM(arg, 0);
  const char *k = PyUnicode_AsUTF8(kind);
  if (!k) return false;
  if (std::strcmp(k, "data") == 0 && PyTuple_GET_SIZE(arg) == 4) {
    out.put(MSG_DATA);
    long channel = PyLong_AsLong(PyTuple_GET_ITEM(arg, 1));
    if (channel == -1 && PyErr_Occurred()) return false;
    out.u32(static_cast<uint32_t>(channel));
    int64_t time = PyLong_AsLongLong(PyTuple_GET_ITEM(arg, 2));
    if (time == -1 && PyErr_Occurred()) return false;
    out.zigzag(time);
    if (!encode_deltas(out, PyTuple_GET_ITEM(arg, 3))) return false;
  } else if (std::strcmp(k, "punct") == 0 && PyTuple_GET_SIZE(arg) == 3) {
    out.put(MSG_PUNCT);
    long channel = PyLong_AsLong(PyTuple_GET_ITEM(arg, 1));
    if (channel == -1 && PyErr_Occurred()) return false;
    out.u32(static_cast<uint32_t>(channel));
    int64_t time = PyLong_AsLongLong(PyTuple_GET_ITEM(arg, 2));
    if (time == -1 && PyErr_Occurred()) return false;
    out.zigzag(time);
  } else if (std::strcmp(k, "coord") == 0 && PyTuple_GET_SIZE(arg) == 3) {
    out.put(MSG_COORD);
    uint64_t round_no =
        PyLong_AsUnsignedLongLong(PyTuple_GET_ITEM(arg, 1));
    if (PyErr_Occurred()) return false;
    out.u64(round_no);
    if (!encode_value(out, PyTuple_GET_ITEM(arg, 2))) return false;
  } else if (std::strcmp(k, "hello") == 0 && PyTuple_GET_SIZE(arg) == 3) {
    out.put(MSG_HELLO);
    long worker = PyLong_AsLong(PyTuple_GET_ITEM(arg, 1));
    if (worker == -1 && PyErr_Occurred()) return false;
    out.u32(static_cast<uint32_t>(worker));
    Py_ssize_t n;
    const char *run_id =
        PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(arg, 2), &n);
    if (!run_id) return false;
    out.uvarint(static_cast<uint64_t>(n));
    out.put_raw(run_id, static_cast<size_t>(n));
  } else {
    wire_err("unknown message kind");
    return false;
  }
  return true;
}

PyObject *py_encode_message(PyObject *, PyObject *arg) {
  Buf out;
  if (!encode_message_body(out, arg)) return nullptr;
  return PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(out.d.data()),
      static_cast<Py_ssize_t>(out.d.size()));
}

// encode_frame(msg) -> the full length-prefixed wire frame in one pass:
// the 4-byte big-endian length slot is reserved up front and patched
// after the body lands, so there is no `_LEN.pack(n) + blob` concat copy.
PyObject *py_encode_frame(PyObject *, PyObject *arg) {
  Buf out;
  out.d.resize(4);
  if (!encode_message_body(out, arg)) return nullptr;
  size_t body = out.d.size() - 4;
  if (body > 0xFFFFFFFFu) {
    wire_err("frame too large");
    return nullptr;
  }
  uint32_t n = static_cast<uint32_t>(body);
  out.d[0] = static_cast<uint8_t>(n >> 24);
  out.d[1] = static_cast<uint8_t>(n >> 16);
  out.d[2] = static_cast<uint8_t>(n >> 8);
  out.d[3] = static_cast<uint8_t>(n);
  return PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(out.d.data()),
      static_cast<Py_ssize_t>(out.d.size()));
}

PyObject *py_decode_message(PyObject *, PyObject *arg) {
  char *raw;
  Py_ssize_t n;
  if (PyBytes_AsStringAndSize(arg, &raw, &n) < 0) return nullptr;
  Reader r;
  r.p = reinterpret_cast<const uint8_t *>(raw);
  r.end = r.p + n;
  r.frame = arg;
  r.base = r.p;
  uint8_t kind = r.byte();
  if (r.fail) {
    wire_err("empty frame");
    return nullptr;
  }
  PyObject *msg = nullptr;
  if (kind == MSG_DATA) {
    const uint8_t *ch = r.take(4);
    if (!ch) {
      wire_err("truncated frame (channel)");
      return nullptr;
    }
    uint32_t channel;
    std::memcpy(&channel, ch, 4);
    int64_t time = r.zigzag();
    if (r.fail) {
      wire_err("truncated frame (time)");
      return nullptr;
    }
    PyObject *deltas = decode_deltas(r);
    if (!deltas) return nullptr;
    msg = Py_BuildValue("(sILN)", "data", (unsigned int)channel,
                        (long long)time, deltas);
  } else if (kind == MSG_PUNCT) {
    const uint8_t *ch = r.take(4);
    if (!ch) {
      wire_err("truncated frame (channel)");
      return nullptr;
    }
    uint32_t channel;
    std::memcpy(&channel, ch, 4);
    int64_t time = r.zigzag();
    if (r.fail) {
      wire_err("truncated frame (time)");
      return nullptr;
    }
    msg = Py_BuildValue("(sIL)", "punct", (unsigned int)channel,
                        (long long)time);
  } else if (kind == MSG_COORD) {
    const uint8_t *rd = r.take(8);
    if (!rd) {
      wire_err("truncated frame (round)");
      return nullptr;
    }
    uint64_t round_no;
    std::memcpy(&round_no, rd, 8);
    PyObject *payload = decode_value(r);
    if (!payload) return nullptr;
    msg = Py_BuildValue("(sKN)", "coord", (unsigned long long)round_no,
                        payload);
  } else if (kind == MSG_HELLO) {
    const uint8_t *w = r.take(4);
    if (!w) {
      wire_err("truncated frame (worker)");
      return nullptr;
    }
    uint32_t worker;
    std::memcpy(&worker, w, 4);
    uint64_t len = r.uvarint();
    const uint8_t *rid = r.take(len);
    if (!rid) {
      wire_err("truncated frame (run id)");
      return nullptr;
    }
    PyObject *rid_str = PyUnicode_DecodeUTF8(
        reinterpret_cast<const char *>(rid), static_cast<Py_ssize_t>(len),
        nullptr);
    if (!rid_str) {
      PyErr_Clear();
      wire_err("bad run id (invalid utf-8)");
      return nullptr;
    }
    msg = Py_BuildValue("(sIN)", "hello", (unsigned int)worker, rid_str);
  } else {
    wire_err("unknown message type");
    return nullptr;
  }
  if (!msg) return nullptr;
  if (r.p != r.end) {
    Py_DECREF(msg);
    wire_err("trailing bytes in frame");
    return nullptr;
  }
  return msg;
}

// C-speed consolidation: sum diffs of identical (key, values), drop zero
// nets, retractions before insertions (mirrors stream.consolidate's
// hashable fast path; raises TypeError for the caller's fallback on
// unhashable values).
PyObject *py_consolidate(PyObject *, PyObject *arg) {
  if (!PyList_CheckExact(arg)) {
    PyErr_SetString(PyExc_TypeError, "consolidate expects a list");
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(arg);
  // validate shape up front: every element must be a (key, values, diff)
  // 3-tuple with an in-range int diff, so the loops below may use the
  // unchecked GET_ITEM / conversion paths safely. The same pass records
  // whether the batch is all-insert (the bulk-ingest fast-path test).
  bool all_insert = true;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PyList_GET_ITEM(arg, i);
    if (!PyTuple_CheckExact(d) || PyTuple_GET_SIZE(d) != 3) {
      PyErr_SetString(PyExc_TypeError,
                      "consolidate expects (key, values, diff) 3-tuples");
      return nullptr;
    }
    int overflow = 0;
    long long dv = PyLong_AsLongLongAndOverflow(PyTuple_GET_ITEM(d, 2),
                                                &overflow);
    if (dv == -1 && PyErr_Occurred()) return nullptr;  // non-int diff
    if (overflow) {
      PyErr_SetString(PyExc_TypeError, "consolidate diff out of i64 range");
      return nullptr;
    }
    if (dv < 0) all_insert = false;
  }
  if (all_insert) {
    PyObject *seen = PySet_New(nullptr);
    if (!seen) return nullptr;
    bool distinct = true;
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *key = PyTuple_GET_ITEM(PyList_GET_ITEM(arg, i), 0);
      int r = PySet_Contains(seen, key);
      if (r < 0) {
        Py_DECREF(seen);
        return nullptr;
      }
      if (r) {
        distinct = false;
        break;
      }
      if (PySet_Add(seen, key) < 0) {
        Py_DECREF(seen);
        return nullptr;
      }
    }
    Py_DECREF(seen);
    if (distinct) {
      Py_INCREF(arg);
      return arg;
    }
  }
  PyObject *acc = PyDict_New();  // (key, values) -> summed diff
  if (!acc) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PyList_GET_ITEM(arg, i);
    PyObject *g = PyTuple_New(2);
    if (!g) {
      Py_DECREF(acc);
      return nullptr;
    }
    PyObject *key = PyTuple_GET_ITEM(d, 0);
    PyObject *values = PyTuple_GET_ITEM(d, 1);
    Py_INCREF(key);
    Py_INCREF(values);
    PyTuple_SET_ITEM(g, 0, key);
    PyTuple_SET_ITEM(g, 1, values);
    PyObject *prev = PyDict_GetItemWithError(acc, g);
    if (!prev && PyErr_Occurred()) {  // unhashable -> caller's fallback
      Py_DECREF(g);
      Py_DECREF(acc);
      return nullptr;
    }
    long long sum = PyLong_AsLongLong(PyTuple_GET_ITEM(d, 2));
    if (prev && __builtin_add_overflow(sum, PyLong_AsLongLong(prev), &sum)) {
      // i64 sum overflow: hand the batch to the caller's arbitrary-
      // precision python fallback rather than wrapping silently
      PyErr_SetString(PyExc_TypeError, "consolidate diff sum overflows i64");
      Py_DECREF(g);
      Py_DECREF(acc);
      return nullptr;
    }
    PyObject *sum_obj = PyLong_FromLongLong(sum);
    if (!sum_obj || PyDict_SetItem(acc, g, sum_obj) < 0) {
      Py_XDECREF(sum_obj);
      Py_DECREF(g);
      Py_DECREF(acc);
      return nullptr;
    }
    Py_DECREF(sum_obj);
    Py_DECREF(g);
  }
  PyObject *neg = PyList_New(0);
  PyObject *pos = PyList_New(0);
  if (!neg || !pos) {
    Py_XDECREF(neg);
    Py_XDECREF(pos);
    Py_DECREF(acc);
    return nullptr;
  }
  PyObject *g, *diff;
  Py_ssize_t pos_i = 0;
  while (PyDict_Next(acc, &pos_i, &g, &diff)) {
    long long dv = PyLong_AsLongLong(diff);
    if (dv == 0) continue;
    PyObject *delta = PyTuple_New(3);
    if (!delta) {
      Py_DECREF(neg);
      Py_DECREF(pos);
      Py_DECREF(acc);
      return nullptr;
    }
    PyObject *key = PyTuple_GET_ITEM(g, 0);
    PyObject *values = PyTuple_GET_ITEM(g, 1);
    Py_INCREF(key);
    Py_INCREF(values);
    Py_INCREF(diff);
    PyTuple_SET_ITEM(delta, 0, key);
    PyTuple_SET_ITEM(delta, 1, values);
    PyTuple_SET_ITEM(delta, 2, diff);
    if (PyList_Append(dv < 0 ? neg : pos, delta) < 0) {
      Py_DECREF(delta);
      Py_DECREF(neg);
      Py_DECREF(pos);
      Py_DECREF(acc);
      return nullptr;
    }
    Py_DECREF(delta);
  }
  Py_DECREF(acc);
  PyObject *result = PySequence_InPlaceConcat(neg, pos);
  Py_DECREF(pos);
  if (!result) {
    Py_DECREF(neg);
    return nullptr;
  }
  return result;  // == neg (in-place concat returns it)
}

// -- bulk Pointer construction ----------------------------------------------
//
// Pointer is a __slots__ class; CPython lays its slots out at fixed
// offsets reachable through the member descriptors in tp_dict. Building
// the objects with tp_alloc + direct slot stores skips the __init__
// bytecode — the per-row key-creation cost that dominates bulk ingest.
// The python side verifies one object built this way against a normally
// constructed Pointer before enabling the path.

Py_ssize_t slot_offset(PyTypeObject *tp, const char *name) {
  PyObject *descr = PyDict_GetItemString(tp->tp_dict, name);
  if (!descr || Py_TYPE(descr) != &PyMemberDescr_Type) return -1;
  PyMemberDef *m = reinterpret_cast<PyMemberDescrObject *>(descr)->d_member;
  if (!m || m->type != T_OBJECT_EX) return -1;
  return m->offset;
}

// Shared slot layout for direct Pointer construction (resolved per call;
// the probe on the Python side guards against layout drift).
struct PointerSlots {
  PyTypeObject *tp;
  Py_ssize_t off_value;
  Py_ssize_t off_origin;
  Py_ssize_t off_h;

  bool resolve() {
    tp = reinterpret_cast<PyTypeObject *>(g_pointer_cls);
    off_value = slot_offset(tp, "value");
    off_origin = slot_offset(tp, "_origin");
    off_h = slot_offset(tp, "_h");
    if (off_value < 0 || off_origin < 0 || off_h < 0) {
      PyErr_SetString(PyExc_TypeError, "Pointer slot layout not recognized");
      return false;
    }
    return true;
  }

  // Build one Pointer from a 16-byte little-endian value; nullptr on error.
  PyObject *build(const uint8_t raw[16]) const {
    uint64_t lo, hi;
    std::memcpy(&lo, raw, 8);
    std::memcpy(&hi, raw + 8, 8);
    PyObject *val = hi ? _PyLong_FromByteArray(raw, 16, 1, 0)
                       : PyLong_FromUnsignedLongLong(lo);
    if (!val) return nullptr;
    Py_hash_t h;
    if (static_cast<uint64_t>(_PyHASH_MODULUS) == ((1ULL << 61) - 1)) {
      // hash(v) of a non-negative int is v mod (2^61 - 1) on 64-bit
      // CPython; computing it from the raw limbs skips a Python call
      // per Pointer (the loader's probe compares against hash()).
      unsigned __int128 v =
          (static_cast<unsigned __int128>(hi) << 64) | lo;
      h = static_cast<Py_hash_t>(
          static_cast<uint64_t>(v % ((1ULL << 61) - 1)));
    } else {
      h = PyObject_Hash(val);
      if (h == -1 && PyErr_Occurred()) {
        Py_DECREF(val);
        return nullptr;
      }
    }
    PyObject *h_obj = PyLong_FromSsize_t(h);
    if (!h_obj) {
      Py_DECREF(val);
      return nullptr;
    }
    PyObject *obj = tp->tp_alloc(tp, 0);
    if (!obj) {
      Py_DECREF(val);
      Py_DECREF(h_obj);
      return nullptr;
    }
    *reinterpret_cast<PyObject **>(reinterpret_cast<char *>(obj) +
                                   off_value) = val;  // steals
    Py_INCREF(Py_None);
    *reinterpret_cast<PyObject **>(reinterpret_cast<char *>(obj) +
                                   off_origin) = Py_None;
    *reinterpret_cast<PyObject **>(reinterpret_cast<char *>(obj) + off_h) =
        h_obj;  // steals
    return obj;
  }
};

// -- blake2b-128 single block (RFC 7693) -------------------------------------
//
// Join output keys must be byte-identical to ref_scalar(lk, rk) — the
// python side hashes b"\x06"+l16+b"\x06"+r16 through hashlib.blake2b with
// digest_size=16. All such messages fit one compression block, so a
// specialized unkeyed single-block compress suffices (verified against
// hashlib by the Python loader before the path is enabled).

const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

#define B2B_G(a, b, c, d, x, y)          \
  do {                                   \
    a = a + b + (x);                     \
    d = rotr64(d ^ a, 32);               \
    c = c + d;                           \
    b = rotr64(b ^ c, 24);               \
    a = a + b + (y);                     \
    d = rotr64(d ^ a, 16);               \
    c = c + d;                           \
    b = rotr64(b ^ c, 63);               \
  } while (0)

// unkeyed blake2b, digest 16 bytes, message length <= 128 (one block)
void blake2b128_single(const uint8_t *msg, size_t len, uint8_t out[16]) {
  uint64_t m[16] = {0};
  std::memcpy(m, msg, len);
  uint64_t v[16];
  for (int i = 0; i < 8; i++) v[i] = B2B_IV[i];
  v[0] ^= 0x01010010ULL;  // digest_length=16, fanout=1, depth=1
  uint64_t h0 = v[0], h1 = v[1];
  for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
  v[12] ^= static_cast<uint64_t>(len);  // t0 = bytes compressed
  v[14] = ~v[14];                       // final-block flag
  for (int r = 0; r < 12; r++) {
    const uint8_t *s = B2B_SIGMA[r];
    B2B_G(v[0], v[4], v[8], v[12], m[s[0]], m[s[1]]);
    B2B_G(v[1], v[5], v[9], v[13], m[s[2]], m[s[3]]);
    B2B_G(v[2], v[6], v[10], v[14], m[s[4]], m[s[5]]);
    B2B_G(v[3], v[7], v[11], v[15], m[s[6]], m[s[7]]);
    B2B_G(v[0], v[5], v[10], v[15], m[s[8]], m[s[9]]);
    B2B_G(v[1], v[6], v[11], v[12], m[s[10]], m[s[11]]);
    B2B_G(v[2], v[7], v[8], v[13], m[s[12]], m[s[13]]);
    B2B_G(v[3], v[4], v[9], v[14], m[s[14]], m[s[15]]);
  }
  h0 ^= v[0] ^ v[8];
  h1 ^= v[1] ^ v[9];
  std::memcpy(out, &h0, 8);
  std::memcpy(out + 8, &h1, 8);
}

// make_pair_pointers(lvals: bytes n*16 LE, rvals: bytes n*16 LE) -> list
//
// The columnar join's output-key kernel: per row, blake2b-128 over the
// 34-byte message \x06+l16+\x06+r16 (identical to ref_scalar(lk, rk))
// and a direct-slot Pointer from the digest.
PyObject *py_make_pair_pointers(PyObject *, PyObject *args) {
  Py_buffer lvals, rvals;
  if (!PyArg_ParseTuple(args, "y*y*", &lvals, &rvals)) return nullptr;
  if (lvals.len % 16 != 0 || lvals.len != rvals.len) {
    PyBuffer_Release(&lvals);
    PyBuffer_Release(&rvals);
    PyErr_SetString(PyExc_ValueError,
                    "lvals/rvals must be equal-length 16-byte-aligned");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) {
    PyBuffer_Release(&lvals);
    PyBuffer_Release(&rvals);
    return nullptr;
  }
  Py_ssize_t n = lvals.len / 16;
  const uint8_t *lp = static_cast<const uint8_t *>(lvals.buf);
  const uint8_t *rp = static_cast<const uint8_t *>(rvals.buf);
  PyObject *out = PyList_New(n);
  if (!out) {
    PyBuffer_Release(&lvals);
    PyBuffer_Release(&rvals);
    return nullptr;
  }
  uint8_t msg[34];
  uint8_t dig[16];
  msg[0] = 0x06;
  msg[17] = 0x06;
  for (Py_ssize_t i = 0; i < n; i++) {
    std::memcpy(msg + 1, lp + i * 16, 16);
    std::memcpy(msg + 18, rp + i * 16, 16);
    blake2b128_single(msg, 34, dig);
    PyObject *obj = slots.build(dig);
    if (!obj) {
      PyBuffer_Release(&lvals);
      PyBuffer_Release(&rvals);
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, obj);
  }
  PyBuffer_Release(&lvals);
  PyBuffer_Release(&rvals);
  return out;
}

// make_pointers_u128(vals: bytes n*16 LE) -> list
//
// Bulk Pointer construction from precomputed 128-bit values with VARYING
// high limbs (make_seq_pointers covers only a constant hi) — the flatten
// path derives element keys vectorized in numpy and materializes the
// Pointer objects here.
PyObject *py_make_pointers_u128(PyObject *, PyObject *arg) {
  Py_buffer vals;
  if (PyObject_GetBuffer(arg, &vals, PyBUF_SIMPLE) != 0) return nullptr;
  if (vals.len % 16 != 0) {
    PyBuffer_Release(&vals);
    PyErr_SetString(PyExc_ValueError, "vals must be 16-byte-aligned bytes");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) {
    PyBuffer_Release(&vals);
    return nullptr;
  }
  Py_ssize_t n = vals.len / 16;
  const uint8_t *src = static_cast<const uint8_t *>(vals.buf);
  PyObject *out = PyList_New(n);
  if (!out) {
    PyBuffer_Release(&vals);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *obj = slots.build(src + i * 16);
    if (!obj) {
      PyBuffer_Release(&vals);
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, obj);
  }
  PyBuffer_Release(&vals);
  return out;
}

// Read a Pointer's 128-bit value slot as 16 little-endian bytes.
inline bool ptr_value_le16(PyObject *obj, const PointerSlots &slots,
                           uint8_t out[16]) {
  if (Py_TYPE(obj) != slots.tp) {
    PyErr_SetString(PyExc_TypeError, "expected Pointer");
    return false;
  }
  PyObject *val = *reinterpret_cast<PyObject **>(
      reinterpret_cast<char *>(obj) + slots.off_value);
  if (!val || !PyLong_Check(val)) {
    PyErr_SetString(PyExc_TypeError, "Pointer.value is not an int");
    return false;
  }
  return _PyLong_AsByteArray(reinterpret_cast<PyLongObject *>(val), out, 16,
                             1, 0) == 0;
}

// make_pair_pointers_list(lks: list[Pointer], rks: list[Pointer]) -> list
//
// ref_scalar(lk, rk) straight from the Pointer objects: the value slots
// are read in C, so callers need no 16-byte-LE buffer bookkeeping.
PyObject *py_make_pair_pointers_list(PyObject *, PyObject *args) {
  PyObject *lks, *rks;
  if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &lks, &PyList_Type,
                        &rks))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(lks);
  if (PyList_GET_SIZE(rks) != n) {
    PyErr_SetString(PyExc_ValueError, "lks/rks length mismatch");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) return nullptr;
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  uint8_t msg[34];
  uint8_t dig[16];
  msg[0] = 0x06;
  msg[17] = 0x06;
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!ptr_value_le16(PyList_GET_ITEM(lks, i), slots, msg + 1) ||
        !ptr_value_le16(PyList_GET_ITEM(rks, i), slots, msg + 18)) {
      Py_DECREF(out);
      return nullptr;
    }
    blake2b128_single(msg, 34, dig);
    PyObject *obj = slots.build(dig);
    if (!obj) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, obj);
  }
  return out;
}

// make_join_triples(lks, rks, lrows, rrows, diffs) -> list
//
// The columnar join's fused output kernel: one C pass per match
// producing (ref_scalar(lk, rk), (lk, rk, *lrow, *rrow), diff) — the
// blake2b pair key, the direct-slot Pointer, and the output row tuple,
// replacing a Python zip/concat comprehension over five parallel lists.
PyObject *py_make_join_triples(PyObject *, PyObject *args) {
  PyObject *lks, *rks, *lrows, *rrows, *diffs;
  if (!PyArg_ParseTuple(args, "O!O!O!O!O!", &PyList_Type, &lks, &PyList_Type,
                        &rks, &PyList_Type, &lrows, &PyList_Type, &rrows,
                        &PyList_Type, &diffs))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(lks);
  if (PyList_GET_SIZE(rks) != n || PyList_GET_SIZE(lrows) != n ||
      PyList_GET_SIZE(rrows) != n || PyList_GET_SIZE(diffs) != n) {
    PyErr_SetString(PyExc_ValueError, "input list length mismatch");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) return nullptr;
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  uint8_t msg[34];
  uint8_t dig[16];
  msg[0] = 0x06;
  msg[17] = 0x06;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *lk = PyList_GET_ITEM(lks, i);
    PyObject *rk = PyList_GET_ITEM(rks, i);
    PyObject *lrow = PyList_GET_ITEM(lrows, i);
    PyObject *rrow = PyList_GET_ITEM(rrows, i);
    if (!PyTuple_Check(lrow) || !PyTuple_Check(rrow)) {
      PyErr_SetString(PyExc_TypeError, "rows must be tuples");
      Py_DECREF(out);
      return nullptr;
    }
    if (!ptr_value_le16(lk, slots, msg + 1) ||
        !ptr_value_le16(rk, slots, msg + 18)) {
      Py_DECREF(out);
      return nullptr;
    }
    blake2b128_single(msg, 34, dig);
    PyObject *key = slots.build(dig);
    if (!key) {
      Py_DECREF(out);
      return nullptr;
    }
    Py_ssize_t nl = PyTuple_GET_SIZE(lrow);
    Py_ssize_t nr = PyTuple_GET_SIZE(rrow);
    PyObject *row = PyTuple_New(2 + nl + nr);
    if (!row) {
      Py_DECREF(key);
      Py_DECREF(out);
      return nullptr;
    }
    Py_INCREF(lk);
    PyTuple_SET_ITEM(row, 0, lk);
    Py_INCREF(rk);
    PyTuple_SET_ITEM(row, 1, rk);
    for (Py_ssize_t j = 0; j < nl; j++) {
      PyObject *v = PyTuple_GET_ITEM(lrow, j);
      Py_INCREF(v);
      PyTuple_SET_ITEM(row, 2 + j, v);
    }
    for (Py_ssize_t j = 0; j < nr; j++) {
      PyObject *v = PyTuple_GET_ITEM(rrow, j);
      Py_INCREF(v);
      PyTuple_SET_ITEM(row, 2 + nl + j, v);
    }
    PyObject *triple = PyTuple_New(3);
    if (!triple) {
      Py_DECREF(key);
      Py_DECREF(row);
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(triple, 0, key);  // steals
    PyTuple_SET_ITEM(triple, 1, row);  // steals
    PyObject *d = PyList_GET_ITEM(diffs, i);
    Py_INCREF(d);
    PyTuple_SET_ITEM(triple, 2, d);
    PyList_SET_ITEM(out, i, triple);
  }
  return out;
}

// join_delta_side(jv_code, jvs, deltas, left_rows, right_rows,
//                 left_side, error_cls, out) -> (saw_retract, n_errors)
//
// One C pass over a delta batch for the columnar join's delta mode:
// join-value -> dense code lookup (allocating a fresh code + empty
// buckets on both sides on a miss), match expansion against the other
// side's bucket with fused (ref_scalar key, (lk, rk, *lrow, *rrow),
// diff) triple construction appended to `out`, and own-bucket update
// in stream order — the exact interleaving of the classic
// JoinNode._delta_side. Error join values are counted and skipped;
// the caller logs them.
PyObject *py_join_delta_side(PyObject *, PyObject *args) {
  PyObject *jv_code, *jvs, *deltas, *left_rows, *right_rows, *error_cls,
      *out;
  int left_side;
  if (!PyArg_ParseTuple(args, "O!O!O!O!O!iOO!", &PyDict_Type, &jv_code,
                        &PyList_Type, &jvs, &PyList_Type, &deltas,
                        &PyList_Type, &left_rows, &PyList_Type, &right_rows,
                        &left_side, &error_cls, &PyList_Type, &out))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  if (PyList_GET_SIZE(jvs) != n) {
    PyErr_SetString(PyExc_ValueError, "jvs/deltas length mismatch");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) return nullptr;
  uint8_t msg[34];
  uint8_t dig[16];
  msg[0] = 0x06;
  msg[17] = 0x06;
  uint8_t *own16 = left_side ? msg + 1 : msg + 18;
  uint8_t *oth16 = left_side ? msg + 18 : msg + 1;
  int saw_retract = 0;
  long n_errors = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *delta = PyList_GET_ITEM(deltas, i);
    if (!PyTuple_Check(delta) || PyTuple_GET_SIZE(delta) != 3) {
      PyErr_SetString(PyExc_TypeError, "deltas must be (key, row, diff)");
      return nullptr;
    }
    PyObject *key = PyTuple_GET_ITEM(delta, 0);
    PyObject *row = PyTuple_GET_ITEM(delta, 1);
    PyObject *diff = PyTuple_GET_ITEM(delta, 2);
    if (!PyTuple_Check(row)) {
      PyErr_SetString(PyExc_TypeError, "rows must be tuples");
      return nullptr;
    }
    long d = PyLong_AsLong(diff);
    if (d == -1 && PyErr_Occurred()) return nullptr;
    PyObject *jv = PyList_GET_ITEM(jvs, i);
    Py_ssize_t code;
    PyObject *code_obj = PyDict_GetItemWithError(jv_code, jv);
    if (code_obj) {
      code = PyLong_AsSsize_t(code_obj);
      if (code == -1 && PyErr_Occurred()) return nullptr;
    } else {
      if (PyErr_Occurred()) return nullptr;
      int is_err = PyObject_IsInstance(jv, error_cls);
      if (is_err < 0) return nullptr;
      if (is_err) {
        n_errors++;
        continue;
      }
      code = PyList_GET_SIZE(left_rows);
      for (int side = 0; side < 2; side++) {
        PyObject *bucket = PyDict_New();
        if (!bucket) return nullptr;
        int rc = PyList_Append(side ? right_rows : left_rows, bucket);
        Py_DECREF(bucket);
        if (rc < 0) return nullptr;
      }
      code_obj = PyLong_FromSsize_t(code);
      if (!code_obj) return nullptr;
      int rc = PyDict_SetItem(jv_code, jv, code_obj);
      Py_DECREF(code_obj);
      if (rc < 0) return nullptr;
    }
    if (code < 0 || code >= PyList_GET_SIZE(left_rows) ||
        code >= PyList_GET_SIZE(right_rows)) {
      PyErr_SetString(PyExc_ValueError, "jv_code entry out of range");
      return nullptr;
    }
    PyObject *own =
        PyList_GET_ITEM(left_side ? left_rows : right_rows, code);
    PyObject *other =
        PyList_GET_ITEM(left_side ? right_rows : left_rows, code);
    if (!PyDict_Check(own) || !PyDict_Check(other)) {
      PyErr_SetString(PyExc_TypeError, "row buckets must be dicts");
      return nullptr;
    }
    if (PyDict_GET_SIZE(other) > 0) {
      if (!ptr_value_le16(key, slots, own16)) return nullptr;
      Py_ssize_t pos = 0;
      PyObject *okey, *orow;
      while (PyDict_Next(other, &pos, &okey, &orow)) {
        if (!PyTuple_Check(orow)) {
          PyErr_SetString(PyExc_TypeError, "rows must be tuples");
          return nullptr;
        }
        if (!ptr_value_le16(okey, slots, oth16)) return nullptr;
        blake2b128_single(msg, 34, dig);
        PyObject *pair = slots.build(dig);
        if (!pair) return nullptr;
        PyObject *lk = left_side ? key : okey;
        PyObject *rk = left_side ? okey : key;
        PyObject *lrow = left_side ? row : orow;
        PyObject *rrow = left_side ? orow : row;
        Py_ssize_t nl = PyTuple_GET_SIZE(lrow);
        Py_ssize_t nr = PyTuple_GET_SIZE(rrow);
        PyObject *orow_t = PyTuple_New(2 + nl + nr);
        if (!orow_t) {
          Py_DECREF(pair);
          return nullptr;
        }
        Py_INCREF(lk);
        PyTuple_SET_ITEM(orow_t, 0, lk);
        Py_INCREF(rk);
        PyTuple_SET_ITEM(orow_t, 1, rk);
        for (Py_ssize_t j = 0; j < nl; j++) {
          PyObject *v = PyTuple_GET_ITEM(lrow, j);
          Py_INCREF(v);
          PyTuple_SET_ITEM(orow_t, 2 + j, v);
        }
        for (Py_ssize_t j = 0; j < nr; j++) {
          PyObject *v = PyTuple_GET_ITEM(rrow, j);
          Py_INCREF(v);
          PyTuple_SET_ITEM(orow_t, 2 + nl + j, v);
        }
        PyObject *triple = PyTuple_New(3);
        if (!triple) {
          Py_DECREF(pair);
          Py_DECREF(orow_t);
          return nullptr;
        }
        PyTuple_SET_ITEM(triple, 0, pair);    // steals
        PyTuple_SET_ITEM(triple, 1, orow_t);  // steals
        Py_INCREF(diff);
        PyTuple_SET_ITEM(triple, 2, diff);
        int rc = PyList_Append(out, triple);
        Py_DECREF(triple);
        if (rc < 0) return nullptr;
      }
    }
    if (d > 0) {
      if (PyDict_SetItem(own, key, row) < 0) return nullptr;
    } else {
      saw_retract = 1;
      int has = PyDict_Contains(own, key);
      if (has < 0) return nullptr;
      if (has && PyDict_DelItem(own, key) < 0) return nullptr;
    }
  }
  return Py_BuildValue("(il)", saw_retract, n_errors);
}

// make_triples_u128(vals: bytes n*16 LE, rows: list, diffs: list) -> list
//
// Bulk (Pointer(v_i), rows[i], diffs[i]) triples from precomputed
// 128-bit key values — the flatten path's output assembly.
PyObject *py_make_triples_u128(PyObject *, PyObject *args) {
  Py_buffer vals;
  PyObject *rows, *diffs;
  if (!PyArg_ParseTuple(args, "y*O!O!", &vals, &PyList_Type, &rows,
                        &PyList_Type, &diffs))
    return nullptr;
  if (vals.len % 16 != 0) {
    PyBuffer_Release(&vals);
    PyErr_SetString(PyExc_ValueError, "vals must be 16-byte-aligned bytes");
    return nullptr;
  }
  Py_ssize_t n = vals.len / 16;
  if (PyList_GET_SIZE(rows) != n || PyList_GET_SIZE(diffs) != n) {
    PyBuffer_Release(&vals);
    PyErr_SetString(PyExc_ValueError, "vals/rows/diffs length mismatch");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) {
    PyBuffer_Release(&vals);
    return nullptr;
  }
  const uint8_t *src = static_cast<const uint8_t *>(vals.buf);
  PyObject *out = PyList_New(n);
  if (!out) {
    PyBuffer_Release(&vals);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *key = slots.build(src + i * 16);
    if (!key) {
      PyBuffer_Release(&vals);
      Py_DECREF(out);
      return nullptr;
    }
    PyObject *triple = PyTuple_New(3);
    if (!triple) {
      Py_DECREF(key);
      PyBuffer_Release(&vals);
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(triple, 0, key);
    PyObject *r = PyList_GET_ITEM(rows, i);
    Py_INCREF(r);
    PyTuple_SET_ITEM(triple, 1, r);
    PyObject *d = PyList_GET_ITEM(diffs, i);
    Py_INCREF(d);
    PyTuple_SET_ITEM(triple, 2, d);
    PyList_SET_ITEM(out, i, triple);
  }
  PyBuffer_Release(&vals);
  return out;
}

// flatten_triples(vals: bytes n*16 LE, parents: list[tuple],
//                 counts: list[int], elems: list, flat_idx: int,
//                 diffs: list) -> list
//
// The columnar flatten's fused output assembly: per element, build the
// output row (the parent row with the sequence column replaced by the
// element), the derived-key Pointer from the precomputed 128-bit value,
// and the delta triple — one C pass instead of a python row
// comprehension feeding make_triples_u128.
PyObject *py_flatten_triples(PyObject *, PyObject *args) {
  Py_buffer vals;
  PyObject *parents, *counts, *elems, *diffs;
  Py_ssize_t flat_idx;
  if (!PyArg_ParseTuple(args, "y*O!O!O!nO!", &vals, &PyList_Type, &parents,
                        &PyList_Type, &counts, &PyList_Type, &elems,
                        &flat_idx, &PyList_Type, &diffs))
    return nullptr;
  Py_ssize_t np_ = PyList_GET_SIZE(parents);
  Py_ssize_t total = PyList_GET_SIZE(elems);
  if (PyList_GET_SIZE(counts) != np_ || PyList_GET_SIZE(diffs) != np_ ||
      vals.len != total * 16) {
    PyBuffer_Release(&vals);
    PyErr_SetString(PyExc_ValueError,
                    "parents/counts/diffs/elems/vals length mismatch");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) {
    PyBuffer_Release(&vals);
    return nullptr;
  }
  const uint8_t *src = static_cast<const uint8_t *>(vals.buf);
  PyObject *out = PyList_New(total);
  if (!out) {
    PyBuffer_Release(&vals);
    return nullptr;
  }
  Py_ssize_t pos = 0;
  for (Py_ssize_t i = 0; i < np_; i++) {
    PyObject *row = PyList_GET_ITEM(parents, i);
    PyObject *diff = PyList_GET_ITEM(diffs, i);
    Py_ssize_t m = PyLong_AsSsize_t(PyList_GET_ITEM(counts, i));
    if (m == -1 && PyErr_Occurred()) goto fail;
    if (!PyTuple_Check(row) || flat_idx < 0 ||
        flat_idx >= PyTuple_GET_SIZE(row)) {
      PyErr_SetString(PyExc_TypeError,
                      "parent rows must be tuples containing flat_idx");
      goto fail;
    }
    if (pos + m > total) {
      PyErr_SetString(PyExc_ValueError, "counts exceed element total");
      goto fail;
    }
    {
      Py_ssize_t w = PyTuple_GET_SIZE(row);
      for (Py_ssize_t j = 0; j < m; j++, pos++) {
        PyObject *new_row = PyTuple_New(w);
        if (!new_row) goto fail;
        for (Py_ssize_t c = 0; c < w; c++) {
          PyObject *v = (c == flat_idx) ? PyList_GET_ITEM(elems, pos)
                                        : PyTuple_GET_ITEM(row, c);
          Py_INCREF(v);
          PyTuple_SET_ITEM(new_row, c, v);
        }
        PyObject *key = slots.build(src + pos * 16);
        if (!key) {
          Py_DECREF(new_row);
          goto fail;
        }
        PyObject *triple = PyTuple_New(3);
        if (!triple) {
          Py_DECREF(new_row);
          Py_DECREF(key);
          goto fail;
        }
        PyTuple_SET_ITEM(triple, 0, key);      // steals
        PyTuple_SET_ITEM(triple, 1, new_row);  // steals
        Py_INCREF(diff);
        PyTuple_SET_ITEM(triple, 2, diff);
        PyList_SET_ITEM(out, pos, triple);
      }
    }
  }
  if (pos != total) {
    PyErr_SetString(PyExc_ValueError, "counts do not cover element total");
    goto fail;
  }
  PyBuffer_Release(&vals);
  return out;
fail:
  PyBuffer_Release(&vals);
  Py_DECREF(out);
  return nullptr;
}

// make_seq_pointers(hi64: int, lows: bytes of little-endian u64) -> list
PyObject *py_make_seq_pointers(PyObject *, PyObject *args) {
  unsigned long long hi64;
  Py_buffer lows;
  if (!PyArg_ParseTuple(args, "Ky*", &hi64, &lows)) return nullptr;
  if (lows.len % 8 != 0) {
    PyBuffer_Release(&lows);
    PyErr_SetString(PyExc_ValueError, "lows must be u64-aligned bytes");
    return nullptr;
  }
  PyTypeObject *tp = reinterpret_cast<PyTypeObject *>(g_pointer_cls);
  Py_ssize_t off_value = slot_offset(tp, "value");
  Py_ssize_t off_origin = slot_offset(tp, "_origin");
  Py_ssize_t off_h = slot_offset(tp, "_h");
  if (off_value < 0 || off_origin < 0 || off_h < 0) {
    PyBuffer_Release(&lows);
    PyErr_SetString(PyExc_TypeError, "Pointer slot layout not recognized");
    return nullptr;
  }
  Py_ssize_t n = lows.len / 8;
  const uint8_t *src = static_cast<const uint8_t *>(lows.buf);
  PyObject *out = PyList_New(n);
  if (!out) {
    PyBuffer_Release(&lows);
    return nullptr;
  }
  uint8_t raw[16];
  std::memcpy(raw + 8, &hi64, 8);
  for (Py_ssize_t i = 0; i < n; i++) {
    std::memcpy(raw, src + i * 8, 8);
    PyObject *val =
        hi64 ? _PyLong_FromByteArray(raw, 16, 1, 0)
             : PyLong_FromUnsignedLongLong(
                   *reinterpret_cast<const uint64_t *>(src + i * 8));
    if (!val) goto fail;
    {
      Py_hash_t h = PyObject_Hash(val);
      if (h == -1 && PyErr_Occurred()) {
        Py_DECREF(val);
        goto fail;
      }
      PyObject *h_obj = PyLong_FromSsize_t(h);
      if (!h_obj) {
        Py_DECREF(val);
        goto fail;
      }
      PyObject *obj = tp->tp_alloc(tp, 0);
      if (!obj) {
        Py_DECREF(val);
        Py_DECREF(h_obj);
        goto fail;
      }
      *reinterpret_cast<PyObject **>(reinterpret_cast<char *>(obj) +
                                     off_value) = val;  // steals
      Py_INCREF(Py_None);
      *reinterpret_cast<PyObject **>(reinterpret_cast<char *>(obj) +
                                     off_origin) = Py_None;
      *reinterpret_cast<PyObject **>(reinterpret_cast<char *>(obj) + off_h) =
          h_obj;  // steals
      PyList_SET_ITEM(out, i, obj);
    }
  }
  PyBuffer_Release(&lows);
  return out;
fail:
  PyBuffer_Release(&lows);
  Py_DECREF(out);
  return nullptr;
}

// -- columnar exchange routing ----------------------------------------------
//
// The exchange node's shard codes in bulk: pointer_shards reads the low
// 16 bits of every key's value slot in one C pass; ref_shards computes
// ref_scalar(v).shard for the common scalar types by serializing each
// value exactly as value._serialize_for_hash does and taking the first
// two digest bytes of the single-block blake2b-128 (the low 16 bits of
// the little-endian digest int). Types the kernel does not cover come
// back as "unresolved" indices for the Python caller to patch — so the
// kernel can never silently diverge from the Python routing.

// pointer_shards(keys: list[Pointer]) -> bytes (n x u16 LE shard codes)
PyObject *py_pointer_shards(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "pointer_shards expects a list");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 2);
  if (!out) return nullptr;
  uint8_t *dst = reinterpret_cast<uint8_t *>(PyBytes_AS_STRING(out));
  uint8_t raw[16];
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!ptr_value_le16(PyList_GET_ITEM(arg, i), slots, raw)) {
      Py_DECREF(out);
      return nullptr;
    }
    dst[2 * i] = raw[0];
    dst[2 * i + 1] = raw[1];
  }
  return out;
}

// ref_shards(values: list) -> (bytes n x u16 LE, list[int] unresolved)
PyObject *py_ref_shards(PyObject *, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "ref_shards expects a list");
    return nullptr;
  }
  PointerSlots slots;
  if (!slots.resolve()) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(arg);
  PyObject *shards = PyBytes_FromStringAndSize(nullptr, n * 2);
  if (!shards) return nullptr;
  PyObject *unresolved = PyList_New(0);
  if (!unresolved) {
    Py_DECREF(shards);
    return nullptr;
  }
  uint8_t *dst = reinterpret_cast<uint8_t *>(PyBytes_AS_STRING(shards));
  uint8_t msg[128];
  uint8_t dig[16];
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *v = PyList_GET_ITEM(arg, i);
    size_t len = 0;
    bool ok = true;
    bool hashed = true;
    uint16_t code = 0;
    if (Py_TYPE(v) == slots.tp) {
      // a Pointer routes by its own shard bits, no rehash
      uint8_t raw[16];
      if (ptr_value_le16(v, slots, raw)) {
        code = static_cast<uint16_t>(raw[0] | (raw[1] << 8));
        hashed = false;
      } else {
        PyErr_Clear();
        ok = false;
      }
    } else if (v == Py_None) {
      msg[0] = 0x00;
      msg[1] = 'N';
      len = 2;
    } else if (PyBool_Check(v)) {
      msg[0] = 0x01;
      msg[1] = (v == Py_True) ? 0x01 : 0x00;
      len = 2;
    } else if (PyLong_CheckExact(v)) {
      msg[0] = 0x02;
      if (_PyLong_AsByteArray(reinterpret_cast<PyLongObject *>(v), msg + 1,
                              16, 1, 1) != 0) {
        PyErr_Clear();  // >128-bit int: Python path raises -> unroutable
        ok = false;
      } else {
        len = 17;
      }
    } else if (PyFloat_CheckExact(v)) {
      double d = PyFloat_AS_DOUBLE(v);
      if (d == std::floor(d) && std::fabs(d) < 4611686018427387904.0) {
        // integral floats hash as their int (1 == 1.0 for keying)
        int64_t iv = static_cast<int64_t>(d);
        uint64_t u = static_cast<uint64_t>(iv);
        msg[0] = 0x02;
        std::memcpy(msg + 1, &u, 8);
        std::memset(msg + 9, iv < 0 ? 0xFF : 0x00, 8);
        len = 17;
      } else {
        msg[0] = 0x03;
        std::memcpy(msg + 1, &d, 8);
        len = 9;
      }
    } else if (PyUnicode_CheckExact(v)) {
      Py_ssize_t sl;
      const char *s = PyUnicode_AsUTF8AndSize(v, &sl);
      if (!s) {
        PyErr_Clear();
        ok = false;
      } else if (sl <= 119) {  // 1 + 8 + len must fit one blake2b block
        uint64_t L = static_cast<uint64_t>(sl);
        msg[0] = 0x04;
        std::memcpy(msg + 1, &L, 8);
        std::memcpy(msg + 9, s, static_cast<size_t>(sl));
        len = 9 + static_cast<size_t>(sl);
      } else {
        ok = false;
      }
    } else if (PyBytes_CheckExact(v)) {
      Py_ssize_t bl = PyBytes_GET_SIZE(v);
      if (bl <= 119) {
        uint64_t L = static_cast<uint64_t>(bl);
        msg[0] = 0x05;
        std::memcpy(msg + 1, &L, 8);
        std::memcpy(msg + 9, PyBytes_AS_STRING(v), static_cast<size_t>(bl));
        len = 9 + static_cast<size_t>(bl);
      } else {
        ok = false;
      }
    } else {
      ok = false;  // containers, ndarrays, subclasses: Python fallback
    }
    if (ok && hashed) {
      blake2b128_single(msg, len, dig);
      code = static_cast<uint16_t>(dig[0] | (dig[1] << 8));
    }
    if (!ok) {
      code = 0;
      PyObject *idx = PyLong_FromSsize_t(i);
      if (!idx || PyList_Append(unresolved, idx) < 0) {
        Py_XDECREF(idx);
        Py_DECREF(shards);
        Py_DECREF(unresolved);
        return nullptr;
      }
      Py_DECREF(idx);
    }
    dst[2 * i] = static_cast<uint8_t>(code & 0xFF);
    dst[2 * i + 1] = static_cast<uint8_t>(code >> 8);
  }
  return Py_BuildValue("(NN)", shards, unresolved);
}

// partition_deltas(deltas: list, shards: bytes n x u16 LE, nparts: int)
//   -> list of nparts lists
//
// Single C pass replacing the per-row `parts[shard % n].append(d)` loop:
// count, allocate each partition exactly-sized, fill. Order within each
// partition preserves stream order.
PyObject *py_partition_deltas(PyObject *, PyObject *args) {
  PyObject *deltas;
  Py_buffer shards;
  Py_ssize_t nparts;
  if (!PyArg_ParseTuple(args, "O!y*n", &PyList_Type, &deltas, &shards,
                        &nparts))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(deltas);
  if (shards.len != n * 2 || nparts <= 0) {
    PyBuffer_Release(&shards);
    PyErr_SetString(PyExc_ValueError,
                    "shards must be 2*len(deltas) bytes, nparts > 0");
    return nullptr;
  }
  const uint8_t *sp = static_cast<const uint8_t *>(shards.buf);
  std::vector<Py_ssize_t> counts(static_cast<size_t>(nparts), 0);
  std::vector<uint32_t> part_of(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; i++) {
    uint32_t code = static_cast<uint32_t>(sp[2 * i] | (sp[2 * i + 1] << 8));
    uint32_t p = code % static_cast<uint32_t>(nparts);
    part_of[i] = p;
    counts[p]++;
  }
  PyObject *out = PyList_New(nparts);
  if (!out) {
    PyBuffer_Release(&shards);
    return nullptr;
  }
  for (Py_ssize_t p = 0; p < nparts; p++) {
    PyObject *part = PyList_New(counts[p]);
    if (!part) {
      PyBuffer_Release(&shards);
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, p, part);
  }
  std::vector<Py_ssize_t> fill(static_cast<size_t>(nparts), 0);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *d = PyList_GET_ITEM(deltas, i);
    Py_INCREF(d);
    PyList_SET_ITEM(PyList_GET_ITEM(out, part_of[i]), fill[part_of[i]]++, d);
  }
  PyBuffer_Release(&shards);
  return out;
}

PyMethodDef methods[] = {
    {"make_seq_pointers", py_make_seq_pointers, METH_VARARGS,
     "bulk-construct Pointer objects from (hi64, u64-LE bytes)"},
    {"make_pair_pointers", py_make_pair_pointers, METH_VARARGS,
     "bulk ref_scalar(lk, rk): blake2b-128 over paired 16-byte LE key "
     "values, returned as Pointer objects"},
    {"make_pointers_u128", py_make_pointers_u128, METH_O,
     "bulk-construct Pointer objects from 16-byte LE value records"},
    {"make_pair_pointers_list", py_make_pair_pointers_list, METH_VARARGS,
     "bulk ref_scalar(lk, rk) from two Pointer lists"},
    {"make_join_triples", py_make_join_triples, METH_VARARGS,
     "fused join output: (pair key, (lk, rk, *lrow, *rrow), diff) triples"},
    {"make_triples_u128", py_make_triples_u128, METH_VARARGS,
     "bulk (Pointer, row, diff) triples from 16-byte LE key values"},
    {"flatten_triples", py_flatten_triples, METH_VARARGS,
     "fused flatten output: derived-key Pointer, element row, diff "
     "triples from per-parent rows + flat element list"},
    {"join_delta_side", py_join_delta_side, METH_VARARGS,
     "fused delta-mode join pass: code lookup, match expansion with "
     "triple construction, and own-bucket update in one C loop"},
    {"register_types", py_register_types, METH_VARARGS,
     "register engine value classes and rare-type helpers"},
    {"encode_message", py_encode_message, METH_O,
     "encode an exchange message tuple to bytes"},
    {"encode_frame", py_encode_frame, METH_O,
     "encode an exchange message tuple to a length-prefixed wire frame"},
    {"pointer_shards", py_pointer_shards, METH_O,
     "bulk shard codes (u16 LE bytes) from a list of Pointer keys"},
    {"ref_shards", py_ref_shards, METH_O,
     "bulk ref_scalar(v).shard codes for scalar values; returns "
     "(u16 LE bytes, unresolved index list)"},
    {"partition_deltas", py_partition_deltas, METH_VARARGS,
     "partition a delta list into nparts lists by shard % nparts in one "
     "C pass"},
    {"decode_message", py_decode_message, METH_O,
     "decode bytes to an exchange message tuple"},
    {"consolidate", py_consolidate, METH_O,
     "sum diffs of identical (key, values); raises TypeError on "
     "unhashable values"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "pw_wire_ext",
                      "native wire codec + consolidation", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit_pw_wire_ext(void) { return PyModule_Create(&module); }
