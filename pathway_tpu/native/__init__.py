"""Native (C++) runtime components, built on demand via the system
toolchain and loaded with ctypes.

The reference keeps its hot runtime in Rust (src/engine, src/connectors);
here the compute hot path is XLA, and the native layer covers the host-side
feeding work that would otherwise bottleneck the chip — currently the batch
tokenizer. Falls back to the pure-python implementations when no compiler
is available.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_lib = None
_build_failed = False


def _source_path(name: str) -> str:
    return os.path.join(os.path.dirname(__file__), name)


def _cache_dir() -> str:
    root = os.environ.get(
        "PATHWAY_NATIVE_CACHE",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "pathway_tpu",
        ),
    )
    os.makedirs(root, exist_ok=True)
    return root


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed or os.environ.get("PATHWAY_DISABLE_NATIVE"):
        return None
    source = _source_path("tokenizer.cpp")
    try:
        with open(source, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        so_path = os.path.join(_cache_dir(), f"pw_native_{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".tmp{os.getpid()}"
            subprocess.run(
                [
                    "g++",
                    "-O3",
                    "-shared",
                    "-fPIC",
                    "-std=c++17",
                    source,
                    "-o",
                    tmp,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, so_path)
        lib = ctypes.CDLL(so_path)
        lib.tokenize_batch.restype = ctypes.c_int32
        lib.tokenize_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.count_tokens.restype = ctypes.c_int32
        lib.count_tokens.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        _lib = lib
        return lib
    except Exception:  # noqa: BLE001 — fall back to python
        _build_failed = True
        return None


def tokenize_batch_native(texts, vocab_size: int, seq_len: int):
    """Returns (ids, mask) int32 [n, seq_len] numpy arrays, or None when
    the native library is unavailable."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    encoded = [t.encode("utf-8", errors="replace") for t in texts]
    buffer = b"".join(encoded)
    offsets = np.zeros(len(texts) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    n = len(texts)
    ids = np.zeros((n, seq_len), dtype=np.int32)
    mask = np.zeros((n, seq_len), dtype=np.int32)
    lib.tokenize_batch(
        buffer,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        vocab_size,
        seq_len,
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return ids, mask


def count_tokens_native(text: str) -> Optional[int]:
    lib = load()
    if lib is None:
        return None
    data = text.encode("utf-8", errors="replace")
    return lib.count_tokens(data, len(data))


# -- CPython extension modules ----------------------------------------------

_wire_ext = None
_wire_ext_failed = False


def load_wire_ext():
    """Build (if needed) and import the native wire codec extension
    (native/wire_ext.cpp); None when the toolchain is unavailable. The
    extension is registered with the engine's value classes so it can
    construct Pointers/Json and delegate rare types back to the python
    codec."""
    global _wire_ext, _wire_ext_failed
    if _wire_ext is not None:
        return _wire_ext
    if _wire_ext_failed or os.environ.get("PATHWAY_DISABLE_NATIVE"):
        return None
    try:
        import importlib.machinery
        import importlib.util
        import sysconfig

        source = _source_path("wire_ext.cpp")
        with open(source, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        so_path = os.path.join(_cache_dir(), f"pw_wire_ext_{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".tmp{os.getpid()}"
            subprocess.run(
                [
                    "g++",
                    "-O2",
                    "-shared",
                    "-fPIC",
                    "-std=c++17",
                    f"-I{sysconfig.get_path('include')}",
                    source,
                    "-o",
                    tmp,
                ],
                check=True,
                capture_output=True,
                timeout=180,
            )
            os.replace(tmp, so_path)
        loader = importlib.machinery.ExtensionFileLoader(
            "pw_wire_ext", so_path
        )
        spec = importlib.util.spec_from_loader("pw_wire_ext", loader)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)

        from pathway_tpu.engine import value as _value
        from pathway_tpu.engine import wire as _wire

        def encode_rare(v) -> bytes:
            out = bytearray()
            _wire.encode_value(out, v)
            return bytes(out)

        def decode_rare(tag: int, frame: bytes, offset: int):
            # zero-copy: read straight out of the whole frame at offset
            r = _wire._Reader(frame, offset)
            v = _wire.decode_value(r, _tag=tag)
            return v, r.pos - offset

        mod.register_types(
            _value.Pointer,
            _value.Json,
            _value.ERROR,
            _value.Error,
            _value.Pending,
            encode_rare,
            decode_rare,
            _wire.WireError,
        )
        _wire_ext = mod
        return mod
    except Exception:  # noqa: BLE001 — fall back to the python codec
        _wire_ext_failed = True
        return None
