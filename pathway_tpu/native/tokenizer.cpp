// Native batch tokenizer for the TPU data plane.
//
// TPU-native counterpart of the reference's native text handling (the
// reference tokenizes inside Rust connectors/parsers and relies on HF
// tokenizers for models). Feature-hashing tokenization: lowercase,
// alnum-run splitting, CRC32 token ids — identical semantics to
// models/tokenizer.py HashTokenizer, ~20x faster, writing the padded
// [batch, seq] int32 id/mask buffers the XLA encoder consumes directly.
//
// Built as a shared library at first use (see native/__init__.py); the
// Python implementation stays as the fallback.

#include <cstdint>
#include <cstring>
#include <cctype>

namespace {

constexpr int32_t PAD_ID = 0;
constexpr int32_t CLS_ID = 1;
constexpr int32_t SEP_ID = 2;
constexpr int32_t RESERVED = 4;

// standard CRC-32 (IEEE 802.3), bit-reflected, table-driven — matches
// python's zlib.crc32
struct Crc32Table {
    uint32_t table[256];
    Crc32Table() {
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++) {
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
            }
            table[i] = c;
        }
    }
};

const Crc32Table kCrc;

inline uint32_t crc32_update(uint32_t crc, const unsigned char* buf, size_t len) {
    crc = crc ^ 0xFFFFFFFFu;
    for (size_t i = 0; i < len; i++) {
        crc = kCrc.table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
    }
    return crc ^ 0xFFFFFFFFu;
}

inline bool is_alnum_ascii(unsigned char c) {
    return (c >= '0' && c <= '9') || (c >= 'a' && c <= 'z') ||
           (c >= 'A' && c <= 'Z');
}

}  // namespace

extern "C" {

// Tokenize one text into out_ids[0..max_len); returns number of ids
// written (including CLS/SEP). Splitting: runs of ASCII alnum are words;
// any other non-space byte is a single-char token (UTF-8 multibyte
// sequences group into one token), mirroring HashTokenizer's regex
// `[A-Za-z0-9]+|[^\sA-Za-z0-9]`.
int32_t tokenize_one(const char* text, int32_t text_len, int32_t vocab_size,
                     int32_t max_len, int32_t* out_ids) {
    int32_t n = 0;
    if (max_len <= 0) return 0;
    out_ids[n++] = CLS_ID;
    const unsigned char* s = reinterpret_cast<const unsigned char*>(text);
    int32_t i = 0;
    unsigned char lowered[256];
    while (i < text_len && n < max_len) {
        unsigned char c = s[i];
        if (isspace(c)) {
            i++;
            continue;
        }
        int32_t start = i;
        if (is_alnum_ascii(c)) {
            while (i < text_len && is_alnum_ascii(s[i])) i++;
        } else if (c < 0x80) {
            i++;  // single ascii punct char
        } else {
            // one UTF-8 multibyte sequence = one token
            i++;
            while (i < text_len && (s[i] & 0xC0) == 0x80) i++;
        }
        int32_t len = i - start;
        uint32_t h;
        if (len <= 256) {
            for (int32_t k = 0; k < len; k++) {
                unsigned char ch = s[start + k];
                lowered[k] = (ch >= 'A' && ch <= 'Z') ? ch + 32 : ch;
            }
            h = crc32_update(0, lowered, len);
        } else {
            h = crc32_update(0, s + start, len);
        }
        out_ids[n++] = RESERVED + (int32_t)(h % (uint32_t)(vocab_size - RESERVED));
    }
    if (n < max_len) {
        out_ids[n++] = SEP_ID;
    }
    // on truncation SEP is dropped, matching HashTokenizer.encode's
    // ids[:max_len] semantics
    return n;
}

// Batch API: texts as one concatenated buffer with offsets; fills
// ids[batch, seq_len] and mask[batch, seq_len] (pre-zeroed by caller).
// Returns the longest row length.
int32_t tokenize_batch(const char* buffer, const int64_t* offsets,
                       int32_t n_texts, int32_t vocab_size, int32_t seq_len,
                       int32_t* ids, int32_t* mask) {
    int32_t longest = 0;
    for (int32_t r = 0; r < n_texts; r++) {
        const char* text = buffer + offsets[r];
        int32_t text_len = (int32_t)(offsets[r + 1] - offsets[r]);
        int32_t* row_ids = ids + (int64_t)r * seq_len;
        int32_t n = tokenize_one(text, text_len, vocab_size, seq_len, row_ids);
        int32_t* row_mask = mask + (int64_t)r * seq_len;
        for (int32_t k = 0; k < n; k++) row_mask[k] = 1;
        if (n > longest) longest = n;
    }
    return longest;
}

// Token counting (splitters use it): number of word tokens, no specials.
int32_t count_tokens(const char* text, int32_t text_len) {
    const unsigned char* s = reinterpret_cast<const unsigned char*>(text);
    int32_t i = 0, count = 0;
    while (i < text_len) {
        unsigned char c = s[i];
        if (isspace(c)) {
            i++;
            continue;
        }
        if (is_alnum_ascii(c)) {
            while (i < text_len && is_alnum_ascii(s[i])) i++;
        } else if (c < 0x80) {
            i++;
        } else {
            i++;
            while (i < text_len && (s[i] & 0xC0) == 0x80) i++;
        }
        count++;
    }
    return count;
}

}  // extern "C"
