"""pw.universes — key-set promises (reference:
python/pathway/internals/universes.py)."""

from __future__ import annotations

from pathway_tpu.internals.universe import solver


def promise_are_equal(*tables) -> None:
    for a, b in zip(tables, tables[1:]):
        solver.register_equal(a._universe, b._universe)


def promise_is_subset_of(subset, superset) -> None:
    solver.register_subset(subset._universe, superset._universe)


def promise_are_pairwise_disjoint(*tables) -> None:
    for i, a in enumerate(tables):
        for b in tables[i + 1:]:
            solver.register_disjoint(a._universe, b._universe)
