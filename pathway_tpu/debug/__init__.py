"""pw.debug — static tables, printing, equality assertions.

TPU-native rebuild of the reference debug utilities (reference:
python/pathway/debug/__init__.py: table_from_markdown:446,
table_from_pandas:358, compute_and_print:222,
compute_and_print_update_stream:250, table_to_pandas).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Type

from pathway_tpu.engine.value import Pointer, ref_scalar
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.runner import run_tables
from pathway_tpu.internals.schema import (
    ColumnSchema,
    Schema,
    schema_from_columns,
    schema_from_pandas,
)
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

_SPECIAL_TIME = "__time__"
_SPECIAL_DIFF = "__diff__"


def _parse_value(text: str):
    text = text.strip()
    if text == "" or text == "None":
        return None
    if text == "True":
        return True
    if text == "False":
        return False
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    if len(text) >= 2 and text[0] == text[-1] and text[0] in "\"'":
        return text[1:-1]
    return text


def table_from_markdown(
    table_def: str,
    *,
    id_from: List[str] | None = None,
    unsafe_trusted_ids: bool = False,
    schema: Type[Schema] | None = None,
    _stream: bool = False,
) -> Table:
    """Parse a markdown-ish table (reference: debug/__init__.py:446).

    Special columns: `id` fixes row keys; `__time__`/`__diff__` make the
    table a stream of timed insertions/retractions.

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... owner | pet
    ... Alice | dog
    ... Bob   | cat
    ... ''')
    >>> pw.debug.compute_and_print(t, include_id=False)
    owner | pet
    Alice | dog
    Bob   | cat
    """
    lines = [ln.strip() for ln in table_def.strip().splitlines()]
    lines = [ln for ln in lines if ln and set(ln) - set("|- ")]
    bordered = lines[0].startswith("|")
    header = [h.strip() for h in lines[0].split("|")]
    header = [h for h in header if h]
    rows = []
    for ln in lines[1:]:
        cells = [c for c in ln.split("|")]
        if bordered:
            if ln.startswith("|"):
                cells = cells[1:]
            if ln.endswith("|"):
                cells = cells[:-1]
        values = [_parse_value(c) for c in cells]
        if len(values) != len(header):
            raise ValueError(
                f"row {ln!r} has {len(values)} cells for {len(header)} columns"
            )
        rows.append(dict(zip(header, values)))
    data_cols = [
        h for h in header if h not in ("id", _SPECIAL_TIME, _SPECIAL_DIFF)
    ]
    if schema is not None:
        out_schema = schema
        dtypes = schema.dtypes()
    else:
        # infer dtypes per column from the values
        cols_schema: Dict[str, ColumnSchema] = {}
        for name in data_cols:
            col_dtype: dt.DType | None = None
            for r in rows:
                v = r[name]
                vd = _value_dtype(v)
                col_dtype = vd if col_dtype is None else dt.types_lca(col_dtype, vd)
            cols_schema[name] = ColumnSchema(name=name, dtype=col_dtype or dt.ANY)
        out_schema = schema_from_columns(cols_schema)
        dtypes = out_schema.dtypes()

    events = []
    # without explicit ids, a `-1` line must cancel the key of an earlier
    # identical `+1` line (the connector sinks match retractions the same
    # way, _connector_runtime.push_row)
    keys_by_values: Dict[tuple, list] = {}
    for i, r in enumerate(rows):
        values = tuple(
            dt.coerce_value(r.get(c), dtypes.get(c, dt.ANY)) for c in out_schema.keys()
        )
        time = int(r.get(_SPECIAL_TIME, 0) or 0)
        diff = int(r.get(_SPECIAL_DIFF, 1) or 1)
        if "id" in r:
            key = ref_scalar(r["id"])
        elif id_from:
            key = ref_scalar(*(r[c] for c in id_from))
        elif schema is not None and schema.primary_key_columns():
            key = ref_scalar(*(r[c] for c in schema.primary_key_columns()))
        elif diff < 0 and keys_by_values.get(values):
            key = keys_by_values[values].pop()
        else:
            key = ref_scalar(i)
            keys_by_values.setdefault(values, []).append(key)
        events.append((time, (key, values, diff)))

    return table_from_events(out_schema, events)


def table_from_parquet(path, id_from=None, unsafe_trusted_ids=False):
    """Read a Parquet file into a table via pandas (reference:
    debug/__init__.py table_from_parquet:476)."""
    import pandas as pd

    df = pd.read_parquet(path)
    return table_from_pandas(
        df, id_from=id_from, unsafe_trusted_ids=unsafe_trusted_ids
    )


def table_to_parquet(table, filename):
    """Write a table to a Parquet file via pandas (reference:
    debug/__init__.py table_to_parquet:493)."""
    df = table_to_pandas(table, include_id=False)
    return df.to_parquet(filename)


parse_to_table = table_from_markdown


def table_from_events(schema: Type[Schema], events) -> Table:
    def build(ctx):
        from pathway_tpu.engine.engine import StaticSource, TimedSource

        if all(t == 0 for t, _ in events):
            rows = {}
            for _, (key, values, diff) in events:
                if diff > 0:
                    rows[key] = values
                else:
                    rows.pop(key, None)
            return StaticSource(ctx.engine, rows)
        return TimedSource(ctx.engine, list(events))

    return Table(schema=schema, universe=Universe(), build=build)


def table_from_rows(
    schema: Type[Schema],
    rows: list,
    is_stream: bool = False,
) -> Table:
    """rows: tuples matching schema; with is_stream, tuples end with
    (time, diff) (reference: debug/__init__.py table_from_rows)."""
    names = list(schema.keys())
    pk = schema.primary_key_columns()
    events = []
    for i, row in enumerate(rows):
        if is_stream:
            *vals, time, diff = row
        else:
            vals, time, diff = list(row), 0, 1
        if pk:
            key = ref_scalar(*(vals[names.index(c)] for c in pk))
        else:
            key = ref_scalar(i)
        events.append((time, (key, tuple(vals), diff)))
    return table_from_events(schema, events)


def table_from_pandas(
    df,
    *,
    id_from: List[str] | None = None,
    unsafe_trusted_ids: bool = False,
    schema: Type[Schema] | None = None,
) -> Table:
    if schema is None:
        schema = schema_from_pandas(df, id_from=id_from)
    names = list(schema.keys())
    dtypes = schema.dtypes()
    events = []
    for i, (idx, row) in enumerate(df.iterrows()):
        if id_from:
            key = ref_scalar(*(row[c] for c in id_from))
        else:
            key = ref_scalar(i)
        values = tuple(
            dt.coerce_value(_from_pandas_value(row[c]), dtypes[c]) for c in names
        )
        events.append((0, (key, values, 1)))
    return table_from_events(schema, events)


def _from_pandas_value(v):
    import numpy as np
    import pandas as pd

    if v is pd.NaT:
        return None
    if isinstance(v, float) and v != v:
        return None
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, pd.Timestamp):
        return v.to_pydatetime()
    if isinstance(v, pd.Timedelta):
        return v.to_pytimedelta()
    return v


def _value_dtype(v) -> dt.DType:
    from pathway_tpu.internals.type_interpreter import const_dtype

    return const_dtype(v)


def table_to_pandas(table: Table, *, include_id: bool = True):
    import pandas as pd

    (capture,) = run_tables(table)
    names = table.column_names()
    rows = capture.state.rows
    keys = sorted(rows.keys())
    data = {n: [rows[k][i] for k in keys] for i, n in enumerate(names)}
    if include_id:
        return pd.DataFrame(data, index=[repr(k) for k in keys])
    return pd.DataFrame(data)


def table_to_dicts(table: Table):
    (capture,) = run_tables(table)
    names = table.column_names()
    keys = list(capture.state.rows.keys())
    columns = {
        n: {k: capture.state.rows[k][i] for k in keys}
        for i, n in enumerate(names)
    }
    return keys, columns


def _format_value(v) -> str:
    if isinstance(v, str):
        return v
    return repr(v)


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    terminate_on_error: bool = True,
) -> None:
    """Run the graph and print the table (reference:
    debug/__init__.py:222).

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('''
    ... g | v
    ... a | 1
    ... a | 2
    ... b | 3
    ... ''')
    >>> res = t.groupby(pw.this.g).reduce(
    ...     g=pw.this.g, total=pw.reducers.sum(pw.this.v)
    ... )
    >>> pw.debug.compute_and_print(res, include_id=False)
    g | total
    b | 3
    a | 3
    """
    (capture,) = run_tables(table)
    names = table.column_names()
    items = sorted(capture.state.rows.items(), key=lambda kv: kv[0])
    if n_rows is not None:
        items = items[:n_rows]
    header = (["id"] if include_id else []) + names
    rows_txt = []
    for k, vals in items:
        cells = ([repr(k)] if include_id else []) + [
            _format_value(v) for v in vals
        ]
        rows_txt.append(cells)
    widths = [
        max(len(h), *(len(r[i]) for r in rows_txt)) if rows_txt else len(h)
        for i, h in enumerate(header)
    ]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip())
    for cells in rows_txt:
        print(" | ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip())


def compute_and_print_update_stream(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    **kwargs,
) -> None:
    """Run and print the change stream incl. retractions (reference:
    debug/__init__.py:250)."""
    (capture,) = run_tables(table, record_stream=True)
    names = table.column_names()
    header = (["id"] if include_id else []) + names + ["__time__", "__diff__"]
    rows_txt = []
    stream = capture.stream
    if n_rows is not None:
        stream = stream[:n_rows]
    for time, (key, vals, diff) in stream:
        cells = ([repr(key)] if include_id else []) + [
            _format_value(v) for v in vals
        ] + [str(time), str(diff)]
        rows_txt.append(cells)
    widths = [
        max(len(h), *(len(r[i]) for r in rows_txt)) if rows_txt else len(h)
        for i, h in enumerate(header)
    ]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip())
    for cells in rows_txt:
        print(" | ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip())


def _as_table_list(x):
    if isinstance(x, Table):
        return [x]
    return list(x)


def _runs(actual, expected):
    from pathway_tpu.engine.engine import Engine

    actual_list = _as_table_list(actual)
    expected_list = _as_table_list(expected)
    engine = Engine()
    captures = run_tables(*actual_list, *expected_list, engine=engine)
    n = len(actual_list)
    return actual_list, expected_list, captures[:n], captures[n:]


def assert_table_equality(actual, expected, **kwargs) -> None:
    """Full equality including row ids (reference: tests/utils.py
    assert_table_equality)."""
    actual_list, expected_list, a_caps, e_caps = _runs(actual, expected)
    for at, et, ac, ec in zip(actual_list, expected_list, a_caps, e_caps):
        a_rows = {k: _norm_row(v) for k, v in ac.state.rows.items()}
        e_rows = {k: _norm_row(v) for k, v in ec.state.rows.items()}
        assert set(at.column_names()) == set(et.column_names()), (
            f"column sets differ: {at.column_names()} vs {et.column_names()}"
        )
        assert a_rows == e_rows, _diff_message(a_rows, e_rows)


def assert_table_equality_wo_index(actual, expected, **kwargs) -> None:
    actual_list, expected_list, a_caps, e_caps = _runs(actual, expected)
    for at, et, ac, ec in zip(actual_list, expected_list, a_caps, e_caps):
        assert set(at.column_names()) == set(et.column_names()), (
            f"column sets differ: {at.column_names()} vs {et.column_names()}"
        )
        # align column order by expected's names
        a_order = [at.column_names().index(c) for c in et.column_names()]
        a_multi = Counter(
            tuple(_norm_row(v)[i] for i in a_order) for v in ac.state.rows.values()
        )
        e_multi = Counter(_norm_row(v) for v in ec.state.rows.values())
        assert a_multi == e_multi, _diff_message(a_multi, e_multi)


def assert_stream_equality(actual, expected_stream) -> None:
    """Determinism check on the UPDATE STREAM, not just final state
    (reference: tests/utils.py assert_key_entries_in_stream_consistent /
    DiffEntry — batch-boundary consistency is the differential-dataflow
    guarantee the engine must keep).

    `expected_stream` entries: (time, values_tuple, diff), or
    (time, key, values_tuple, diff) to also pin row keys (key determinism).
    Comparison is per-time multisets so within-batch ordering stays free."""
    from collections import defaultdict

    from pathway_tpu.internals.runner import run_tables

    expected_stream = list(expected_stream)
    with_keys = any(len(e) == 4 for e in expected_stream)
    (cap,) = run_tables(actual, record_stream=True)
    got: dict = defaultdict(Counter)
    for time, (key, values, diff) in cap.stream:
        entry = (key, _norm_row(values), diff) if with_keys else (
            _norm_row(values), diff
        )
        got[time][entry] += 1
    want: dict = defaultdict(Counter)
    for e in expected_stream:
        if with_keys:
            time, key, values, diff = e
            want[time][(key, _norm_row(tuple(values)), diff)] += 1
        else:
            time, values, diff = e
            want[time][(_norm_row(tuple(values)), diff)] += 1
    assert dict(got) == dict(want), _diff_message(dict(got), dict(want))


def assert_stream_equality_wo_index(actual, expected_stream) -> None:
    """Values-only variant (keys ignored even if provided)."""
    assert_stream_equality(
        actual,
        [
            (e[0], e[-2], e[-1])
            for e in expected_stream
        ],
    )


def assert_table_equality_wo_types(actual, expected, **kwargs) -> None:
    assert_table_equality(actual, expected)


def assert_table_equality_wo_index_types(actual, expected, **kwargs) -> None:
    assert_table_equality_wo_index(actual, expected)


def _norm_row(v: tuple) -> tuple:
    return tuple(_norm_value(x) for x in v)


def _norm_value(x):
    import numpy as np

    if isinstance(x, float) and x.is_integer():
        return x  # keep floats as floats
    if isinstance(x, np.ndarray):
        return (x.shape, tuple(x.flatten().tolist()))
    if isinstance(x, tuple):
        return tuple(_norm_value(i) for i in x)
    return x


def _diff_message(a, e) -> str:
    return f"tables differ:\n  actual:   {_show(a)}\n  expected: {_show(e)}"


def _show(rows) -> str:
    if isinstance(rows, Counter):
        return repr(sorted(rows.items(), key=repr))
    return repr(sorted(rows.items(), key=repr))


class StreamGenerator:
    """Per-worker timed batches for streaming tests (reference:
    debug/__init__.py StreamGenerator:508)."""

    def __init__(self):
        self._events: Dict[int, list] = {}
        self._counter = 0

    def table_from_list_of_batches_by_workers(
        self, batches: List[Dict[int, List[dict]]], schema: Type[Schema]
    ) -> Table:
        names = list(schema.keys())
        events = []
        time = 2
        for batch in batches:
            for _worker, rows in batch.items():
                for row in rows:
                    self._counter += 1
                    key = ref_scalar(self._counter)
                    events.append(
                        (time, (key, tuple(row[c] for c in names), 1))
                    )
            time += 2
        return table_from_events(schema, events)

    def table_from_list_of_batches(
        self, batches: List[List[dict]], schema: Type[Schema]
    ) -> Table:
        return self.table_from_list_of_batches_by_workers(
            [{0: batch} for batch in batches], schema
        )
