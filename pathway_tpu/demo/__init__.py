"""pw.demo — synthetic demo streams (reference:
python/pathway/demo/__init__.py: generate_custom_stream:28,
noisy_linear_stream:117, range_stream:164, replay_csv:211,
replay_csv_with_time:256)."""

from __future__ import annotations

import csv as csv_mod
import random
import time as time_mod
from typing import Any, Callable, Dict, Optional, Type

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import (
    ColumnSchema,
    Schema,
    schema_from_columns,
    schema_from_types,
)
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class _GeneratorSubject(ConnectorSubjectBase):
    def __init__(self, value_generators, nb_rows, input_rate, autocommit_ms):
        super().__init__()
        self.value_generators = value_generators
        self.nb_rows = nb_rows
        self.input_rate = input_rate

    def run(self) -> None:
        i = 0
        while self.nb_rows is None or i < self.nb_rows:
            row = {
                name: gen(i) for name, gen in self.value_generators.items()
            }
            self.next(**row)
            self.commit()
            i += 1
            if self.input_rate:
                time_mod.sleep(1.0 / self.input_rate)


def generate_custom_stream(
    value_generators: Dict[str, Callable[[int], Any]],
    *,
    schema: Type[Schema],
    nb_rows: int | None = None,
    autocommit_duration_ms: int = 1000,
    input_rate: float = 1.0,
    persistent_id: str | None = None,
    name: str | None = None,
):
    """reference: demo/__init__.py generate_custom_stream:28."""
    return connector_table(
        schema,
        lambda: _GeneratorSubject(
            value_generators, nb_rows, input_rate, autocommit_duration_ms
        ),
        mode="streaming",
        name=name,
    )


def noisy_linear_stream(nb_rows: int = 10, input_rate: float = 1.0, **kwargs):
    """y ≈ x with noise (reference: demo/__init__.py:117)."""
    rng = random.Random(0)
    schema = schema_from_types(x=float, y=float)
    return generate_custom_stream(
        {
            "x": lambda i: float(i),
            "y": lambda i: float(i) + rng.uniform(-1, 1),
        },
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
    )


def range_stream(
    nb_rows: int = 30, offset: int = 0, input_rate: float = 1.0, **kwargs
):
    """values offset..offset+nb_rows (reference: demo/__init__.py:164)."""
    schema = schema_from_types(value=float)
    return generate_custom_stream(
        {"value": lambda i: float(i + offset)},
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
    )


class _CsvReplaySubject(ConnectorSubjectBase):
    def __init__(self, path, schema, input_rate, time_column, unit, speedup=1.0):
        super().__init__()
        self.path = path
        self.schema = schema
        self.input_rate = input_rate
        self.time_column = time_column
        self.unit = unit
        self.speedup = speedup or 1.0

    def run(self) -> None:
        div = {"s": 1.0, "ms": 1e3, "us": 1e6, "ns": 1e9}.get(self.unit, 1.0)
        dtypes = self.schema.dtypes()
        prev_t = None
        with open(self.path, newline="") as fh:
            for rec in csv_mod.DictReader(fh):
                row = {}
                for name, dtype in dtypes.items():
                    raw = rec.get(name)
                    core = dt.unoptionalize(dtype)
                    if raw is None:
                        row[name] = None
                    elif core is dt.INT:
                        row[name] = int(raw)
                    elif core is dt.FLOAT:
                        row[name] = float(raw)
                    elif core is dt.BOOL:
                        row[name] = raw.lower() in ("true", "1")
                    else:
                        row[name] = raw
                if self.time_column is not None:
                    t = float(rec[self.time_column]) / div
                    if prev_t is not None and t > prev_t:
                        time_mod.sleep(min((t - prev_t) / self.speedup, 5.0))
                    prev_t = t
                elif self.input_rate:
                    time_mod.sleep(1.0 / self.input_rate)
                self.next(**row)
                self.commit()


def replay_csv(path: str, *, schema: Type[Schema], input_rate: float = 1.0):
    """reference: demo/__init__.py replay_csv:211."""
    return connector_table(
        schema,
        lambda: _CsvReplaySubject(path, schema, input_rate, None, "s"),
        mode="streaming",
    )


def replay_csv_with_time(
    path: str,
    *,
    schema: Type[Schema],
    time_column: str,
    unit: str = "s",
    autocommit_ms: int = 100,
    speedup: float = 1,
):
    """reference: demo/__init__.py replay_csv_with_time:256."""
    return connector_table(
        schema,
        lambda: _CsvReplaySubject(
            path, schema, None, time_column, unit, speedup=speedup
        ),
        mode="streaming",
    )
