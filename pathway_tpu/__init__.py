"""pathway_tpu — a TPU-native incremental stream-processing framework.

A ground-up rebuild of the capabilities of Pathway (reference mounted at
/root/reference) designed for TPU hardware: the dataflow control plane runs on
host CPU; the numeric data plane (embedding, KNN retrieval, reranking,
generation) is jit-compiled JAX sharded over a `jax.sharding.Mesh`.

Usage mirrors the reference::

    import pathway_tpu as pw

    class InputSchema(pw.Schema):
        value: int

    t = pw.debug.table_from_markdown('''
    value
    1
    2
    ''')
    result = t.select(doubled=pw.this.value * 2)
    pw.debug.compute_and_print(result)
"""

from __future__ import annotations

import datetime as _datetime

# -- core DSL ---------------------------------------------------------------
from pathway_tpu.internals import dtype as _dt
from pathway_tpu.internals.api import (
    apply,
    apply_async,
    apply_fully_async,
    apply_with_type,
    assert_table_has_schema,
    cast,
    coalesce,
    declare_type,
    fill_error,
    if_else,
    iterate,
    make_tuple,
    require,
    table_transformer,
    unwrap,
)
from pathway_tpu.internals.config import (
    pathway_config,
    set_license_key,
    set_monitoring_config,
)
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
)
from pathway_tpu.internals.joins import (
    GroupedJoinResult,
    JoinMode,
    JoinResult,
    OuterJoinResult,
    join,
    join_inner,
    join_left,
    join_outer,
    join_right,
)
from pathway_tpu.internals.joins import groupby as groupby  # noqa: PLC0414
from pathway_tpu.internals.groupbys import GroupedTable
from pathway_tpu.internals.api import (
    PathwayType as Type,
    PersistenceMode,
)
from pathway_tpu.internals.schema import SchemaProperties
from pathway_tpu.internals.iterate import iterate_universe
from pathway_tpu.internals.parse_graph import G as parse_graph_G
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.reducers import BaseCustomAccumulator, reducers
from pathway_tpu.internals.runner import run, run_all
from pathway_tpu.internals.schema import (
    ColumnDefinition,
    Schema,
    column_definition,
    schema_builder,
    schema_from_csv,
    schema_from_dict,
    schema_from_pandas,
    schema_from_types,
)
from pathway_tpu.internals.async_transformer import AsyncTransformer
from pathway_tpu.internals.row_transformer import (
    ClassArg,
    attribute,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)
from pathway_tpu.internals.table import Table, TableSlice
from pathway_tpu.internals.thisclass import left, right, this
from pathway_tpu.engine.value import (
    Json,
    Pointer,
    PyObjectWrapper,
    ref_scalar,
    wrap_py_object,
)

# -- type aliases (reference: pw.DateTimeNaive etc.) ------------------------
DateTimeNaive = _datetime.datetime
DateTimeUtc = _datetime.datetime
Duration = _datetime.timedelta
Date = _datetime.date

DATE_TIME_NAIVE = _dt.DATE_TIME_NAIVE
DATE_TIME_UTC = _dt.DATE_TIME_UTC
DURATION = _dt.DURATION


# -- subpackages ------------------------------------------------------------
from pathway_tpu import debug  # noqa: E402
from pathway_tpu import io  # noqa: E402
from pathway_tpu import stdlib  # noqa: E402
from pathway_tpu import universes  # noqa: E402
from pathway_tpu.internals import udfs  # noqa: E402
from pathway_tpu.internals.udfs import UDF, udf  # noqa: E402
from pathway_tpu.stdlib import indexing, ml, ordered, stateful, statistical  # noqa: E402
from pathway_tpu.stdlib import temporal  # noqa: E402
from pathway_tpu.stdlib import utils  # noqa: E402
from pathway_tpu.stdlib import viz  # noqa: E402
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer  # noqa: E402
from pathway_tpu.stdlib.temporal import (  # noqa: E402
    intervals_over,
    session,
    sliding,
    tumbling,
)

# graft frequently-used stdlib entry points onto the pw namespace, as the
# reference does (reference: python/pathway/__init__.py:155-176)
windowby = temporal.windowby

# Table.diff (reference grafts it the same way: pathway/__init__.py:207)
from pathway_tpu.stdlib import ordered as _ordered  # noqa: E402

Table.diff = _ordered.diff

# graft the temporal join/window surface onto Table, exactly as the
# reference does (reference: python/pathway/__init__.py:184-214)
Table.asof_join = temporal.asof_join
Table.asof_join_left = temporal.asof_join_left
Table.asof_join_right = temporal.asof_join_right
Table.asof_join_outer = temporal.asof_join_outer

Table.asof_now_join = temporal.asof_now_join
Table.asof_now_join_inner = temporal.asof_now_join_inner
Table.asof_now_join_left = temporal.asof_now_join_left

Table.window_join = temporal.window_join
Table.window_join_inner = temporal.window_join_inner
Table.window_join_left = temporal.window_join_left
Table.window_join_right = temporal.window_join_right
Table.window_join_outer = temporal.window_join_outer

Table.interval_join = temporal.interval_join
Table.interval_join_inner = temporal.interval_join_inner
Table.interval_join_left = temporal.interval_join_left
Table.interval_join_right = temporal.interval_join_right
Table.interval_join_outer = temporal.interval_join_outer

Table.windowby = temporal.windowby
Table.interpolate = statistical.interpolate
Table.inactivity_detection = temporal.inactivity_detection

# type exports (reference: pathway/__init__.py __all__ — Joinable/
# TableLike are base classes there; independent classes here, so the
# names are virtual base classes preserving isinstance semantics)
import abc as _abc  # noqa: E402


class Joinable(metaclass=_abc.ABCMeta):
    """Anything join()-able: Table or JoinResult (reference: joins.py
    Joinable:46 — a real base class there, a virtual one here)."""


class TableLike(metaclass=_abc.ABCMeta):
    """reference: table_like.py TableLike."""


Joinable.register(Table)
Joinable.register(JoinResult)
TableLike.register(Table)

# the reference lists these in __all__ without binding them (stale
# entries); bind them to their historical meanings so both names resolve
asynchronous = udfs  # the pre-rename name of the udfs module
window = temporal  # window types live in the temporal namespace
from pathway_tpu.stdlib.temporal import (  # noqa: E402
    AsofJoinResult,
    IntervalJoinResult,
    WindowJoinResult,
)


def __getattr__(name):
    if name == "xpacks":
        import pathway_tpu.xpacks as xp

        return xp
    if name == "persistence":
        import pathway_tpu.persistence as p

        return p
    if name == "demo":
        import pathway_tpu.demo as d

        return d
    if name == "sql":
        from pathway_tpu.internals.sql import sql as s

        return s
    if name == "graphs":
        from pathway_tpu.stdlib import graphs as g

        return g
    if name in ("enable_interactive_mode", "LiveTable"):
        from pathway_tpu.internals import interactive

        return getattr(interactive, name)
    if name == "MonitoringLevel":
        from pathway_tpu.internals.monitoring import MonitoringLevel as m

        return m
    if name == "load_yaml":
        # lazy: keeps PyYAML an optional dependency
        from pathway_tpu.internals.yaml_loader import load_yaml as ly

        return ly
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def global_error_log() -> Table:
    """Error log as a queryable table (reference: pw.global_error_log,
    Graph::error_log graph.rs:932)."""
    from pathway_tpu.internals.error_log import global_error_log as _gel

    return _gel()


local_error_log = global_error_log


class udf_async:  # legacy alias (reference had pw.udf_async)
    def __new__(cls, *args, **kwargs):
        from pathway_tpu.internals.udfs import udf

        return udf(*args, executor="async", **kwargs)


Json = Json
Error = None  # populated below to avoid import cycle at module top

from pathway_tpu.engine.value import ERROR as _ERROR_VALUE  # noqa: E402

Error = _ERROR_VALUE

__version__ = "0.1.0"

__all__ = [
    "Table",
    "Schema",
    "Json",
    "Pointer",
    "PyObjectWrapper",
    "wrap_py_object",
    "this",
    "left",
    "right",
    "apply",
    "apply_with_type",
    "apply_async",
    "apply_fully_async",
    "cast",
    "declare_type",
    "if_else",
    "coalesce",
    "require",
    "unwrap",
    "fill_error",
    "make_tuple",
    "iterate",
    "udf",
    "UDF",
    "reducers",
    "run",
    "run_all",
    "debug",
    "io",
    "indexing",
    "temporal",
    "windowby",
    "session",
    "sliding",
    "tumbling",
    "intervals_over",
    "column_definition",
    "schema_from_types",
    "schema_builder",
    "universes",
]
