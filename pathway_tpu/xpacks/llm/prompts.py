"""Prompt library (reference: python/pathway/xpacks/llm/prompts.py)."""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from typing import Any, List, Tuple

from pathway_tpu.engine.value import Json
from pathway_tpu.internals.api import apply_with_type


@dataclass
class BasePromptTemplate:
    """reference: prompts.py template classes :12-104."""

    template: str = ""

    def format(self, **kwargs) -> str:
        return self.template.format(**kwargs)


@dataclass
class RAGPromptTemplate(BasePromptTemplate):
    template: str = (
        "Please answer the question using only the provided context.\n"
        "If the answer is not in the context, reply exactly: No information found.\n"
        "Context: {context}\nQuestion: {query}\nAnswer:"
    )


@dataclass
class RAGFunctionPromptTemplate(BasePromptTemplate):
    pass


def _docs_to_context(docs: Any) -> str:
    if isinstance(docs, Json):
        docs = docs.value
    parts: List[str] = []
    for doc in docs or ():
        if isinstance(doc, Json):
            doc = doc.value
        if isinstance(doc, dict):
            parts.append(str(doc.get("text", doc)))
        else:
            parts.append(str(doc))
    return "\n\n".join(parts)


def prompt_qa(
    query,
    docs,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
):
    """reference: prompts.py prompt_qa:173."""

    def build(q: str, d) -> str:
        context = _docs_to_context(d)
        return (
            "Please provide an answer based solely on the provided sources. "
            "When referencing information from a source, cite it. "
            f"If none of the sources are helpful, respond with "
            f"{information_not_found_response!r}.{additional_rules}\n"
            f"Context: {context}\nQuestion: {q}\nAnswer:"
        )

    return apply_with_type(build, str, query, docs)


def prompt_short_qa(
    query, docs, additional_rules: str = ""
):
    """reference: prompts.py prompt_short_qa:133."""

    def build(q: str, d) -> str:
        context = _docs_to_context(d)
        return (
            "Answer the question concisely (a few words) based on the "
            f"context.{additional_rules}\n"
            f"Context: {context}\nQuestion: {q}\nAnswer:"
        )

    return apply_with_type(build, str, query, docs)


def prompt_qa_geometric_rag(
    query,
    docs,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
):
    """reference: prompts.py prompt_qa_geometric_rag:223 (adaptive RAG)."""
    return prompt_qa(
        query,
        docs,
        information_not_found_response=information_not_found_response,
        additional_rules=additional_rules,
    )


def prompt_summarize(text_list):
    """reference: prompts.py prompt_summarize."""

    def build(texts) -> str:
        if isinstance(texts, Json):
            texts = texts.value
        joined = "\n".join(str(t) for t in (texts or ()))
        return f"Summarize the following texts:\n{joined}\nSummary:"

    return apply_with_type(build, str, text_list)


def prompt_rerank(query, doc):
    """reference: prompts.py prompt_rerank:256."""

    def build(q: str, d: str) -> str:
        return (
            'Rate relevance 1-5. Respond as JSON: {"score": <n>}\n'
            f"Query: {q}\nDocument: {d}"
        )

    return apply_with_type(build, str, query, doc)


def parse_score_json(response: str) -> float:
    """reference: prompts.py parse_score_json:307."""
    match = re.search(r"\{[^}]*\}", response or "")
    if match:
        try:
            return float(json.loads(match.group(0)).get("score", 1.0))
        except (json.JSONDecodeError, TypeError, ValueError):
            pass
    digits = re.search(r"[1-5]", response or "")
    return float(digits.group(0)) if digits else 1.0


def prompt_citing_qa(query, docs, additional_rules: str = ""):
    """reference: prompts.py prompt_citing_qa:324."""

    def build(q: str, d) -> str:
        if isinstance(d, Json):
            d = d.value
        numbered = []
        for i, doc in enumerate(d or ()):
            if isinstance(doc, Json):
                doc = doc.value
            text = doc.get("text", doc) if isinstance(doc, dict) else doc
            numbered.append(f"[{i}] {text}")
        context = "\n".join(numbered)
        return (
            "Answer using the sources; cite them as [number].\n"
            f"{additional_rules}\nSources:\n{context}\n"
            f"Question: {q}\nAnswer:"
        )

    return apply_with_type(build, str, query, docs)


def parse_cited_response(response: str, docs: list) -> Tuple[str, list]:
    """reference: prompts.py parse_cited_response:372."""
    cited = [int(m) for m in re.findall(r"\[(\d+)\]", response or "")]
    cited_docs = [docs[i] for i in cited if 0 <= i < len(docs)]
    answer = re.sub(r"\s*\[\d+\]", "", response or "").strip()
    return answer, cited_docs
