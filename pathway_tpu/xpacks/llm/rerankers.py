"""Rerankers (reference: python/pathway/xpacks/llm/rerankers.py).

`CrossEncoderReranker` scores the whole candidate batch in one MXU pass —
the reference scores ONE (query, doc) pair per call (rerankers.py:209-213),
which SURVEY.md flags as the big TPU win here."""

from __future__ import annotations

import json
from typing import Any, List, Optional, Tuple

from pathway_tpu.engine.value import Json
from pathway_tpu.internals.api import apply_with_type
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.udfs import UDF, async_executor


def rerank_topk_filter(
    docs, scores, k: int = 5
) -> ColumnExpression:
    """Keep the k best docs by score (reference: rerankers.py
    rerank_topk_filter:17). Returns (docs_tuple, scores_tuple)."""

    def topk(docs_v, scores_v):
        ranked = sorted(
            zip(docs_v, scores_v), key=lambda p: p[1], reverse=True
        )[:k]
        if not ranked:
            return ((), ())
        kept_docs, kept_scores = zip(*ranked)
        return (tuple(kept_docs), tuple(kept_scores))

    return apply_with_type(topk, tuple, docs, scores)


class LLMReranker(UDF):
    """Score relevance 1-5 by prompting an LLM (reference: rerankers.py
    LLMReranker:60)."""

    def __init__(
        self,
        llm,
        *,
        retry_strategy=None,
        cache_strategy=None,
        use_logit_bias: bool | None = None,
    ):
        super().__init__(
            return_type=float,
            executor=async_executor(retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.llm = llm

        async def rerank(doc: str, query: str, **kwargs) -> float:
            prompt = (
                "Rate the relevance of the document to the query on a "
                "scale from 1 to 5. Answer with a single number only.\n"
                f"Query: {query}\nDocument: {doc}"
            )
            response = self.llm.func(
                [{"role": "user", "content": prompt}]
            )
            import inspect

            if inspect.isawaitable(response):
                response = await response
            if isinstance(response, list):
                response = response[0]
            try:
                return float(str(response).strip().split()[0])
            except (ValueError, IndexError):
                return 1.0

        self.func = rerank

    def __call__(self, doc, query, **kwargs) -> ColumnExpression:
        return super().__call__(doc, query, **kwargs)


class CrossEncoderReranker(UDF):
    """Cross-encoder scoring on TPU, batched (reference: rerankers.py
    CrossEncoderReranker:163 — one pair per call there; full-batch MXU pass
    here)."""

    def __init__(
        self,
        model_name: str = "cross-encoder/ms-marco-MiniLM-L-6-v2",
        *,
        cache_strategy=None,
        max_batch_size: int = 256,
        **init_kwargs,
    ):
        super().__init__(
            return_type=float,
            deterministic=True,
            cache_strategy=cache_strategy,
            max_batch_size=max_batch_size,
        )
        from pathway_tpu.models.cross_encoder import CrossEncoderModel

        self.model = CrossEncoderModel.cached(model_name)

        def score_batch(docs: List[str], queries: List[str]) -> List[float]:
            scores = self.model.score(list(zip(queries, docs)))
            return [float(s) for s in scores]

        self.func = score_batch

    def __call__(self, doc, query, **kwargs) -> ColumnExpression:
        return super().__call__(doc, query, **kwargs)


class EncoderReranker(UDF):
    """Bi-encoder dot-product reranker (reference: rerankers.py
    EncoderReranker:228)."""

    def __init__(
        self,
        model_name: str = "all-MiniLM-L6-v2",
        *,
        cache_strategy=None,
        max_batch_size: int = 512,
        **init_kwargs,
    ):
        super().__init__(
            return_type=float,
            deterministic=True,
            cache_strategy=cache_strategy,
            max_batch_size=max_batch_size,
        )
        from pathway_tpu.models.minilm import SentenceEncoder

        self.encoder = SentenceEncoder.cached(model_name)

        def score_batch(docs: List[str], queries: List[str]) -> List[float]:
            import numpy as np

            doc_vecs = self.encoder.encode(docs)
            query_vecs = self.encoder.encode(queries)
            return [float(np.dot(d, q)) for d, q in zip(doc_vecs, query_vecs)]

        self.func = score_batch

    def __call__(self, doc, query, **kwargs) -> ColumnExpression:
        return super().__call__(doc, query, **kwargs)


class FlashRankReranker(UDF):
    """reference: rerankers.py FlashRankReranker:296 — requires flashrank."""

    def __init__(self, model_name: str = "ms-marco-TinyBERT-L-2-v2", **kwargs):
        super().__init__(return_type=float, deterministic=True)

        def score(doc: str, query: str) -> float:
            raise ImportError(
                "FlashRankReranker requires the flashrank package"
            )

        self.func = score

    def __call__(self, doc, query, **kwargs) -> ColumnExpression:
        return super().__call__(doc, query, **kwargs)
