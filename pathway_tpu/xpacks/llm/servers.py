"""REST servers for document stores and RAG apps (reference:
python/pathway/xpacks/llm/servers.py BaseRestServer:16,
DocumentStoreServer:92, QARestServer:140, QASummaryRestServer:207)."""

from __future__ import annotations

import threading
from typing import Callable, Optional, Type

from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.http import PathwayWebserver, rest_connector


class BaseRestServer:
    """reference: servers.py BaseRestServer:16."""

    def __init__(self, host: str, port: int, with_cors: bool = False, **kwargs):
        self.host = host
        self.port = port
        self.webserver = PathwayWebserver(host, port, with_cors=with_cors)

    def serve(
        self,
        route: str,
        schema: Type[Schema],
        handler: Callable,
        *,
        methods=("POST",),
        documentation=None,
        **kwargs,
    ) -> None:
        """Register a route: requests become a table, `handler(table)`
        returns the result table whose `result` column is the response."""
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            schema=schema,
            methods=methods,
            documentation=documentation,
            delete_completed_queries=True,
        )
        writer(handler(queries))

    def run(
        self,
        *,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend=None,
        terminate_on_error: bool = True,
        **kwargs,
    ):
        """reference: servers.py run — pw.run under the hood."""
        from pathway_tpu.internals.runner import run as pw_run

        if threaded:
            t = threading.Thread(target=pw_run, daemon=True, name="pw-server")
            t.start()
            return t
        pw_run()
        return None


class DocumentStoreServer(BaseRestServer):
    """reference: servers.py DocumentStoreServer:92."""

    def __init__(self, host: str, port: int, document_store, **kwargs):
        super().__init__(host, port, **kwargs)
        self.document_store = document_store
        ds = document_store
        self.serve(
            "/v1/retrieve", ds.RetrieveQuerySchema, ds.retrieve_query
        )
        self.serve(
            "/v1/statistics", ds.StatisticsQuerySchema, ds.statistics_query
        )
        self.serve("/v1/inputs", ds.InputsQuerySchema, ds.inputs_query)


class QARestServer(BaseRestServer):
    """reference: servers.py QARestServer:140."""

    def __init__(self, host: str, port: int, rag_question_answerer, **kwargs):
        super().__init__(host, port, **kwargs)
        self.rag = rag_question_answerer
        rag = rag_question_answerer
        self.serve(
            "/v1/pw_ai_answer", rag.AnswerQuerySchema, rag.answer_query
        )
        self.serve(
            "/v2/answer", rag.AnswerQuerySchema, rag.answer_query
        )
        self.serve(
            "/v1/retrieve",
            rag.indexer.RetrieveQuerySchema,
            rag.indexer.retrieve_query,
        )
        self.serve(
            "/v2/list_documents",
            rag.indexer.InputsQuerySchema,
            rag.indexer.inputs_query,
        )
        self.serve(
            "/v1/statistics",
            rag.indexer.StatisticsQuerySchema,
            rag.indexer.statistics_query,
        )


class QASummaryRestServer(QARestServer):
    """reference: servers.py QASummaryRestServer:207."""

    def __init__(self, host: str, port: int, rag_question_answerer, **kwargs):
        super().__init__(host, port, rag_question_answerer, **kwargs)
        rag = rag_question_answerer
        self.serve(
            "/v1/pw_ai_summary", rag.SummarizeQuerySchema, rag.summarize_query
        )
        self.serve(
            "/v2/summarize", rag.SummarizeQuerySchema, rag.summarize_query
        )
