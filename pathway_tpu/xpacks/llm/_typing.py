"""Shared typing aliases for the LLM xpack (reference:
xpacks/llm/_typing.py)."""

from __future__ import annotations

from typing import Callable, Iterable, TypeAlias, Union

from pathway_tpu.internals.udfs import UDF

Doc: TypeAlias = "dict[str, str | dict]"

DocTransformerCallable: TypeAlias = Union[
    Callable[[Iterable["Doc"]], Iterable["Doc"]],
    Callable[[Iterable["Doc"], float], Iterable["Doc"]],
]

DocTransformer: TypeAlias = Union[UDF, DocTransformerCallable]
