"""Text splitters (reference: python/pathway/xpacks/llm/splitters.py).

Splitters are UDFs returning `list[tuple[str, dict]]` — (chunk, metadata)
pairs, exactly the reference contract (splitters.py BaseSplitter:21)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from pathway_tpu.engine.value import Json
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.udfs import UDF

_SEPARATORS = ["\n\n", "\n", ". ", " ", ""]


def _meta(value: Any) -> dict:
    if isinstance(value, Json):
        value = value.value
    return dict(value or {})


class BaseSplitter(UDF):
    """reference: splitters.py BaseSplitter:21."""

    def __init__(self, **kwargs):
        super().__init__(return_type=list, deterministic=True, **kwargs)

    def __call__(self, text, metadata=None, **kwargs) -> ColumnExpression:
        if metadata is None:
            metadata = Json({})
        return super().__call__(text, metadata, **kwargs)


class NullSplitter(BaseSplitter):
    """reference: splitters.py NullSplitter:161."""

    def __init__(self):
        super().__init__(max_batch_size=65536)

        def split(texts: list, metadatas: list) -> list:
            return [
                [(text, _meta(metadata))]
                for text, metadata in zip(texts, metadatas)
            ]

        self.func = split


class TokenCountSplitter(BaseSplitter):
    """Split into chunks of min..max tokens (reference: splitters.py
    TokenCountSplitter:177 — tiktoken there, the in-tree tokenizer here)."""

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
    ):
        super().__init__()
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        from pathway_tpu.models.tokenizer import HashTokenizer

        tokenizer = HashTokenizer()

        def split(text: str, metadata) -> list:
            meta = _meta(metadata)
            words = text.split()
            if not words:
                return []
            chunks: List[Tuple[str, dict]] = []
            current: List[str] = []
            count = 0
            for word in words:
                n = max(1, tokenizer.count_tokens(word))
                if count + n > self.max_tokens and count >= self.min_tokens:
                    chunks.append((" ".join(current), dict(meta)))
                    current, count = [], 0
                current.append(word)
                count += n
            if current:
                if chunks and count < self.min_tokens:
                    last_text, last_meta = chunks[-1]
                    chunks[-1] = (last_text + " " + " ".join(current), last_meta)
                else:
                    chunks.append((" ".join(current), dict(meta)))
            return chunks

        self.func = split


class RecursiveSplitter(BaseSplitter):
    """Character/token recursive splitting with overlap (reference:
    splitters.py RecursiveSplitter:88 — langchain there; a self-contained
    recursive splitter here)."""

    def __init__(
        self,
        chunk_size: int = 500,
        chunk_overlap: int = 0,
        separators: List[str] | None = None,
        encoding_name: str | None = None,
        model_name: str | None = None,
    ):
        super().__init__()
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.separators = separators or _SEPARATORS

        def split_recursive(text: str, separators: List[str]) -> List[str]:
            if len(text) <= self.chunk_size:
                return [text] if text.strip() else []
            if not separators:
                return [
                    text[i : i + self.chunk_size]
                    for i in range(0, len(text), self.chunk_size)
                ]
            sep, rest = separators[0], separators[1:]
            if sep == "":
                return [
                    text[i : i + self.chunk_size]
                    for i in range(0, len(text), self.chunk_size)
                ]
            parts = text.split(sep)
            chunks: List[str] = []
            current = ""
            for part in parts:
                candidate = current + sep + part if current else part
                if len(candidate) <= self.chunk_size:
                    current = candidate
                else:
                    if current.strip():
                        chunks.append(current)
                    if len(part) > self.chunk_size:
                        chunks.extend(split_recursive(part, rest))
                        current = ""
                    else:
                        current = part
            if current.strip():
                chunks.append(current)
            return chunks

        def split(text: str, metadata) -> list:
            meta = _meta(metadata)
            chunks = split_recursive(text, self.separators)
            # overlap applies ONCE over the final chunk list (inside the
            # recursion it compounds tails across levels)
            if self.chunk_overlap > 0 and len(chunks) > 1:
                chunks = [chunks[0]] + [
                    prev[-self.chunk_overlap :] + cur
                    for prev, cur in zip(chunks, chunks[1:])
                ]
            return [(chunk, dict(meta)) for chunk in chunks]

        self.func = split
