"""Chat wrappers (reference: python/pathway/xpacks/llm/llms.py).

`HFPipelineChat` is the local-generation path: on TPU it runs the JAX
decoder (reference: llms.py:456 — torch transformers pipeline, batch 32).
API chats (OpenAI/LiteLLM/Cohere) are async UDFs with retry/cache.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional

from pathway_tpu.engine.value import Json
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import apply_with_type
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.udfs import UDF, async_executor


def _messages_to_prompt(messages: Any) -> str:
    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, str):
        return messages
    if isinstance(messages, (list, tuple)):
        parts = []
        for m in messages:
            if isinstance(m, Json):
                m = m.value
            if isinstance(m, dict):
                parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}")
            else:
                parts.append(str(m))
        return "\n".join(parts)
    return str(messages)


class BaseChat(UDF):
    """reference: llms.py BaseChat:43."""

    model: str | None = None

    def get_model_name(self) -> str | None:
        return self.model

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return True

    def __call__(self, messages, **kwargs) -> ColumnExpression:
        return super().__call__(messages, **kwargs)


class OpenAIChat(BaseChat):
    """reference: llms.py OpenAIChat:95."""

    def __init__(
        self,
        model: str | None = "gpt-4o-mini",
        *,
        capacity: int | None = None,
        retry_strategy=None,
        cache_strategy=None,
        api_key: str | None = None,
        base_url: str | None = None,
        **openai_kwargs,
    ):
        super().__init__(
            return_type=Optional[str],
            executor=async_executor(
                capacity=capacity, retry_strategy=retry_strategy
            ),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.api_key = api_key
        self.base_url = base_url or "https://api.openai.com/v1"
        self.kwargs = dict(openai_kwargs)

        async def chat(messages, **kwargs) -> str | None:
            from pathway_tpu.xpacks.llm.embedders import _post_json

            msgs = messages.value if isinstance(messages, Json) else messages
            if isinstance(msgs, str):
                msgs = [{"role": "user", "content": msgs}]
            payload = {
                "model": kwargs.pop("model", self.model),
                "messages": msgs,
                **{**self.kwargs, **kwargs},
            }
            data = await _post_json(
                f"{self.base_url}/chat/completions", payload, self.api_key
            )
            return data["choices"][0]["message"]["content"]

        self.func = chat


class LiteLLMChat(BaseChat):
    """reference: llms.py LiteLLMChat:324."""

    def __init__(
        self,
        model: str | None = None,
        *,
        capacity: int | None = None,
        retry_strategy=None,
        cache_strategy=None,
        **litellm_kwargs,
    ):
        super().__init__(
            return_type=Optional[str],
            executor=async_executor(
                capacity=capacity, retry_strategy=retry_strategy
            ),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(litellm_kwargs)

        async def chat(messages, **kwargs) -> str | None:
            try:
                import litellm
            except ImportError as exc:
                raise ImportError(
                    "LiteLLMChat requires the litellm package"
                ) from exc
            msgs = messages.value if isinstance(messages, Json) else messages
            if isinstance(msgs, str):
                msgs = [{"role": "user", "content": msgs}]
            response = await litellm.acompletion(
                model=kwargs.pop("model", self.model),
                messages=msgs,
                **{**self.kwargs, **kwargs},
            )
            return response.choices[0].message.content

        self.func = chat


class CohereChat(BaseChat):
    """reference: llms.py CohereChat:621."""

    def __init__(
        self,
        model: str | None = "command",
        *,
        capacity: int | None = None,
        retry_strategy=None,
        cache_strategy=None,
        api_key: str | None = None,
        **cohere_kwargs,
    ):
        super().__init__(
            return_type=Optional[str],
            executor=async_executor(
                capacity=capacity, retry_strategy=retry_strategy
            ),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.api_key = api_key
        self.kwargs = dict(cohere_kwargs)

        async def chat(messages, **kwargs) -> str | None:
            from pathway_tpu.xpacks.llm.embedders import _post_json

            prompt = _messages_to_prompt(messages)
            payload = {
                "model": self.model,
                "message": prompt,
                **{**self.kwargs, **kwargs},
            }
            data = await _post_json(
                "https://api.cohere.ai/v1/chat", payload, self.api_key
            )
            return data.get("text")

        self.func = chat


class HFPipelineChat(BaseChat):
    """Local generation on TPU via the JAX decoder (reference: llms.py
    HFPipelineChat:456 — name kept for parity; 'HF pipeline' here means the
    in-tree TransformerLM, Mistral-class geometry for the Private-RAG
    config)."""

    def __init__(
        self,
        model: str | None = "tiny-decoder",
        *,
        call_kwargs: dict = {},
        device: str = "tpu",
        max_batch_size: int = 32,
        max_new_tokens: int = 32,
        generator=None,
        **pipeline_kwargs,
    ):
        super().__init__(
            return_type=Optional[str],
            deterministic=True,
            max_batch_size=max_batch_size,
        )
        self.model = model
        self.max_new_tokens = call_kwargs.get("max_new_tokens", max_new_tokens)
        if generator is not None:
            self.generator = generator
        else:
            from pathway_tpu.models.decoder_lm import ChatModel

            self.generator = ChatModel.cached(model or "tiny-decoder")

        def chat_batch(messages_batch: List[Any]) -> List[str | None]:
            prompts = [_messages_to_prompt(m) for m in messages_batch]
            return list(
                self.generator.generate(
                    prompts, max_new_tokens=self.max_new_tokens
                )
            )

        self.func = chat_batch

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        words = input_string.split()
        if len(words) > max_prompt_length:
            words = words[-max_prompt_length:]
        return " ".join(words)


def prompt_chat_single_qa(question) -> ColumnExpression:
    """Wrap a question column into a single-message chat (reference:
    llms.py prompt_chat_single_qa:761)."""
    return apply_with_type(
        lambda q: Json([{"role": "user", "content": q}]), Json, question
    )
