"""LLM xpack: embedders, chats, splitters, parsers, rerankers, document
store, vector store, RAG question answering, servers (reference:
python/pathway/xpacks/llm/)."""

from pathway_tpu.xpacks.llm._typing import (
    Doc,
    DocTransformer,
    DocTransformerCallable,
)
from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    parsers,
    prompts,
    rerankers,
    splitters,
)

__all__ = [
    "Doc",
    "DocTransformer",
    "DocTransformerCallable",
    "embedders",
    "llms",
    "parsers",
    "prompts",
    "rerankers",
    "splitters",
]


def __getattr__(name):
    import importlib

    known = {
        "document_store",
        "vector_store",
        "question_answering",
        "servers",
        "mcp_server",
    }
    if name in known:
        return importlib.import_module(f"pathway_tpu.xpacks.llm.{name}")
    raise AttributeError(name)
