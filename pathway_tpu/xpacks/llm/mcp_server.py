"""MCP server — expose document-store/RAG endpoints as MCP tools
(reference: python/pathway/xpacks/llm/mcp_server.py McpServer:143,
McpServable:129, PathwayMcp:237; fastmcp there, a self-contained
JSON-RPC-over-HTTP implementation here)."""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Type

from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.http import PathwayWebserver, rest_connector


class McpServable:
    """Implement `register_mcp(server)` to expose tools (reference:
    mcp_server.py McpServable:129)."""

    def register_mcp(self, server: "McpServer") -> None:
        raise NotImplementedError


class McpConfig:
    def __init__(self, name: str = "pathway-mcp", transport: str = "streamable-http", host: str = "127.0.0.1", port: int = 8123):
        self.name = name
        self.transport = transport
        self.host = host
        self.port = port


class McpServer:
    """Streamable-HTTP MCP endpoint: JSON-RPC methods initialize,
    tools/list, tools/call (reference: mcp_server.py McpServer:143)."""

    _instances: Dict[str, "McpServer"] = {}

    def __init__(self, config: McpConfig):
        self.config = config
        self.webserver = PathwayWebserver(config.host, config.port)
        self._tools: Dict[str, dict] = {}

    @classmethod
    def get(cls, config: McpConfig) -> "McpServer":
        key = f"{config.host}:{config.port}"
        if key not in cls._instances:
            cls._instances[key] = cls(config)
        return cls._instances[key]

    def tool(
        self,
        name: str,
        *,
        request_handler: Callable,
        schema: Type[Schema],
        description: str | None = None,
    ) -> None:
        """Register a tool backed by a dataflow handler (handler(table) ->
        result table with `result` column)."""
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=f"/mcp/tools/{name}",
            schema=schema,
            methods=("POST",),
            delete_completed_queries=True,
        )
        writer(request_handler(queries))
        self._tools[name] = {
            "name": name,
            "description": description or name,
            "inputSchema": {
                "type": "object",
                "properties": {
                    col: {"type": _json_type(c.dtype)}
                    for col, c in schema.columns().items()
                },
            },
        }
        self._register_rpc_route()

    _rpc_registered = False

    def _register_rpc_route(self) -> None:
        if self._rpc_registered:
            return
        self._rpc_registered = True

        async def rpc_handler(payload: dict, request):
            method = payload.get("method")
            msg_id = payload.get("id")
            if method == "initialize":
                result = {
                    "protocolVersion": "2024-11-05",
                    "serverInfo": {"name": self.config.name, "version": "1.0"},
                    "capabilities": {"tools": {}},
                }
            elif method == "tools/list":
                result = {"tools": list(self._tools.values())}
            elif method == "tools/call":
                params = payload.get("params", {})
                name = params.get("name")
                args = params.get("arguments", {})
                import aiohttp

                url = (
                    f"http://{self.config.host}:{self.config.port}"
                    f"/mcp/tools/{name}"
                )
                async with aiohttp.ClientSession() as session:
                    async with session.post(url, json=args) as resp:
                        tool_result = await resp.json()
                result = {
                    "content": [
                        {"type": "text", "text": json.dumps(tool_result)}
                    ]
                }
            else:
                return {
                    "jsonrpc": "2.0",
                    "id": msg_id,
                    "error": {"code": -32601, "message": "method not found"},
                }
            return {"jsonrpc": "2.0", "id": msg_id, "result": result}

        self.webserver.register_route("/mcp", ("POST",), rpc_handler)
        self.webserver._ensure_started()


def _json_type(dtype) -> str:
    from pathway_tpu.internals import dtype as dt

    core = dt.unoptionalize(dtype)
    if core is dt.INT:
        return "integer"
    if core is dt.FLOAT:
        return "number"
    if core is dt.BOOL:
        return "boolean"
    if core is dt.STR:
        return "string"
    return "object"


@dataclass
class PathwayMcp:
    """Declarative MCP wiring (reference: mcp_server.py PathwayMcp:237)."""

    name: str = "pathway-mcp"
    transport: str = "streamable-http"
    host: str = "127.0.0.1"
    port: int = 8123
    serve: List[McpServable] = field(default_factory=list)

    def __post_init__(self):
        config = McpConfig(
            name=self.name,
            transport=self.transport,
            host=self.host,
            port=self.port,
        )
        server = McpServer.get(config)
        for servable in self.serve:
            servable.register_mcp(server)
