"""RAG question answering (reference:
python/pathway/xpacks/llm/question_answering.py: BaseQuestionAnswerer:389,
SummaryQuestionAnswerer:428, BaseRAGQuestionAnswerer:443,
AdaptiveRAGQuestionAnswerer:744, answer_with_geometric_rag_strategy:185,
RAGClient:995)."""

from __future__ import annotations

import json
from typing import Any, Callable, List, Optional

from pathway_tpu.engine.value import Json
from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.schema import Schema
from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm import prompts as prompt_lib
from pathway_tpu.xpacks.llm.document_store import DocumentStore


class BaseContextProcessor:
    """Turn retrieved docs into prompt context (reference:
    question_answering.py:40-106)."""

    def docs_to_context(self, docs) -> str:
        return prompt_lib._docs_to_context(docs)


class BaseQuestionAnswerer:
    """reference: question_answering.py BaseQuestionAnswerer:389."""

    class AnswerQuerySchema(Schema):
        prompt: str
        filters: Optional[str]
        metadata_filter: Optional[str]
        filepath_globpattern: Optional[str]
        model: Optional[str]
        return_context_docs: Optional[bool]

    class SummarizeQuerySchema(Schema):
        text_list: Json
        model: Optional[str]

    def answer_query(self, pw_ai_queries: Table) -> Table:
        raise NotImplementedError


class BaseRAGQuestionAnswerer(BaseQuestionAnswerer):
    """Standard RAG: retrieve → prompt → llm (reference:
    question_answering.py BaseRAGQuestionAnswerer:443)."""

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        default_llm_name: str | None = None,
        short_prompt_template=None,
        long_prompt_template=None,
        summarize_template=None,
        search_topk: int = 6,
        prompt_template=None,
        context_processor: BaseContextProcessor | None = None,
    ):
        self.llm = llm
        self.indexer = indexer
        self.search_topk = search_topk
        self.prompt_udf = prompt_template or prompt_lib.prompt_qa
        self.context_processor = context_processor or BaseContextProcessor()
        self.server = None

    # -- retrieval helper -------------------------------------------------
    def _retrieve_docs(self, queries: Table, k: int | None = None) -> Table:
        retrieval_queries = queries.select(
            query=queries.prompt,
            k=k or self.search_topk,
            metadata_filter=pw_api.coalesce(
                queries.filters, queries.metadata_filter, None
            ),
            filepath_globpattern=queries.filepath_globpattern,
        )
        return self.indexer.retrieve_query(retrieval_queries)

    def answer_query(self, pw_ai_queries: Table) -> Table:
        """reference: question_answering.py answer endpoint :560-740."""
        docs = self._retrieve_docs(pw_ai_queries)
        with_docs = pw_ai_queries.select(
            prompt=pw_ai_queries.prompt,
            return_context_docs=pw_ai_queries.return_context_docs,
            docs=docs.result,
        )
        prompted = with_docs.select(
            prompt_text=self.prompt_udf(with_docs.prompt, with_docs.docs),
            docs=with_docs.docs,
            return_context_docs=with_docs.return_context_docs,
        )
        from pathway_tpu.xpacks.llm.llms import prompt_chat_single_qa

        answered = prompted.select(
            response=self.llm(prompt_chat_single_qa(prompted.prompt_text)),
            docs=prompted.docs,
            return_context_docs=prompted.return_context_docs,
        )

        def pack(response, docs, return_context_docs) -> Json:
            out: dict = {"response": response}
            if return_context_docs:
                out["context_docs"] = (
                    docs.value if isinstance(docs, Json) else docs
                )
            return Json(out)

        return answered.select(
            result=pw_api.apply_with_type(
                pack,
                Json,
                answered.response,
                answered.docs,
                answered.return_context_docs,
            )
        )

    def summarize_query(self, summarize_queries: Table) -> Table:
        """reference: SummaryQuestionAnswerer:428."""
        from pathway_tpu.xpacks.llm.llms import prompt_chat_single_qa

        prompted = summarize_queries.select(
            prompt_text=prompt_lib.prompt_summarize(
                summarize_queries.text_list
            ),
        )
        answered = prompted.select(
            result=pw_api.apply_with_type(
                lambda r: Json({"response": r}),
                Json,
                self.llm(prompt_chat_single_qa(prompted.prompt_text)),
            )
        )
        return answered

    # -- serving ----------------------------------------------------------
    def build_server(self, host: str, port: int, **kwargs) -> None:
        """reference: question_answering.py build_server."""
        from pathway_tpu.xpacks.llm.servers import QASummaryRestServer

        self.server = QASummaryRestServer(host, port, self, **kwargs)

    def run_server(self, *args, threaded: bool = False, **kwargs):
        if self.server is None:
            raise RuntimeError("call build_server(host, port) first")
        return self.server.run(threaded=threaded)


SummaryQuestionAnswerer = BaseRAGQuestionAnswerer


def answer_with_geometric_rag_strategy(
    questions: List[str],
    documents: List[List[str]],
    llm_chat_model,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    strict_prompt: bool = False,
) -> List[str]:
    """Geometric doc-count escalation (reference: question_answering.py
    answer_with_geometric_rag_strategy:185): ask with n docs, escalate n*=factor
    while the model answers 'No information found'."""
    no_answer = "No information found."
    answers: List[str] = []
    for question, docs in zip(questions, documents):
        n = n_starting_documents
        answer = no_answer
        for _ in range(max_iterations):
            context = "\n\n".join(docs[:n])
            strictness = (
                "Answer with the shortest possible span from the context, "
                "no explanations. "
                if strict_prompt
                else ""
            )
            prompt = (
                f"{strictness}Please answer using only the context. If the "
                f"context is insufficient, reply exactly {no_answer!r}.\n"
                f"Context: {context}\nQuestion: {question}\nAnswer:"
            )
            result = llm_chat_model.func([{"role": "user", "content": prompt}])
            import asyncio
            import inspect

            if inspect.isawaitable(result):
                result = asyncio.run(result)
            if isinstance(result, list):
                result = result[0]
            answer = str(result).strip() if result is not None else no_answer
            if no_answer.lower() not in answer.lower():
                break
            n *= factor
        answers.append(answer)
    return answers


def answer_with_geometric_rag_strategy_from_index(
    questions,
    index,
    documents_column_name,
    llm_chat_model,
    *,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    metadata_filter=None,
    strict_prompt: bool = False,
):
    """Dataflow form of geometric RAG (reference: question_answering.py
    answer_with_geometric_rag_strategy_from_index:304): retrieve
    ``n_starting_documents * factor^(max_iterations-1)`` documents from the
    index once, then escalate the per-prompt document count geometrically
    until the chat commits to an answer. Returns the answer column."""
    if not isinstance(documents_column_name, str):
        documents_column_name = documents_column_name.name
    if questions.name == documents_column_name:
        # collapse_rows gives query columns precedence over same-named
        # reply columns — requery under a reserved name so the documents
        # column survives
        qt = questions._table.select(**{"_pw_rag_query": questions})
        questions = qt["_pw_rag_query"]
    max_documents = n_starting_documents * (factor ** (max_iterations - 1))
    reply = index.query_as_of_now(
        questions,
        number_of_matches=max_documents,
        collapse_rows=True,
        metadata_filter=metadata_filter,
    )
    q_name = questions.name

    def per_row(question, docs):
        return answer_with_geometric_rag_strategy(
            [question],
            [[d for d in (docs or []) if d is not None]],
            llm_chat_model,
            n_starting_documents=n_starting_documents,
            factor=factor,
            max_iterations=max_iterations,
            strict_prompt=strict_prompt,
        )[0]

    result = reply.select(
        answer=pw_api.apply_with_type(
            per_row,
            str,
            reply[q_name],
            reply[documents_column_name],
        )
    )
    return result.answer


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Adaptive RAG: retrieve max docs once, escalate the prompt doc count
    geometrically until the LLM commits to an answer (reference:
    question_answering.py AdaptiveRAGQuestionAnswerer:744)."""

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        **kwargs,
    ):
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        max_docs = n_starting_documents * factor ** (max_iterations - 1)
        self.search_topk = max(self.search_topk, max_docs)

    def answer_query(self, pw_ai_queries: Table) -> Table:
        docs = self._retrieve_docs(pw_ai_queries, k=self.search_topk)
        with_docs = pw_ai_queries.select(
            prompt=pw_ai_queries.prompt,
            return_context_docs=pw_ai_queries.return_context_docs,
            docs=docs.result,
        )
        llm = self.llm
        n0, factor, max_iter = (
            self.n_starting_documents,
            self.factor,
            self.max_iterations,
        )

        def adaptive_answer(question: str, docs_json) -> Json:
            doc_entries = (
                docs_json.value if isinstance(docs_json, Json) else docs_json
            ) or []
            texts = [
                d.get("text", "") if isinstance(d, dict) else str(d)
                for d in doc_entries
            ]
            (answer,) = answer_with_geometric_rag_strategy(
                [question],
                [texts],
                llm,
                n_starting_documents=n0,
                factor=factor,
                max_iterations=max_iter,
            )
            return Json({"response": answer})

        return with_docs.select(
            result=pw_api.apply_with_type(
                adaptive_answer, Json, with_docs.prompt, with_docs.docs
            )
        )


class DeckRetriever(BaseQuestionAnswerer):
    """reference: question_answering.py DeckRetriever:877 — slide search."""

    def __init__(self, indexer: DocumentStore, *, search_topk: int = 6):
        self.indexer = indexer
        self.search_topk = search_topk

    def answer_query(self, pw_ai_queries: Table) -> Table:
        retrieval_queries = pw_ai_queries.select(
            query=pw_ai_queries.prompt,
            k=self.search_topk,
            metadata_filter=pw_ai_queries.metadata_filter,
            filepath_globpattern=pw_ai_queries.filepath_globpattern,
        )
        return self.indexer.retrieve_query(retrieval_queries)


class RAGClient:
    """HTTP client for QA servers (reference: question_answering.py
    RAGClient:995)."""

    def __init__(self, host: str | None = None, port: int | None = None, url: str | None = None, timeout: int = 90):
        if url is None:
            url = f"http://{host}:{port}"
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, route: str, payload: dict):
        import urllib.request

        req = urllib.request.Request(
            self.url + route,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def answer(self, prompt: str, filters: str | None = None, model: str | None = None, return_context_docs: bool = False):
        return self._post(
            "/v2/answer",
            {
                "prompt": prompt,
                "filters": filters,
                "model": model,
                "return_context_docs": return_context_docs,
            },
        )

    pw_ai_answer = answer

    def summarize(self, text_list: List[str], model: str | None = None):
        return self._post(
            "/v2/summarize", {"text_list": text_list, "model": model}
        )

    pw_ai_summary = summarize

    def retrieve(self, query: str, k: int = 6, metadata_filter: str | None = None, filepath_globpattern: str | None = None):
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    def list_documents(self, filters: str | None = None):
        return self._post("/v2/list_documents", {"metadata_filter": filters})

    def statistics(self):
        return self._post("/v1/statistics", {})
