"""Embedders (reference: python/pathway/xpacks/llm/embedders.py).

`SentenceTransformerEmbedder` is the north-star TPU model: batched sync UDF
whose batches hit a jit-compiled JAX encoder (reference runs torch on
CPU/GPU, embedders.py:342-434). API-backed embedders (OpenAI/LiteLLM/Gemini)
are async UDFs with capacity/retry, as in the reference.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.udfs import UDF, async_executor


class BaseEmbedder(UDF):
    """reference: embedders.py BaseEmbedder:67."""

    def get_embedding_dimension(self, **kwargs) -> int:
        import asyncio
        import inspect

        result = self.func(".", **kwargs)
        if inspect.isawaitable(result):
            # asyncio.run would explode if a loop is already running (e.g.
            # called from inside the aiohttp server) — run the coroutine on
            # a private loop in a helper thread instead.
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                result = asyncio.run(result)
            else:
                import concurrent.futures

                with concurrent.futures.ThreadPoolExecutor(1) as pool:
                    result = pool.submit(asyncio.run, result).result()
        return len(result)

    def __call__(self, input: Any, **kwargs) -> ColumnExpression:
        return super().__call__(input, **kwargs)


class SentenceTransformerEmbedder(BaseEmbedder):
    """Sentence encoder on TPU via JAX (reference: embedders.py
    SentenceTransformerEmbedder:342 — torch SentenceTransformer with
    max_batch_size batching; here batches land on the MXU in bf16)."""

    def __init__(
        self,
        model: str = "all-MiniLM-L6-v2",
        *,
        call_kwargs: dict = {},
        device: str = "tpu",
        max_batch_size: int = 1024,
        **init_kwargs,
    ):
        super().__init__(
            return_type=np.ndarray,
            deterministic=True,
            max_batch_size=max_batch_size,
        )
        from pathway_tpu.models.minilm import SentenceEncoder

        self.model = model
        self.encoder = SentenceEncoder.cached(model, **init_kwargs)
        self.kwargs = dict(init_kwargs)

        def embed_batch(texts: List[str]) -> List[np.ndarray]:
            vectors = self.encoder.encode(texts)
            return list(vectors)

        # async submit/await contract for the batched-UDF runtime
        # (engine/expression_eval.py two-phase path): submit tokenizes +
        # enqueues the device encode for chunk i, so the host tokenizes
        # chunk i+1 while the MXU runs chunk i. encode() is literally
        # await(submit(...)), so sync and async results are bit-identical.
        def submit_batch(texts: List[str]):
            return self.encoder.encode_submit(list(texts))

        def await_batch(handle) -> List[np.ndarray]:
            return list(self.encoder.encode_await(handle))

        embed_batch.submit_batch = submit_batch
        embed_batch.await_batch = await_batch
        # static-analyzer marker (analysis PWT401/PWT402): enough shape
        # facts to predict the classic path's padding waste and check
        # mesh-axis divisibility without building a model
        embed_batch._pw_embedder = {
            "model": model,
            "max_batch_size": max_batch_size,
            "max_len": self.encoder.max_len,
            "dimension": self.encoder.dimension,
        }
        self.func = embed_batch

    def get_embedding_dimension(self, **kwargs) -> int:
        return self.encoder.dimension

    def __call__(self, input: Any, **kwargs) -> ColumnExpression:
        return UDF.__call__(self, input)


class OpenAIEmbedder(BaseEmbedder):
    """reference: embedders.py OpenAIEmbedder:88 — async API UDF."""

    def __init__(
        self,
        model: str | None = "text-embedding-3-small",
        *,
        capacity: int | None = None,
        retry_strategy=None,
        cache_strategy=None,
        api_key: str | None = None,
        base_url: str | None = None,
        **openai_kwargs,
    ):
        super().__init__(
            return_type=np.ndarray,
            executor=async_executor(
                capacity=capacity, retry_strategy=retry_strategy
            ),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.api_key = api_key
        self.base_url = base_url or "https://api.openai.com/v1"
        self.kwargs = dict(openai_kwargs)

        async def embed(text: str, **kwargs) -> np.ndarray:
            payload = {"model": self.model, "input": text or ".", **kwargs}
            data = await _post_json(
                f"{self.base_url}/embeddings", payload, self.api_key
            )
            return np.array(data["data"][0]["embedding"], dtype=np.float32)

        self.func = embed


class LiteLLMEmbedder(BaseEmbedder):
    """reference: embedders.py LiteLLMEmbedder:251 — delegates to the
    litellm package when installed."""

    def __init__(
        self,
        model: str | None = None,
        *,
        capacity: int | None = None,
        retry_strategy=None,
        cache_strategy=None,
        **litellm_kwargs,
    ):
        super().__init__(
            return_type=np.ndarray,
            executor=async_executor(
                capacity=capacity, retry_strategy=retry_strategy
            ),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(litellm_kwargs)

        async def embed(text: str, **kwargs) -> np.ndarray:
            try:
                import litellm
            except ImportError as exc:
                raise ImportError(
                    "LiteLLMEmbedder requires the litellm package"
                ) from exc
            result = await litellm.aembedding(
                model=self.model, input=[text or "."], **{**self.kwargs, **kwargs}
            )
            return np.array(result.data[0]["embedding"], dtype=np.float32)

        self.func = embed


class GeminiEmbedder(BaseEmbedder):
    """reference: embedders.py GeminiEmbedder:446."""

    def __init__(
        self,
        model: str | None = "models/embedding-001",
        *,
        capacity: int | None = None,
        retry_strategy=None,
        cache_strategy=None,
        api_key: str | None = None,
        **gemini_kwargs,
    ):
        super().__init__(
            return_type=np.ndarray,
            executor=async_executor(
                capacity=capacity, retry_strategy=retry_strategy
            ),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.api_key = api_key
        self.kwargs = dict(gemini_kwargs)

        async def embed(text: str, **kwargs) -> np.ndarray:
            url = (
                "https://generativelanguage.googleapis.com/v1beta/"
                f"{self.model}:embedContent?key={self.api_key}"
            )
            payload = {"content": {"parts": [{"text": text or "."}]}}
            data = await _post_json(url, payload, None)
            return np.array(
                data["embedding"]["values"], dtype=np.float32
            )

        self.func = embed


async def _post_json(url: str, payload: dict, bearer: str | None) -> dict:
    import aiohttp

    headers = {"Content-Type": "application/json"}
    if bearer:
        headers["Authorization"] = f"Bearer {bearer}"
    async with aiohttp.ClientSession() as session:
        async with session.post(url, json=payload, headers=headers) as resp:
            resp.raise_for_status()
            return await resp.json()
