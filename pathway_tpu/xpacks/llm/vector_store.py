"""VectorStoreServer — DocumentStore + auto embedder index + REST server
(reference: python/pathway/xpacks/llm/vector_store.py VectorStoreServer:31,
run_server:64). The north-star entrypoint (BASELINE.json configs[0-1])."""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm.document_store import (
    DocumentStore,
    DocumentStoreClient,
)


class VectorStoreServer:
    """reference: vector_store.py VectorStoreServer:31."""

    def __init__(
        self,
        *docs: Table,
        embedder=None,
        parser=None,
        splitter=None,
        doc_post_processors=None,
        index_factory=None,
    ):
        if index_factory is None:
            from pathway_tpu.stdlib.indexing.nearest_neighbors import (
                BruteForceKnnFactory,
            )

            if embedder is None:
                raise ValueError("provide embedder= or index_factory=")
            index_factory = BruteForceKnnFactory(
                dimensions=embedder.get_embedding_dimension(),
                embedder=embedder,
            )
        self.embedder = embedder
        self.document_store = DocumentStore(
            list(docs),
            retriever_factory=index_factory,
            parser=parser,
            splitter=splitter,
            doc_post_processors=doc_post_processors,
        )

    @classmethod
    def from_langchain_components(
        cls, *docs, embedder=None, parser=None, splitter=None, **kwargs
    ):
        """reference: document_store.py from_langchain_components:121 —
        wraps langchain embedder/splitter callables."""
        from pathway_tpu.internals.udfs import udf

        lc_embedder = embedder

        @udf
        async def embedding_udf(text: str):
            import numpy as np

            result = await lc_embedder.aembed_documents([text])
            return np.array(result[0], dtype=np.float32)

        class _Wrapper:
            def __call__(self, column):
                return embedding_udf(column)

            def get_embedding_dimension(self):
                import asyncio

                return len(asyncio.run(lc_embedder.aembed_documents(["."]))[0])

        wrapped_splitter = None
        if splitter is not None:

            @udf
            def splitter_udf(text: str, metadata) -> list:
                return [
                    (c.page_content, dict(c.metadata))
                    for c in splitter.create_documents([text])
                ]

            class _SplitWrapper:
                def __call__(self, text, metadata):
                    return splitter_udf(text, metadata)

            wrapped_splitter = _SplitWrapper()

        return cls(
            *docs,
            embedder=_Wrapper(),
            parser=parser,
            splitter=wrapped_splitter,
            **kwargs,
        )

    @classmethod
    def from_llamaindex_components(
        cls, *docs, transformations=None, parser=None, **kwargs
    ):
        """Build the store from LlamaIndex TransformComponents (reference:
        document_store.py from_llamaindex_components:162): each document
        becomes a TextNode, runs through the transformation pipeline, and
        the resulting nodes become (text, metadata) chunks."""
        try:
            from llama_index.core.ingestion.pipeline import (  # type: ignore
                run_transformations,
            )
            from llama_index.core.schema import (  # type: ignore
                MetadataMode,
                TextNode,
            )
        except ImportError as exc:
            raise ImportError(
                "Please install llama-index-core: "
                "`pip install llama-index-core`"
            ) from exc

        from pathway_tpu.internals.udfs import udf

        @udf
        def splitter_udf(text: str, metadata) -> list:
            nodes = run_transformations(
                [TextNode(text=text)], transformations or []
            )
            return [
                (
                    node.get_content(metadata_mode=MetadataMode.NONE),
                    dict(node.extra_info or {}),
                )
                for node in nodes
            ]

        return cls(*docs, parser=parser, splitter=splitter_udf, **kwargs)

    def run_server(
        self,
        host: str,
        port: int,
        *,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend=None,
        terminate_on_error: bool = True,
    ):
        """Serve /v1/retrieve, /v1/statistics, /v1/inputs (reference:
        vector_store.py run_server:64)."""
        from pathway_tpu.xpacks.llm.servers import DocumentStoreServer

        server = DocumentStoreServer(
            host=host, port=port, document_store=self.document_store
        )
        return server.run(threaded=threaded, with_cache=with_cache)


class VectorStoreClient(DocumentStoreClient):
    """reference: vector_store client (query by text)."""

    def query(self, query: str, k: int = 3, metadata_filter: str | None = None):
        return self.retrieve(query, k=k, metadata_filter=metadata_filter)
