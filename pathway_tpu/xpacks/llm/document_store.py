"""DocumentStore: sources → parse → post-process → split → index
(reference: python/pathway/xpacks/llm/document_store.py DocumentStore:53,
build_pipeline:319, retrieve_query:530, statistics_query:409,
inputs_query:453)."""

from __future__ import annotations

import json
from typing import Any, Callable, Iterable, List, Optional

from pathway_tpu.engine.value import Json
from pathway_tpu.internals import api as pw_api
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.schema import Schema
from pathway_tpu.internals.table import Table


class DocumentStore:
    """reference: document_store.py DocumentStore:53."""

    class RetrieveQuerySchema(Schema):
        query: str
        k: int
        metadata_filter: Optional[str]
        filepath_globpattern: Optional[str]

    class StatisticsQuerySchema(Schema):
        pass

    class InputsQuerySchema(Schema):
        metadata_filter: Optional[str]
        filepath_globpattern: Optional[str]

    class QueryResultSchema(Schema):
        result: Json

    def __init__(
        self,
        docs,
        retriever_factory,
        parser=None,
        splitter=None,
        doc_post_processors: List[Callable] | None = None,
    ):
        from pathway_tpu.xpacks.llm.parsers import Utf8Parser
        from pathway_tpu.xpacks.llm.splitters import NullSplitter

        if isinstance(docs, Table):
            docs = [docs]
        self.docs_tables = list(docs)
        self.retriever_factory = retriever_factory
        self.parser = parser or Utf8Parser()
        self.splitter = splitter or NullSplitter()
        self.doc_post_processors = doc_post_processors or []
        self.build_pipeline()

    # -- pipeline ---------------------------------------------------------
    def build_pipeline(self) -> None:
        """reference: document_store.py build_pipeline:319."""
        normalized = []
        for t in self.docs_tables:
            cols = {"data": t.data}
            if "_metadata" in t.column_names():
                cols["_metadata"] = t._metadata
            else:
                cols["_metadata"] = Json({})
            normalized.append(t.select(**cols))
        docs = normalized[0]
        if len(normalized) > 1:
            docs = docs.concat_reindex(*normalized[1:])
        self.input_docs = docs

        parsed = docs.select(
            parts=self.parser(docs.data), _metadata=docs._metadata
        ).flatten(thisclass.this.parts)
        parsed = parsed.select(
            text=parsed.parts.get(0),
            metadata=pw_api.apply_with_type(
                _merge_meta, Json, parsed._metadata, parsed.parts.get(1)
            ),
        )
        for post in self.doc_post_processors:
            parsed = parsed.select(
                text=pw_api.apply_with_type(
                    lambda t, m, post=post: post(t, m)[0], str,
                    parsed.text, parsed.metadata,
                ),
                metadata=pw_api.apply_with_type(
                    lambda t, m, post=post: Json(post(t, m)[1]), Json,
                    parsed.text, parsed.metadata,
                ),
            )

        from pathway_tpu.xpacks.llm.splitters import NullSplitter

        if type(self.splitter) is NullSplitter:
            # a null split is one chunk per document with metadata passed
            # through — the split/flatten/repack stages would only rebuild
            # identical rows (bulk-ingest host path stays O(1) per doc)
            self.chunked_docs = parsed
        else:
            chunked = parsed.select(
                chunks=self.splitter(parsed.text, parsed.metadata),
            ).flatten(thisclass.this.chunks)
            self.chunked_docs = chunked.select(
                text=chunked.chunks.get(0),
                metadata=pw_api.apply_with_type(
                    lambda m: Json(
                        m if isinstance(m, dict) else getattr(m, "value", {})
                    ),
                    Json,
                    chunked.chunks.get(1),
                ),
            )
        self._index = self.retriever_factory.build_index(
            self.chunked_docs.text,
            self.chunked_docs,
            metadata_column=self.chunked_docs.metadata,
        )

    @property
    def index(self):
        return self._index

    @staticmethod
    def merge_filters(queries: Table) -> Table:
        """Fold filepath_globpattern into the metadata filter (reference:
        document_store.py merge_filters)."""
        return queries.select(
            thisclass.this.without("metadata_filter", "filepath_globpattern"),
            metadata_filter=pw_api.apply_with_type(
                _combined_filter,
                Optional[str],
                queries.metadata_filter,
                queries.filepath_globpattern,
            ),
        )

    # -- endpoints --------------------------------------------------------
    def retrieve_query(self, retrieval_queries: Table) -> Table:
        """reference: document_store.py retrieve_query:530."""
        queries = self.merge_filters(retrieval_queries)
        reply = self._index.query_as_of_now(
            queries.query,
            number_of_matches=queries.k,
            metadata_filter=queries.metadata_filter,
            collapse_rows=True,
        )
        result = reply.select(
            result=pw_api.apply_with_type(
                _pack_retrieval_results,
                Json,
                reply.text,
                reply.metadata,
                reply._pw_index_reply_score,
            )
        )
        return result

    def statistics_query(self, info_queries: Table) -> Table:
        """reference: document_store.py statistics_query:409."""
        stats = self.input_docs.reduce(
            count=reducers.count(),
            metas=reducers.tuple(self.input_docs._metadata),
        )

        def pack_stats(count, metas):
            modified = [
                m.value.get("modified_at")
                for m in (metas or ())
                if isinstance(m, Json) and isinstance(m.value, dict)
                and m.value.get("modified_at") is not None
            ]
            seen = [
                m.value.get("seen_at")
                for m in (metas or ())
                if isinstance(m, Json) and isinstance(m.value, dict)
                and m.value.get("seen_at") is not None
            ]
            return Json(
                {
                    "file_count": count,
                    "last_modified": max(modified) if modified else None,
                    "last_indexed": max(seen) if seen else None,
                }
            )

        packed = stats.select(
            result=pw_api.apply_with_type(
                pack_stats, Json, stats.count, stats.metas
            )
        )
        joined = info_queries.join(
            packed, id=__import__('pathway_tpu').left.id
        ).select(result=packed.result)
        return joined

    def inputs_query(self, input_queries: Table) -> Table:
        """reference: document_store.py inputs_query:453."""
        queries = self.merge_filters(input_queries)
        files = self.input_docs.reduce(
            metas=reducers.tuple(self.input_docs._metadata)
        )

        def pack_inputs(metas, metadata_filter):
            from pathway_tpu.stdlib.indexing._filters import evaluate_filter

            out = []
            for m in metas or ():
                value = m.value if isinstance(m, Json) else m
                if metadata_filter and not evaluate_filter(
                    metadata_filter, value
                ):
                    continue
                out.append(value)
            return Json(out)

        joined = queries.join(
            files, id=__import__('pathway_tpu').left.id
        ).select(
            result=pw_api.apply_with_type(
                pack_inputs, Json, files.metas, queries.metadata_filter
            )
        )
        return joined


class SlidesDocumentStore(DocumentStore):
    """reference: document_store.py SlidesDocumentStore:575."""


class DocumentStoreClient:
    """HTTP client for a served DocumentStore (reference:
    document_store.py DocumentStoreClient:636)."""

    def __init__(self, host: str | None = None, port: int | None = None, url: str | None = None, timeout: int = 30):
        if url is None:
            url = f"http://{host}:{port}"
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, route: str, payload: dict):
        import urllib.request

        req = urllib.request.Request(
            self.url + route,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def retrieve(self, query: str, k: int = 3, metadata_filter: str | None = None, filepath_globpattern: str | None = None):
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    __call__ = retrieve

    def statistics(self):
        return self._post("/v1/statistics", {})

    def get_input_files(self, metadata_filter: str | None = None, filepath_globpattern: str | None = None):
        return self._post(
            "/v1/inputs",
            {
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )


def _merge_meta(doc_meta, part_meta) -> Json:
    # bulk-ingest fast path: parsers without per-chunk metadata (Utf8 on
    # the hot path) pass the document metadata through untouched — no new
    # Json per row.  Only for dict-valued metadata: non-dicts must still
    # normalize to Json({}) like the slow path.
    if (
        (not part_meta or (isinstance(part_meta, Json) and not part_meta.value))
        and isinstance(doc_meta, Json)
        and isinstance(doc_meta.value, dict)
    ):
        return doc_meta
    base = doc_meta.value if isinstance(doc_meta, Json) else (doc_meta or {})
    extra = part_meta.value if isinstance(part_meta, Json) else (part_meta or {})
    if not isinstance(base, dict):
        base = {}
    if not isinstance(extra, dict):
        extra = {}
    return Json({**base, **extra})


def _combined_filter(metadata_filter, globpattern) -> str | None:
    filters = []
    if metadata_filter:
        filters.append(f"({metadata_filter})")
    if globpattern:
        filters.append(f"globmatch('{globpattern}', path)")
    return " && ".join(filters) if filters else None


def _pack_retrieval_results(texts, metas, scores) -> Json:
    out = []
    for text, meta, score in zip(texts or (), metas or (), scores or ()):
        out.append(
            {
                "text": text,
                "metadata": meta.value if isinstance(meta, Json) else meta,
                "dist": -float(score),
                "score": float(score),
            }
        )
    return Json(out)
