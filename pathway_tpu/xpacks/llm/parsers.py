"""Document parsers (reference: python/pathway/xpacks/llm/parsers.py).

Parsers are UDFs `bytes -> list[tuple[str, dict]]` (text, metadata).

Design notes vs the reference:
- The reference delegates partitioning to the `unstructured` package and
  chunks its Element objects (parsers.py:87-330).  Here the five chunking
  modes (single / elements / paged / by_title / basic) are implemented
  natively over a light element model, with `unstructured` used for
  partitioning when installed and a built-in partitioner (plain text,
  markdown, HTML via bs4) otherwise — parsing stays real without the
  optional dependency.
- PypdfParser (reference parsers.py:1019-1093) keeps the pypdf extraction
  when available and adds the same text cleanup pass (de-hyphenation,
  wrapped-line joining, whitespace collapse); a built-in extractor covers
  simple Flate/plain PDFs so the parser works on real bytes either way.
- DoclingParser genuinely attempts the docling import and converts when
  present (reference parsers.py:334-672).
Parsers run host-side; the TPU path starts downstream at the embedder.
"""

from __future__ import annotations

import inspect
import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from pathway_tpu.internals.udfs import UDF


class Utf8Parser(UDF):
    """reference: parsers.py Utf8Parser:48."""

    def __init__(self):
        # batched: one Python call per engine batch, not per document —
        # this parser sits on the bulk-ingest hot path (SURVEY §3.4)
        super().__init__(
            return_type=list, deterministic=True, max_batch_size=65536
        )

        def parse(contents_batch: list) -> list:
            out = []
            for contents in contents_batch:
                if isinstance(contents, str):
                    text = contents
                else:
                    text = contents.decode("utf-8", errors="replace")
                out.append([(text, {})])
            return out

        self.func = parse


# kept name from older reference versions
ParseUtf8 = Utf8Parser


# ---------------------------------------------------------------------------
# Element model + built-in partitioner
# ---------------------------------------------------------------------------


@dataclass
class Element:
    """Light analogue of unstructured's Element: text + category + meta."""

    text: str
    category: str = "NarrativeText"  # Title | ListItem | NarrativeText | ...
    page_number: Optional[int] = None
    metadata: dict = field(default_factory=dict)

    def to_meta(self) -> dict:
        meta = {"category": self.category, **self.metadata}
        if self.page_number is not None:
            meta["page_number"] = self.page_number
        return meta


_MD_HEADING = re.compile(r"^(#{1,6})\s+(.*)$")
_PAGE_BREAK = "\x0c"


def _partition_text(text: str) -> List[Element]:
    """Plain text / markdown: blank-line separated blocks; markdown
    headings and short ALL-CAPS lines become Title elements; form feeds
    advance the page number."""
    elements: List[Element] = []
    page = 1
    for page_chunk in text.split(_PAGE_BREAK):
        for block in re.split(r"\n\s*\n", page_chunk):
            block = block.strip()
            if not block:
                continue
            lines = block.splitlines()
            m = _MD_HEADING.match(lines[0])
            if m and len(lines) == 1:
                elements.append(Element(m.group(2).strip(), "Title", page))
                continue
            first = lines[0].strip()
            if (
                len(lines) == 1
                and 0 < len(first) <= 80
                and first == first.upper()
                and any(c.isalpha() for c in first)
                and not first.endswith((".", ":", ";", ","))
            ):
                elements.append(Element(first, "Title", page))
                continue
            if block.lstrip().startswith(("- ", "* ", "+ ")) or re.match(
                r"^\d+[.)]\s", block.lstrip()
            ):
                for line in lines:
                    line = line.strip()
                    if line:
                        elements.append(
                            Element(
                                re.sub(r"^([-*+]|\d+[.)])\s+", "", line),
                                "ListItem",
                                page,
                            )
                        )
                continue
            elements.append(
                Element(" ".join(block.split()), "NarrativeText", page)
            )
        page += 1
    return elements


_HTML_TITLE_TAGS = {"h1", "h2", "h3", "h4", "h5", "h6"}
_HTML_BLOCK_TAGS = _HTML_TITLE_TAGS | {"p", "li", "td", "pre", "blockquote"}


def _partition_html(markup: str) -> List[Element]:
    try:
        from bs4 import BeautifulSoup
    except ImportError:
        # degrade without bs4: strip tags, keep the text blocks
        text = re.sub(r"<(script|style)\b.*?</\1>", " ", markup, flags=re.S | re.I)
        text = re.sub(r"<br\s*/?>|</(p|div|li|h[1-6])>", "\n\n", text, flags=re.I)
        text = re.sub(r"<[^>]+>", " ", text)
        import html as html_mod

        return _partition_text(html_mod.unescape(text))

    soup = BeautifulSoup(markup, "html.parser")
    for tag in soup(["script", "style"]):
        tag.decompose()
    elements: List[Element] = []
    for tag in soup.find_all(_HTML_BLOCK_TAGS):
        text = " ".join(tag.get_text(" ", strip=True).split())
        if not text:
            continue
        if tag.name in _HTML_TITLE_TAGS:
            cat = "Title"
        elif tag.name == "li":
            cat = "ListItem"
        else:
            cat = "NarrativeText"
        elements.append(Element(text, cat, metadata={"tag": tag.name}))
    if not elements:
        text = " ".join(soup.get_text(" ", strip=True).split())
        if text:
            elements.append(Element(text))
    return elements


def partition_builtin(contents: bytes | str) -> List[Element]:
    """Dependency-free partitioner: sniffs HTML, falls back to
    text/markdown block parsing."""
    if isinstance(contents, bytes):
        text = contents.decode("utf-8", errors="replace")
    else:
        text = contents
    sniff = text[:512].lstrip().lower()
    if sniff.startswith(("<!doctype html", "<html")) or "<body" in sniff:
        return _partition_html(text)
    if re.search(r"<(p|h[1-6]|li)\b", sniff):
        return _partition_html(text)
    return _partition_text(text)


# ---------------------------------------------------------------------------
# Chunking modes (reference: parsers.py UnstructuredParser._chunk:176-233)
# ---------------------------------------------------------------------------

CHUNKING_MODES = ("single", "elements", "paged", "by_title", "basic")


def _combine_metadata(left: dict, right: dict) -> dict:
    out = dict(left)
    for k, v in right.items():
        if k in out and out[k] != v:
            prev = out[k]
            if isinstance(prev, list):
                if v not in prev:
                    out[k] = prev + [v]
            else:
                out[k] = [prev, v]
        else:
            out[k] = v
    return out


def chunk_elements_basic(
    elements: List[Element], *, max_characters: int = 500, **_kw
) -> List[Tuple[str, dict]]:
    """Greedy packing of consecutive elements up to max_characters
    (unstructured's chunk_elements in spirit); an oversized element is
    hard-split at the boundary."""
    chunks: List[Tuple[str, dict]] = []
    buf: List[str] = []
    meta: dict = {}
    size = 0

    def flush():
        nonlocal buf, meta, size
        if buf:
            chunks.append(("\n\n".join(buf), meta))
        buf, meta, size = [], {}, 0

    for el in elements:
        text = el.text
        while len(text) > max_characters:
            flush()
            chunks.append((text[:max_characters], el.to_meta()))
            text = text[max_characters:]
        if not text:
            continue
        if size and size + len(text) + 2 > max_characters:
            flush()
        buf.append(text)
        meta = _combine_metadata(meta, el.to_meta())
        size += len(text) + 2
    flush()
    return chunks


def chunk_elements_by_title(
    elements: List[Element], *, max_characters: int = 2000, **_kw
) -> List[Tuple[str, dict]]:
    """New chunk at every Title element; oversized sections split by the
    basic packer (unstructured's chunk_by_title in spirit)."""
    sections: List[List[Element]] = []
    cur: List[Element] = []
    for el in elements:
        if el.category == "Title" and cur:
            sections.append(cur)
            cur = []
        cur.append(el)
    if cur:
        sections.append(cur)
    out: List[Tuple[str, dict]] = []
    for section in sections:
        joined = "\n\n".join(e.text for e in section)
        meta: dict = {}
        for e in section:
            meta = _combine_metadata(meta, e.to_meta())
        if len(joined) <= max_characters:
            out.append((joined, meta))
        else:
            out.extend(
                chunk_elements_basic(section, max_characters=max_characters)
            )
    return out


def chunk_elements_paged(elements: List[Element]) -> List[Tuple[str, dict]]:
    text_by_page: dict = {}
    meta_by_page: dict = {}
    for el in elements:
        page = el.page_number if el.page_number is not None else 1
        text_by_page[page] = text_by_page.get(page, "") + el.text + "\n\n"
        meta_by_page[page] = _combine_metadata(
            meta_by_page.get(page, {}), el.to_meta()
        )
    return [
        (text_by_page[p], meta_by_page[p]) for p in sorted(text_by_page)
    ]


def chunk(
    elements: List[Element], mode: str, **chunking_kwargs
) -> List[Tuple[str, dict]]:
    if mode == "elements":
        return [(el.text, el.to_meta()) for el in elements]
    if mode == "paged":
        return chunk_elements_paged(elements)
    if mode == "by_title":
        return chunk_elements_by_title(elements, **chunking_kwargs)
    if mode == "basic":
        return chunk_elements_basic(elements, **chunking_kwargs)
    if mode == "single":
        meta: dict = {}
        for el in elements:
            meta = _combine_metadata(meta, el.to_meta())
        return [("\n\n".join(el.text for el in elements), meta)]
    raise ValueError(
        f"chunking_mode must be one of {CHUNKING_MODES}, got {mode!r}"
    )


class UnstructuredParser(UDF):
    """reference: parsers.py UnstructuredParser:87-330.

    Partitioning uses the `unstructured` package when installed; the
    built-in partitioner (text/markdown/HTML) otherwise.  All five
    chunking modes run natively either way."""

    def __init__(
        self,
        chunking_mode: str = "single",
        mode: str | None = None,  # old reference keyword
        post_processors: list | None = None,
        chunking_kwargs: dict | None = None,
        **unstructured_kwargs,
    ):
        super().__init__(return_type=list, deterministic=True)
        chunking_mode = mode or chunking_mode
        if chunking_mode not in CHUNKING_MODES:
            raise ValueError(
                f"Got {chunking_mode!r} for `chunking_mode`, but should "
                f"be one of {CHUNKING_MODES}"
            )
        self.chunking_mode = chunking_mode
        self.chunking_kwargs = chunking_kwargs or {}
        self.post_processors = post_processors or []
        self.kwargs = unstructured_kwargs

        def parse(contents: bytes) -> list:
            elements = self._partition(contents)
            docs = chunk(
                elements, self.chunking_mode, **self.chunking_kwargs
            )
            for proc in self.post_processors:
                docs = [(proc(text), meta) for text, meta in docs]
            return docs

        self.func = parse

    def _partition(self, contents: bytes) -> List[Element]:
        try:
            from unstructured.partition.auto import partition
        except ImportError:
            return partition_builtin(contents)
        import io

        raw = partition(file=io.BytesIO(contents), **self.kwargs)
        out = []
        for el in raw:
            meta = (
                el.metadata.to_dict()
                if getattr(el, "metadata", None) is not None
                else {}
            )
            out.append(
                Element(
                    str(el),
                    getattr(el, "category", "NarrativeText"),
                    meta.get("page_number"),
                    meta,
                )
            )
        return out


class ParseUnstructured(UnstructuredParser):
    """Deprecated alias kept from older reference versions."""


# ---------------------------------------------------------------------------
# PDF
# ---------------------------------------------------------------------------

_HYPHEN_BREAK = re.compile(r"(\w)-\n(\w)")
_LINE_WRAP = re.compile(r"(?<![.!?:;])\n(?!\n)")


def clean_pdf_text(text: str) -> str:
    """Extracted-PDF cleanup (reference: PypdfParser's cleanup pass):
    rejoin hyphenated line breaks, unwrap mid-sentence newlines, collapse
    runs of spaces, keep paragraph breaks."""
    text = _HYPHEN_BREAK.sub(r"\1\2", text)
    text = _LINE_WRAP.sub(" ", text)
    lines = [" ".join(ln.split()) for ln in text.split("\n")]
    return "\n".join(ln for ln in lines if ln).strip()


_PDF_STREAM = re.compile(rb"stream\r?\n(.*?)endstream", re.S)
_PDF_TEXT_OP = re.compile(
    rb"\((?:[^()\\]|\\.)*\)\s*Tj|\[((?:[^\[\]\\]|\\.)*)\]\s*TJ", re.S
)
_PDF_STR = re.compile(rb"\((?:[^()\\]|\\.)*\)", re.S)


def _pdf_unescape(raw: bytes) -> str:
    out = []
    i = 0
    while i < len(raw):
        c = raw[i : i + 1]
        if c == b"\\" and i + 1 < len(raw):
            nxt = raw[i + 1 : i + 2]
            mapped = {
                b"n": "\n", b"r": "\r", b"t": "\t",
                b"(": "(", b")": ")", b"\\": "\\",
            }.get(nxt)
            if mapped is not None:
                out.append(mapped)
                i += 2
                continue
            if nxt in b"01234567":  # octal escape: 1-3 octal digits
                j = 1
                while j < 3 and raw[i + 1 + j : i + 2 + j] in (
                    b"0", b"1", b"2", b"3", b"4", b"5", b"6", b"7",
                ):
                    j += 1
                out.append(chr(int(raw[i + 1 : i + 1 + j], 8) & 0xFF))
                i += 1 + j
                continue
            # unknown escape (incl. \8, \9): backslash is dropped
            out.append(nxt.decode("latin-1"))
            i += 2
            continue
        out.append(c.decode("latin-1"))
        i += 1
    return "".join(out)


def extract_pdf_text_builtin(contents: bytes) -> List[str]:
    """Minimal text extraction for simple PDFs: inflate Flate streams,
    read Tj/TJ show-text operators per content stream.  Covers plain
    generator output (our test fixtures, simple exports); complex
    encodings need pypdf."""
    import zlib

    pages: List[str] = []
    for m in _PDF_STREAM.finditer(contents):
        data = m.group(1)
        if b"Tj" not in data and b"TJ" not in data:
            try:
                data = zlib.decompress(data)
            except Exception:  # noqa: BLE001 — not Flate / not text
                continue
        if b"Tj" not in data and b"TJ" not in data:
            continue
        parts: List[str] = []
        for op in _PDF_TEXT_OP.finditer(data):
            if op.group(1) is not None:  # TJ array: strings + kern numbers
                for s in _PDF_STR.finditer(op.group(1)):
                    parts.append(_pdf_unescape(s.group(0)[1:-1]))
            else:
                s = _PDF_STR.search(op.group(0))
                if s:
                    parts.append(_pdf_unescape(s.group(0)[1:-1]))
        if parts:
            pages.append("\n".join(parts))
    return pages


class PypdfParser(UDF):
    """reference: parsers.py PypdfParser:1019-1093 — pypdf extraction +
    cleanup pass; built-in extractor for simple PDFs when pypdf is
    absent."""

    def __init__(self, apply_text_cleanup: bool = True):
        super().__init__(return_type=list, deterministic=True)
        self.apply_text_cleanup = apply_text_cleanup

        def parse(contents: bytes) -> list:
            texts = self._extract(contents)
            out = []
            for i, text in enumerate(texts):
                if self.apply_text_cleanup:
                    text = clean_pdf_text(text)
                out.append((text, {"page": i}))
            return out

        self.func = parse

    def _extract(self, contents: bytes) -> List[str]:
        try:
            import io

            from pypdf import PdfReader
        except ImportError:
            return extract_pdf_text_builtin(contents)
        reader = PdfReader(io.BytesIO(contents))
        return [page.extract_text() or "" for page in reader.pages]


class DoclingParser(UDF):
    """reference: parsers.py DoclingParser:334-672 — requires docling
    (genuinely gated: the import is attempted at parse time)."""

    def __init__(self, chunk: bool = True, **converter_kwargs):
        super().__init__(return_type=list, deterministic=True)
        self.chunk = chunk
        self.converter_kwargs = converter_kwargs

        def parse(contents: bytes) -> list:
            try:
                from docling.document_converter import DocumentConverter
            except ImportError as exc:
                raise ImportError(
                    "DoclingParser requires the docling package"
                ) from exc
            import io

            converter = DocumentConverter(**self.converter_kwargs)
            result = converter.convert(io.BytesIO(contents))
            doc = result.document
            if self.chunk:
                try:
                    from docling.chunking import HybridChunker

                    chunks = HybridChunker().chunk(doc)
                    return [
                        (c.text, dict(getattr(c, "meta", {}) or {}))
                        for c in chunks
                    ]
                except ImportError:
                    pass
            return [(doc.export_to_markdown(), {})]

        self.func = parse


class ImageParser(UDF):
    """reference: parsers.py ImageParser:676 — vision-LLM description of
    images. The image decodes via PIL (dimensions/format land in the chunk
    metadata); the text is the configured LLM's description of the
    base64-encoded image, so any chat wrapper with vision support (or a
    test fake) plugs in."""

    def __init__(self, llm=None, prompt: str | None = None, **kwargs):
        super().__init__(return_type=list, deterministic=False)
        self.llm = llm
        self.prompt = prompt or "Describe this image."

        def parse(contents: bytes) -> list:
            import base64
            import io

            meta: dict = {}
            mime = "image/png"
            try:
                from PIL import Image

                with Image.open(io.BytesIO(contents)) as img:
                    meta = {
                        "width": img.width,
                        "height": img.height,
                        "format": img.format,
                    }
                    if img.format:
                        mime = f"image/{img.format.lower()}"
            except Exception:  # noqa: BLE001 — undecodable: still try llm
                pass
            if self.llm is None:
                raise ValueError(
                    "ImageParser needs llm= (a vision-capable chat wrapper)"
                )
            b64 = base64.b64encode(contents).decode()
            messages = [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": self.prompt},
                        {
                            "type": "image_url",
                            "image_url": {
                                "url": f"data:{mime};base64,{b64}"
                            },
                        },
                    ],
                }
            ]
            text = self.llm.func(messages)
            if inspect.isawaitable(text):
                import asyncio
                import concurrent.futures

                try:
                    asyncio.get_running_loop()
                except RuntimeError:
                    text = asyncio.run(text)
                else:
                    with concurrent.futures.ThreadPoolExecutor(1) as pool:
                        text = pool.submit(asyncio.run, text).result()
            return [(text, meta)]

        self.func = parse


class SlideParser(ImageParser):
    """reference: parsers.py SlideParser:830."""
