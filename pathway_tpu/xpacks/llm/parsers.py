"""Document parsers (reference: python/pathway/xpacks/llm/parsers.py).

Parsers are UDFs `bytes -> list[tuple[str, dict]]` (text, metadata). The
Utf8 path is native; heavyweight parsers (unstructured, docling, vision
LLMs) stay host-side and gate on their optional packages, as in the
reference."""

from __future__ import annotations

import inspect
from typing import Any, List, Tuple

from pathway_tpu.internals.udfs import UDF


class Utf8Parser(UDF):
    """reference: parsers.py Utf8Parser:48."""

    def __init__(self):
        # batched: one Python call per engine batch, not per document —
        # this parser sits on the bulk-ingest hot path (SURVEY §3.4)
        super().__init__(
            return_type=list, deterministic=True, max_batch_size=65536
        )

        def parse(contents_batch: list) -> list:
            out = []
            for contents in contents_batch:
                if isinstance(contents, str):
                    text = contents
                else:
                    text = contents.decode("utf-8", errors="replace")
                out.append([(text, {})])
            return out

        self.func = parse


# kept name from older reference versions
ParseUtf8 = Utf8Parser


class PypdfParser(UDF):
    """reference: parsers.py PypdfParser:1019 — requires pypdf."""

    def __init__(self, apply_text_cleanup: bool = True):
        super().__init__(return_type=list, deterministic=True)
        self.apply_text_cleanup = apply_text_cleanup

        def parse(contents: bytes) -> list:
            try:
                import io

                from pypdf import PdfReader
            except ImportError as exc:
                raise ImportError(
                    "PypdfParser requires the pypdf package"
                ) from exc
            reader = PdfReader(io.BytesIO(contents))
            out = []
            for i, page in enumerate(reader.pages):
                text = page.extract_text() or ""
                if self.apply_text_cleanup:
                    text = " ".join(text.split())
                out.append((text, {"page": i}))
            return out

        self.func = parse


class UnstructuredParser(UDF):
    """reference: parsers.py UnstructuredParser:87 — requires
    unstructured."""

    def __init__(
        self,
        mode: str = "single",
        post_processors: list | None = None,
        **unstructured_kwargs,
    ):
        super().__init__(return_type=list, deterministic=True)
        self.mode = mode
        self.kwargs = unstructured_kwargs

        def parse(contents: bytes) -> list:
            try:
                from unstructured.partition.auto import partition
            except ImportError as exc:
                raise ImportError(
                    "UnstructuredParser requires the unstructured package"
                ) from exc
            import io

            elements = partition(file=io.BytesIO(contents), **self.kwargs)
            if self.mode == "single":
                return [("\n\n".join(str(e) for e in elements), {})]
            return [
                (str(e), getattr(e, "metadata", None).to_dict() if getattr(e, "metadata", None) else {})
                for e in elements
            ]

        self.func = parse


class DoclingParser(UDF):
    """reference: parsers.py DoclingParser:334 — requires docling."""

    def __init__(self, **kwargs):
        super().__init__(return_type=list, deterministic=True)

        def parse(contents: bytes) -> list:
            raise ImportError("DoclingParser requires the docling package")

        self.func = parse


class ImageParser(UDF):
    """reference: parsers.py ImageParser:676 — vision-LLM description of
    images. The image decodes via PIL (dimensions/format land in the chunk
    metadata); the text is the configured LLM's description of the
    base64-encoded image, so any chat wrapper with vision support (or a
    test fake) plugs in."""

    def __init__(self, llm=None, prompt: str | None = None, **kwargs):
        super().__init__(return_type=list, deterministic=False)
        self.llm = llm
        self.prompt = prompt or "Describe this image."

        def parse(contents: bytes) -> list:
            import base64
            import io

            meta: dict = {}
            mime = "image/png"
            try:
                from PIL import Image

                with Image.open(io.BytesIO(contents)) as img:
                    meta = {
                        "width": img.width,
                        "height": img.height,
                        "format": img.format,
                    }
                    if img.format:
                        mime = f"image/{img.format.lower()}"
            except Exception:  # noqa: BLE001 — undecodable: still try llm
                pass
            if self.llm is None:
                raise ValueError(
                    "ImageParser needs llm= (a vision-capable chat wrapper)"
                )
            b64 = base64.b64encode(contents).decode()
            messages = [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": self.prompt},
                        {
                            "type": "image_url",
                            "image_url": {
                                "url": f"data:{mime};base64,{b64}"
                            },
                        },
                    ],
                }
            ]
            text = self.llm.func(messages)
            if inspect.isawaitable(text):
                import asyncio
                import concurrent.futures

                try:
                    asyncio.get_running_loop()
                except RuntimeError:
                    text = asyncio.run(text)
                else:
                    with concurrent.futures.ThreadPoolExecutor(1) as pool:
                        text = pool.submit(asyncio.run, text).result()
            return [(text, meta)]

        self.func = parse


class SlideParser(ImageParser):
    """reference: parsers.py SlideParser:830."""
