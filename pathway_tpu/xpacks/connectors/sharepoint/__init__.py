"""SharePoint file source (reference:
python/pathway/xpacks/connectors/sharepoint/__init__.py read:255 —
certificate-authenticated Office365 client, polling scanner with
modify/delete detection, binary `data` column + optional `_metadata`).

The Office365 client is optional and injectable: production passes
tenant/client_id/cert credentials (requires Office365-REST-Python-Client),
tests inject `_client_factory` returning any object with
`list_files(root_path, recursive) -> [(path, modified_at, created_at,
size)]` and `download(path) -> bytes`."""

from __future__ import annotations

import time as time_mod
from typing import Any, Callable, Dict, Optional, Tuple

from pathway_tpu.engine.value import Json
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.io._connector_runtime import (
    ConnectorSubjectBase,
    connector_table,
)


class _Office365Client:
    """Thin adapter over Office365-REST-Python-Client (gated)."""

    def __init__(self, url, tenant, client_id, thumbprint, cert_path):
        try:
            from office365.sharepoint.client_context import (  # type: ignore
                ClientContext,
            )
        except ImportError as exc:
            raise ImportError(
                "pw.xpacks.connectors.sharepoint requires "
                "Office365-REST-Python-Client; install it or inject "
                "_client_factory"
            ) from exc
        self._ctx = ClientContext(url).with_client_certificate(
            tenant=tenant,
            client_id=client_id,
            thumbprint=thumbprint,
            cert_path=cert_path,
        )

    def list_files(self, root_path: str, recursive: bool):
        folder = self._ctx.web.get_folder_by_server_relative_url(root_path)
        out = []
        stack = [folder]
        while stack:
            current = stack.pop()
            self._ctx.load(current.files)
            self._ctx.load(current.folders)
            self._ctx.execute_query()
            for f in current.files:
                out.append(
                    (
                        f.serverRelativeUrl,
                        f.time_last_modified.timestamp(),
                        f.time_created.timestamp(),
                        f.length,
                    )
                )
            if recursive:
                stack.extend(list(current.folders))
        return out

    def download(self, path: str) -> bytes:
        import io

        buf = io.BytesIO()
        (
            self._ctx.web.get_file_by_server_relative_url(path)
            .download(buf)
            .execute_query()
        )
        return buf.getvalue()


class _SharePointSubject(ConnectorSubjectBase):
    def __init__(
        self,
        client_factory: Callable[[], Any],
        root_path: str,
        *,
        mode: str,
        recursive: bool,
        with_metadata: bool,
        object_size_limit: int | None,
        refresh_interval: float,
        max_failed_attempts_in_row: int | None,
    ):
        super().__init__()
        self.client_factory = client_factory
        self.root_path = root_path
        self.mode = mode
        self.recursive = recursive
        self.with_metadata = with_metadata
        self.object_size_limit = object_size_limit
        self.refresh_interval = refresh_interval
        self.max_failed = max_failed_attempts_in_row
        # path -> (modified_at, row) for update/delete detection
        self._seen: Dict[str, Tuple[float, dict]] = {}

    def _row(self, payload: bytes, path: str, modified, created) -> dict:
        row = {"data": payload}
        if self.with_metadata:
            row["_metadata"] = Json(
                {
                    "path": path,
                    "modified_at": int(modified),
                    "created_at": int(created),
                    "size": len(payload),
                }
            )
        return row

    def run(self) -> None:
        client = self.client_factory()
        failures = 0
        while True:
            try:
                listing = client.list_files(self.root_path, self.recursive)
                failures = 0
            except Exception:  # noqa: BLE001
                failures += 1
                if self.max_failed is not None and failures >= self.max_failed:
                    raise
                time_mod.sleep(self.refresh_interval)
                continue
            current_paths = set()
            for path, modified, created, size in listing:
                current_paths.add(path)
                if (
                    self.object_size_limit is not None
                    and size > self.object_size_limit
                ):
                    continue
                prev = self._seen.get(path)
                if prev is not None and prev[0] == modified:
                    continue
                cache = self._object_cache
                payload = (
                    cache.get(path, modified) if cache is not None else None
                )
                if payload is None:
                    payload = client.download(path)
                    if cache is not None:
                        cache.put(path, modified, payload)
                row = self._row(payload, path, modified, created)
                if prev is not None:
                    self._remove(prev[1])
                self.next(**row)
                self._seen[path] = (modified, row)
            for path in list(self._seen):
                if path not in current_paths:
                    _mtime, row = self._seen.pop(path)
                    self._remove(row)
                    if self._object_cache is not None:
                        self._object_cache.evict(path)
            self.commit()
            if self.mode == "static":
                return
            time_mod.sleep(self.refresh_interval)

    def _persisted_state(self):
        # the full rows persist (payload included): retracting a modified/
        # deleted file after a restart needs the OLD row's values, exactly
        # why the reference caches source objects for recovery
        # (src/persistence/cached_object_storage.rs)
        return {"seen": dict(self._seen)}

    def _restore_persisted_state(self, state) -> None:
        if not state:
            return
        if "seen" in state:
            self._seen.update(state["seen"])
        elif "seen_mtimes" in state:
            # legacy cursor (mtimes only): keep it so unchanged files are
            # not re-downloaded/re-emitted on top of the snapshot replay;
            # the known limitation is that a file modified later cannot
            # retract its pre-upgrade row (no cached payload)
            for p, m in state["seen_mtimes"].items():
                self._seen.setdefault(p, (m, {}))


def read(
    url: str = "",
    *,
    tenant: str = "",
    client_id: str = "",
    cert_path: str = "",
    thumbprint: str = "",
    root_path: str,
    mode: str = "streaming",
    recursive: bool = True,
    object_size_limit: int | None = None,
    with_metadata: bool = False,
    refresh_interval: float = 30,
    max_failed_attempts_in_row: int | None = 8,
    _client_factory: Callable[[], Any] | None = None,
    name: str | None = None,
):
    """reference: sharepoint/__init__.py read:255 (binary `data` column,
    optional `_metadata`)."""
    if _client_factory is None:
        def _client_factory():
            return _Office365Client(
                url, tenant, client_id, thumbprint, cert_path
            )

    schema_cols: dict = {"data": bytes}
    if with_metadata:
        schema_cols["_metadata"] = Json
    schema = schema_from_types(**schema_cols)

    def factory():
        return _SharePointSubject(
            _client_factory,
            root_path,
            mode=mode,
            recursive=recursive,
            with_metadata=with_metadata,
            object_size_limit=object_size_limit,
            refresh_interval=refresh_interval,
            max_failed_attempts_in_row=max_failed_attempts_in_row,
        )

    return connector_table(
        schema,
        factory,
        mode=mode,
        # site url + path: two sites sharing a root_path must not share a
        # persistence scope (object cache / input snapshots)
        name=name or f"sharepoint_{url}_{root_path}",
        exclusive=True
    )
