"""Pure-JAX transformer: encoder (bidirectional) and decoder (causal), one
parameterization.

This is the data-plane model the LLM xpack runs on TPU — the counterpart of
the reference's torch models behind SentenceTransformerEmbedder
(xpacks/llm/embedders.py:342), CrossEncoderReranker (rerankers.py:163) and
HFPipelineChat (llms.py:456).

TPU-first choices:
  * bf16 activations/matmuls (MXU native), f32 params + layernorm stats;
  * static shapes everywhere — batches arrive bucketed from the tokenizer;
  * tensor parallel over heads/mlp via PartitionSpecs on a ("dp","tp") mesh
    (param_sharding_rules); batch (dp) sharding on inputs. XLA inserts the
    all-reduces after attention out-proj / mlp down-proj;
  * decode uses a KV cache carried as an explicit pytree through lax.scan.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30522
    hidden: int = 384
    layers: int = 6
    heads: int = 12
    mlp_dim: int = 1536
    max_len: int = 512
    causal: bool = False
    pooling: str = "mean"  # mean | cls | none
    dtype: str = "bfloat16"
    # "pre" = GPT-style pre-LN (default, trains stably from scratch);
    # "post" = BERT/MiniLM layout (embedding LayerNorm, residual-then-LN,
    # erf GELU) — required for loading real HF encoder checkpoints
    norm_style: str = "pre"

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


# MiniLM-L6-class config (the reference's default embedder model family)
MINILM_L6 = TransformerConfig(
    vocab_size=30522, hidden=384, layers=6, heads=12, mlp_dim=1536
)

# Mistral-7B-class geometry (the reference's Private-RAG HFPipelineChat
# target, llms.py:456); instantiate smaller variants for tests
MISTRAL_7B = TransformerConfig(
    vocab_size=32000,
    hidden=4096,
    layers=32,
    heads=32,
    mlp_dim=14336,
    max_len=4096,
    causal=True,
    pooling="none",
)

TINY_DECODER = TransformerConfig(
    vocab_size=1024,
    hidden=64,
    layers=2,
    heads=4,
    mlp_dim=128,
    max_len=128,
    causal=True,
    pooling="none",
)


def init_params(rng, config: TransformerConfig) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    h, mlp, v = config.hidden, config.mlp_dim, config.vocab_size
    keys = jax.random.split(rng, 4 + config.layers)
    scale = 0.02

    def dense(key, shape):
        return jax.random.normal(key, shape, dtype=jnp.float32) * scale

    params: Dict[str, Any] = {
        "embed": dense(keys[0], (v, h)),
        "pos_embed": dense(keys[1], (config.max_len, h)),
        "ln_f": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
        "layers": [],
    }
    for i in range(config.layers):
        k = jax.random.split(keys[4 + i], 6)
        params["layers"].append(
            {
                "ln1": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
                "ln2": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
                "qkv": dense(k[0], (h, 3 * h)),
                "qkv_b": jnp.zeros((3 * h,)),
                "out": dense(k[1], (h, h)),
                "out_b": jnp.zeros((h,)),
                "up": dense(k[2], (h, mlp)),
                "up_b": jnp.zeros((mlp,)),
                "down": dense(k[3], (mlp, h)),
                "down_b": jnp.zeros((h,)),
            }
        )
    return params


def param_sharding_rules(config: TransformerConfig, mesh) -> Dict[str, Any]:
    """PartitionSpecs for tensor parallelism on the mesh's 'tp' axis:
    qkv/up column-sharded, out/down row-sharded (Megatron-style), embeddings
    vocab-sharded. Scaling-book recipe: annotate, let XLA place collectives."""
    from jax.sharding import PartitionSpec as P

    tp = "tp" if "tp" in mesh.axis_names else None
    rules = {
        "embed": P(tp, None),
        "pos_embed": P(None, None),
        "ln_f": {"scale": P(None), "bias": P(None)},
        "layers": [
            {
                "ln1": {"scale": P(None), "bias": P(None)},
                "ln2": {"scale": P(None), "bias": P(None)},
                "qkv": P(None, tp),
                "qkv_b": P(tp),
                "out": P(tp, None),
                "out_b": P(None),
                "up": P(None, tp),
                "up_b": P(tp),
                "down": P(tp, None),
                "down_b": P(None),
            }
            for _ in range(config.layers)
        ],
    }
    return rules


def _layer_norm(x, scale, bias, eps=1e-6):
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    out = (x32 - mean) * (1.0 / jnp.sqrt(var + eps))
    return (out * scale + bias).astype(x.dtype)


def _attention(q, k, v, mask, causal: bool, use_flash):
    """Dispatch between the Pallas flash kernel (TPU; O(L) memory) and the
    dense XLA path. q,k,v: [B,H,L,D]; mask: [B,L]."""
    import jax
    import jax.numpy as jnp

    if use_flash is None:
        # flash wins where O(L^2) score materialization hurts; at short L
        # the dense MXU path is ~2x faster (measured: L=64 MiniLM batch,
        # 20.6k vs 9.4k docs/s on v5e) and Mosaic small-block tiling is
        # untested territory — so gate flash to long sequences
        use_flash = jax.default_backend() == "tpu" and q.shape[2] > 256
    if use_flash:
        from pathway_tpu.ops.kernels import flash_attention

        return flash_attention(q, k, v, mask, causal=causal)

    # dense path shares the flash kernel's numerical definition (it is also
    # the kernel's custom_vjp backward), so the two can't drift apart
    from pathway_tpu.ops.kernels.flash_attention import _reference_attention

    return _reference_attention(
        q, k, v, mask, 1.0 / np.sqrt(q.shape[3]), causal
    )


def _segment_attention(q, k, v, seg, sm_scale):
    """Dense attention with a pairwise same-segment mask for packed
    ragged batches. q,k,v: [B,H,L,D]; seg: [B,L] int32, 1..S per packed
    document, 0 = padding. Mirrors `_reference_attention`'s numerics
    (f32 scores, NEG_INF additive mask, +1e-30 softmax denominator) so a
    doc packed with neighbors attends over exactly the tokens it would
    see alone. At packed slab lengths (<=512) the O(L^2) scores are the
    dense-MXU regime where flash loses (see `_attention`'s measured
    gate), so no Pallas variant is needed. Pad rows produce finite
    garbage that per-segment pooling never reads."""
    import jax.numpy as jnp

    from pathway_tpu.ops.kernels.flash_attention import NEG_INF

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    same = (seg[:, None, :, None] == seg[:, None, None, :]) & (
        seg[:, None, :, None] > 0
    )
    s = jnp.where(same, s, NEG_INF)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / (p.sum(-1, keepdims=True) + 1e-30)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _packed_positions(seg):
    """Per-token positions that RESTART at every segment boundary, so a
    packed doc reads the same pos_embed rows it would alone. Computed on
    device from seg (no third wire upload): a token starts a segment
    where seg differs from its left neighbor; cummax propagates each
    segment's start index rightward."""
    import jax
    import jax.numpy as jnp

    l = seg.shape[1]
    pos = jnp.broadcast_to(jnp.arange(l, dtype=jnp.int32)[None, :], seg.shape)
    is_start = jnp.concatenate(
        [jnp.ones_like(seg[:, :1], dtype=bool), seg[:, 1:] != seg[:, :-1]],
        axis=1,
    )
    seg_start = jax.lax.cummax(jnp.where(is_start, pos, 0), axis=1)
    return pos - seg_start


def forward(
    params,
    config: TransformerConfig,
    ids,
    mask,
    *,
    return_hidden: bool = False,
    use_flash: Optional[bool] = None,
    seg=None,
    max_segments: int = 0,
):
    """Encoder/decoder forward. ids, mask: [B, L] int32. Returns pooled
    embeddings [B, H] (pooling != none), else logits [B, L, V].

    Packed mode (seg is not None): rows hold several concatenated docs
    distinguished by segment ids; attention is confined within segments,
    positions restart per segment, and pooling returns [B, max_segments,
    H] — one L2-normalized vector per packed doc slot. mask is ignored
    (seg > 0 is the validity mask); causal packed decode is unsupported."""
    import jax
    import jax.numpy as jnp

    compute_dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    post_ln = config.norm_style == "post"
    b, l = ids.shape
    if seg is not None:
        if config.causal:
            raise ValueError("packed segment batching requires a bidirectional encoder")
        pos = _packed_positions(seg)
        x = params["embed"][ids] + params["pos_embed"][pos]
    else:
        x = params["embed"][ids] + params["pos_embed"][:l][None, :, :]
    if post_ln and "type_embed" in params:
        x = x + params["type_embed"][0][None, None, :]
    if post_ln and "embed_ln" in params:
        x = _layer_norm(
            x, params["embed_ln"]["scale"], params["embed_ln"]["bias"],
            eps=1e-12,
        )
    x = x.astype(compute_dtype)
    eps = 1e-12 if post_ln else 1e-6

    heads, hd = config.heads, config.head_dim
    for layer in params["layers"]:
        if post_ln:
            y = x  # BERT: attention reads the residual stream directly
        else:
            y = _layer_norm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        qkv = (
            y @ layer["qkv"].astype(compute_dtype)
            + layer["qkv_b"].astype(compute_dtype)
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, l, heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, l, heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, l, heads, hd).transpose(0, 2, 1, 3)
        if seg is not None:
            ctx = _segment_attention(q, k, v, seg, 1.0 / np.sqrt(hd))
        else:
            ctx = _attention(q, k, v, mask, config.causal, use_flash)
        ctx = ctx.astype(compute_dtype)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, l, config.hidden)
        attn_out = (
            ctx @ layer["out"].astype(compute_dtype)
            + layer["out_b"].astype(compute_dtype)
        )
        if post_ln:
            x = _layer_norm(
                x + attn_out, layer["ln1"]["scale"], layer["ln1"]["bias"],
                eps=eps,
            ).astype(compute_dtype)
            y = x
        else:
            x = x + attn_out
            y = _layer_norm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
        y = (
            y @ layer["up"].astype(compute_dtype)
            + layer["up_b"].astype(compute_dtype)
        )
        if post_ln:
            # exact erf GELU (BERT convention), in f32 for checkpoint parity
            y32 = y.astype(jnp.float32)
            y = (y32 * 0.5 * (1.0 + jax.scipy.special.erf(
                y32 * 0.7071067811865476
            ))).astype(compute_dtype)
        else:
            y = y * 0.5 * (
                1.0 + jnp.tanh(0.7978845608 * (y + 0.044715 * y**3))
            )
        mlp_out = (
            y @ layer["down"].astype(compute_dtype)
            + layer["down_b"].astype(compute_dtype)
        )
        if post_ln:
            x = _layer_norm(
                x + mlp_out, layer["ln2"]["scale"], layer["ln2"]["bias"],
                eps=eps,
            ).astype(compute_dtype)
        else:
            x = x + mlp_out

    if not post_ln:
        x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if return_hidden or config.pooling == "none":
        logits = jnp.einsum(
            "blh,vh->blv", x.astype(jnp.float32), params["embed"]
        )
        return logits
    if seg is not None:
        # per-segment mean pooling: one-hot the segment ids and contract
        # the token axis on the MXU — [B, L, H] x [B, L, S] -> [B, S, H].
        # Same dtype discipline as the classic branch (sum in x.dtype,
        # normalize in f32); empty slots pool to the zero vector.
        oh = (
            seg[:, :, None] == jnp.arange(1, max_segments + 1)[None, None, :]
        ).astype(x.dtype)
        pooled = jnp.einsum("blh,bls->bsh", x, oh) / (
            oh.sum(axis=1)[:, :, None] + 1e-9
        )
    elif config.pooling == "cls":
        pooled = x[:, 0, :]
    else:  # mean over valid tokens
        m = mask[:, :, None].astype(x.dtype)
        pooled = (x * m).sum(1) / (m.sum(1) + 1e-9)
    # L2-normalize (SentenceTransformer convention)
    pooled = pooled.astype(jnp.float32)
    pooled = pooled / (
        jnp.linalg.norm(pooled, axis=-1, keepdims=True) + 1e-9
    )
    return pooled


class TransformerLM:
    """Bundles config+params with jitted entry points."""

    def __init__(self, config: TransformerConfig, params=None, seed: int = 0):
        import jax

        self.config = config
        if params is None:
            params = init_params(jax.random.PRNGKey(seed), config)
        self.params = params

        def _fwd(params, ids, mask):
            # narrow wire dtypes (tokenizer._wire_dtype policy) upcast on
            # device: behind a tunneled chip the token upload is
            # bandwidth-bound and 16-bit ids/mask halve it vs int32
            import jax.numpy as jnp

            return forward(
                params,
                config=self.config,
                ids=ids.astype(jnp.int32),
                mask=mask.astype(jnp.int32),
            )

        self._encode_jit = jax.jit(_fwd)

        def _fwd_packed(params, ids, seg, max_segments):
            import jax.numpy as jnp

            return forward(
                params,
                config=self.config,
                ids=ids.astype(jnp.int32),
                mask=None,
                seg=seg.astype(jnp.int32),
                max_segments=max_segments,
            )

        # max_segments is a static one-hot width; callers pass a fixed
        # constant (tokenizer.PACK_MAX_SEGMENTS) so there is one compile
        # per (R, L) slab shape, same cache discipline as the classic path
        self._packed_jit = jax.jit(_fwd_packed, static_argnums=(3,))
        self._mesh_params: tuple | None = None

    def mesh_params(self, mesh):
        """Tensor-parallel copy of the weights for a mesh backend: each
        array device_put once under the `param_sharding_rules` partition
        specs (qkv/up column-, out/down row-sharded on 'tp'), cached per
        mesh. `self.params` — and every caller that doesn't opt in via
        the `params=` override — keeps its exact single-device layout."""
        cached = self._mesh_params
        if cached is not None and cached[0] is mesh:
            return cached[1]
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        rules = param_sharding_rules(self.config, mesh)
        shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec),
            rules,
            is_leaf=lambda x: isinstance(x, P),
        )
        placed = jax.device_put(self.params, shardings)
        self._mesh_params = (mesh, placed)
        return placed

    def encode_packed(self, ids, seg, max_segments: int, *, params=None):
        """Packed ragged encode: ids/seg from tokenizer.pack_batch (wire
        dtypes; upcast on device). Returns [R, max_segments, H] pooled
        L2-normalized vectors; empty slots are zero. Inputs are NOT
        donated — the device-side int upcast changes the buffer dtype, so
        XLA could never reuse them and would warn on every dispatch."""
        return self._packed_jit(
            self.params if params is None else params,
            ids,
            seg,
            int(max_segments),
        )

    def __call__(self, ids, mask, *, params=None):
        # ids/mask arrive already wire-narrowed by encode_batch (tokenizer
        # _wire_dtype is the single policy); no host casts here — a cast
        # would pull mesh-sharded inputs back to host and destroy their
        # NamedSharding placement
        return self._encode_jit(
            self.params if params is None else params, ids=ids, mask=mask
        )

    # -- greedy generation (decoder) --------------------------------------
    def generate(self, ids: np.ndarray, mask: np.ndarray, max_new_tokens: int = 16):
        """Greedy decode; recomputes the prefix each step (fine for the
        test-scale decoder; a KV-cached lax.scan path is the optimization
        target for the Private-RAG config)."""
        import jax.numpy as jnp

        ids = np.asarray(ids)
        mask = np.asarray(mask)
        max_len = self.config.max_len
        if ids.shape[1] > max_len:
            ids = ids[:, :max_len]
            mask = mask[:, :max_len]
        out_tokens = []
        for _ in range(max_new_tokens):
            logits = self._encode_jit(self.params, ids=ids, mask=mask)
            lengths = mask.sum(axis=1) - 1
            last = np.asarray(logits)[
                np.arange(ids.shape[0]), lengths, :
            ]
            nxt = last.argmax(-1).astype(np.int32)
            out_tokens.append(nxt)
            b, l = ids.shape
            if (lengths + 1 >= l).any():
                if l >= max_len:
                    # context window exhausted — positional table is the
                    # hard ceiling; stop rather than overflow pos_embed
                    break
                grow = min(l, max_len - l)
                ids = np.concatenate(
                    [ids, np.zeros((b, grow), dtype=ids.dtype)], axis=1
                )
                mask = np.concatenate(
                    [mask, np.zeros((b, grow), dtype=mask.dtype)], axis=1
                )
            ids[np.arange(b), lengths + 1] = nxt
            mask[np.arange(b), lengths + 1] = 1
        return np.stack(out_tokens, axis=1)
