"""Decoder-only LM, Mistral-7B-class architecture, TPU-first.

Replaces the reference's local torch pipeline (xpacks/llm/llms.py
HFPipelineChat:456) with an in-tree JAX decoder: GQA (8 kv heads vs 32 q
heads), RoPE, RMSNorm, SwiGLU — the Mistral-7B recipe — with

  * prefill via the Pallas flash-attention kernel (causal, O(L) memory);
  * a preallocated, donated KV cache ([B, kv_heads, max_len, hd] per layer)
    updated in place with lax.dynamic_update_slice;
  * the whole generation loop as ONE jit (lax.scan over steps): no host
    round trip per token, greedy or temperature sampling on device;
  * Megatron tensor-parallel PartitionSpecs (q/k/v/gate/up column-sharded,
    o/down row-sharded, cache sharded over kv heads on 'tp').
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    layers: int = 32
    q_heads: int = 32
    kv_heads: int = 8
    mlp_dim: int = 14336
    max_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.hidden // self.q_heads


MISTRAL_7B_DECODER = DecoderConfig()

TINY = DecoderConfig(
    vocab_size=1024, hidden=64, layers=2, q_heads=4, kv_heads=2,
    mlp_dim=128, max_len=128, dtype="float32",
)


def init_decoder_params(rng, config: DecoderConfig) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    h, hd = config.hidden, config.head_dim
    kv_dim = config.kv_heads * hd
    keys = jax.random.split(rng, 2 + config.layers)
    scale = 0.02
    # store params in the config dtype: a 7B-class config in bf16 is
    # 14 GB and fits a single v5e; float32 storage would not (the
    # forward already computes in config.dtype either way)
    param_dtype = (
        jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    )

    def dense(key, shape):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32) * scale
        ).astype(param_dtype)

    params: Dict[str, Any] = {
        "embed": dense(keys[0], (config.vocab_size, h)),
        "ln_f": jnp.ones((h,)),
        "layers": [],
    }
    for i in range(config.layers):
        k = jax.random.split(keys[2 + i], 7)
        params["layers"].append(
            {
                "ln1": jnp.ones((h,)),
                "ln2": jnp.ones((h,)),
                "wq": dense(k[0], (h, h)),
                "wk": dense(k[1], (h, kv_dim)),
                "wv": dense(k[2], (h, kv_dim)),
                "wo": dense(k[3], (h, h)),
                "gate": dense(k[4], (h, config.mlp_dim)),
                "up": dense(k[5], (h, config.mlp_dim)),
                "down": dense(k[6], (config.mlp_dim, h)),
            }
        )
    return params


def decoder_sharding_rules(config: DecoderConfig, mesh):
    """Megatron TP specs on the mesh's 'tp' axis."""
    from jax.sharding import PartitionSpec as P

    tp = "tp" if "tp" in mesh.axis_names else None
    layer = {
        "ln1": P(None),
        "ln2": P(None),
        "wq": P(None, tp),
        "wk": P(None, tp),
        "wv": P(None, tp),
        "wo": P(tp, None),
        "gate": P(None, tp),
        "up": P(None, tp),
        "down": P(tp, None),
    }
    return {
        "embed": P(tp, None),
        "ln_f": P(None),
        "layers": [dict(layer) for _ in range(config.layers)],
    }


def _rms_norm(x, scale, eps):
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * (1.0 / jnp.sqrt(var + eps)) * scale).astype(x.dtype)


def _rope(x, positions, theta):
    """x: [B, H, L, D]; positions: [B, L] absolute token positions."""
    import jax.numpy as jnp

    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, None, :, None].astype(jnp.float32) * freqs  # B,1,L,half
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


def _repeat_kv(x, n_rep: int):
    import jax.numpy as jnp

    if n_rep == 1:
        return x
    b, h, l, d = x.shape
    return jnp.broadcast_to(
        x[:, :, None, :, :], (b, h, n_rep, l, d)
    ).reshape(b, h * n_rep, l, d)


def init_kv_cache(config: DecoderConfig, batch: int):
    """Preallocated cache pytree: per layer {'k','v'} [B, KVH, max_len, hd]."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    shape = (batch, config.kv_heads, config.max_len, config.head_dim)
    return [
        {"k": jnp.zeros(shape, dtype=dtype), "v": jnp.zeros(shape, dtype=dtype)}
        for _ in range(config.layers)
    ]


def decoder_forward(params, config: DecoderConfig, ids, mask, *,
                    positions=None, kv_cache=None, kv_valid=None,
                    slot_offset=0, use_flash=None):
    """ids, mask: [B, L] (left-aligned prompts).

    Cacheless mode (kv_cache is None): plain causal attention over the
    batch (prefill-style scoring; flash kernel on TPU).

    Cache mode: writes this call's K/V into slots [slot_offset,
    slot_offset+L) of the preallocated cache and attends over every cache
    slot j with kv_valid[b, j] == 1 and j <= (slot_offset + query index) —
    slot order equals sequence order for left-aligned prompts, so slot
    causality is token causality. `positions` feeds RoPE with each row's
    true token position (ragged lengths ⇒ positions differ from slots
    during decode).

    Returns (logits [B, L, V] f32, new_cache).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    compute_dtype = (
        jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    )
    b, l = ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(l), (b, l))
    x = params["embed"][ids].astype(compute_dtype)
    qh, kvh, hd = config.q_heads, config.kv_heads, config.head_dim
    n_rep = qh // kvh
    new_cache = [] if kv_cache is not None else None

    is_prefill = (
        kv_cache is not None
        and isinstance(slot_offset, int)
        and slot_offset == 0
        and l > 1
    )
    if kv_cache is not None and not is_prefill:
        # [B, L, max_len] attention mask shared by all layers (decode /
        # chunked-prefill path; initial prefill uses the flash path below)
        slot_idx = jnp.arange(config.max_len)[None, None, :]
        q_slot = slot_offset + jnp.arange(l)[None, :, None]
        attend = (slot_idx <= q_slot) & (
            kv_valid[:, None, :].astype(bool)
        )

    for li, layer in enumerate(params["layers"]):
        y = _rms_norm(x, layer["ln1"], config.norm_eps)
        q = (y @ layer["wq"].astype(compute_dtype)).reshape(b, l, qh, hd)
        k = (y @ layer["wk"].astype(compute_dtype)).reshape(b, l, kvh, hd)
        v = (y @ layer["wv"].astype(compute_dtype)).reshape(b, l, kvh, hd)
        q = _rope(q.transpose(0, 2, 1, 3), positions, config.rope_theta)
        k = _rope(k.transpose(0, 2, 1, 3), positions, config.rope_theta)
        v = v.transpose(0, 2, 1, 3)

        if kv_cache is not None:
            ck = lax.dynamic_update_slice(
                kv_cache[li]["k"], k.astype(kv_cache[li]["k"].dtype),
                (0, 0, slot_offset, 0),
            )
            cv = lax.dynamic_update_slice(
                kv_cache[li]["v"], v.astype(kv_cache[li]["v"].dtype),
                (0, 0, slot_offset, 0),
            )
            new_cache.append({"k": ck, "v": cv})
            if is_prefill:
                # Prefill: no cache slots beyond this call's L can be
                # valid, so attention over the cache reduces to causal
                # attention over this call's own K/V (keys masked by
                # kv_valid's first L slots, per the cache-mode contract) —
                # O(L) flash path instead of a dense [B, H, L, max_len]
                # f32 score matrix.
                from pathway_tpu.models.transformer import _attention

                ctx = _attention(
                    q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep),
                    kv_valid[:, :l], True, use_flash,
                ).astype(compute_dtype)
            else:
                s = jnp.einsum(
                    "bhqd,bhkd->bhqk", q.astype(jnp.float32),
                    _repeat_kv(ck.astype(jnp.float32), n_rep),
                    preferred_element_type=jnp.float32,
                ) / np.sqrt(hd)
                s = jnp.where(attend[:, None, :, :], s, -1e30)
                p = jnp.exp(s - s.max(-1, keepdims=True))
                p = p / (p.sum(-1, keepdims=True) + 1e-30)
                ctx = jnp.einsum(
                    "bhqk,bhkd->bhqd", p.astype(compute_dtype),
                    _repeat_kv(cv.astype(compute_dtype), n_rep),
                )
        else:
            from pathway_tpu.models.transformer import _attention

            ctx = _attention(
                q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep), mask,
                True, use_flash,
            ).astype(compute_dtype)

        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, l, config.hidden)
        x = x + ctx @ layer["wo"].astype(compute_dtype)
        y = _rms_norm(x, layer["ln2"], config.norm_eps)
        gate = y @ layer["gate"].astype(compute_dtype)
        up = y @ layer["up"].astype(compute_dtype)
        swish = gate * jax.nn.sigmoid(gate.astype(jnp.float32)).astype(
            compute_dtype
        )
        x = x + (swish * up) @ layer["down"].astype(compute_dtype)

    x = _rms_norm(x, params["ln_f"], config.norm_eps)
    # HF Llama/Mistral checkpoints ship an untied lm_head; fall back to
    # weight tying (our from-scratch init) when absent
    head = params.get("lm_head", params["embed"])
    logits = jnp.einsum("blh,vh->blv", x.astype(jnp.float32), head)
    return logits, new_cache


@functools.lru_cache(maxsize=None)
def _compiled_generate(config: DecoderConfig, max_new_tokens: int,
                       temperature: float):
    """One jit for prefill + scan-decode. Static: config, step count."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def sample(logit, key):
        if temperature == 0.0:
            return jnp.argmax(logit, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logit / temperature, axis=-1
        ).astype(jnp.int32)

    def generate(params, ids, mask, rng):
        b, l = ids.shape
        positions = jnp.cumsum(mask, axis=1) - 1
        lengths = mask.sum(axis=1)  # [B]
        cache = init_kv_cache(config, b)
        kv_valid = jnp.concatenate(
            [mask, jnp.zeros((b, config.max_len - l), dtype=mask.dtype)],
            axis=1,
        )
        first_key, scan_rng = jax.random.split(rng)
        # ---- prefill: write the prompt into the cache
        logits, cache = decoder_forward(
            params, config, ids, mask, positions=positions,
            kv_cache=cache, kv_valid=kv_valid, slot_offset=0,
        )
        last_logit = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0, :]  # [B, V]
        first = sample(last_logit, first_key)

        def step(carry, inp):
            cache, kv_valid, tok = carry
            t, key = inp
            # every row writes decode step t at slot l + t; RoPE position
            # is the row's true next position lengths + t
            kv_valid = lax.dynamic_update_slice(
                kv_valid, jnp.ones((b, 1), dtype=kv_valid.dtype), (0, l + t)
            )
            logits, cache = decoder_forward(
                params, config, tok[:, None],
                jnp.ones((b, 1), dtype=jnp.int32),
                positions=(lengths + t)[:, None],
                kv_cache=cache, kv_valid=kv_valid, slot_offset=l + t,
            )
            nxt = sample(logits[:, 0, :], key)
            return (cache, kv_valid, nxt), tok

        keys = jax.random.split(scan_rng, max_new_tokens)
        ts = jnp.arange(max_new_tokens)
        _, toks = lax.scan(step, (cache, kv_valid, first), (ts, keys))
        return toks.T  # [B, max_new_tokens]

    return jax.jit(generate, donate_argnums=())


def generate_tokens(params, config: DecoderConfig, ids, mask, *,
                    max_new_tokens: int = 16, temperature: float = 0.0,
                    seed: int = 0):
    """Greedy/temperature generation, fully on device. ids/mask: [B, L]
    (left-aligned prompts). Returns [B, max_new_tokens] int32."""
    import jax
    import jax.numpy as jnp

    l = int(np.asarray(ids).shape[1])
    if l + max_new_tokens > config.max_len:
        raise ValueError(
            f"prompt_len ({l}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the cache budget max_len ({config.max_len}); "
            "lax.dynamic_update_slice would silently clamp and corrupt the "
            "tail cache slots"
        )
    fn = _compiled_generate(config, max_new_tokens, float(temperature))
    return np.asarray(
        fn(params, jnp.asarray(ids), jnp.asarray(mask),
           jax.random.PRNGKey(seed))
    )
