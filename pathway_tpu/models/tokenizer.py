"""Offline-friendly tokenizer.

The reference loads HuggingFace tokenizers with downloaded vocab files
(xpacks/llm/embedders.py SentenceTransformerEmbedder). This environment has
zero egress, so the default is a deterministic hashing tokenizer (stable
token ids via blake2, like feature hashing); a wordpiece vocab file is used
when present. Either way the contract is the same: `encode_batch` returns
fixed-shape (ids, mask) arrays bucketed to power-of-two lengths so XLA sees
a small set of shapes.
"""

from __future__ import annotations

import os
import re
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

_WORD_RE = re.compile(r"[A-Za-z0-9]+|[^\sA-Za-z0-9]")

PAD_ID = 0
CLS_ID = 1
SEP_ID = 2
UNK_ID = 3
_RESERVED = 4


class HashTokenizer:
    def __init__(self, vocab_size: int = 30522, lowercase: bool = True):
        self.vocab_size = vocab_size
        self.lowercase = lowercase

    def token_id(self, token: str) -> int:
        # crc32 runs in C and is stable across processes; collisions at
        # 30k-vocab scale are acceptable for a feature-hashing tokenizer
        value = zlib.crc32(token.encode())
        return _RESERVED + value % (self.vocab_size - _RESERVED)

    def tokenize(self, text: str) -> List[str]:
        if self.lowercase:
            text = text.lower()
        return _WORD_RE.findall(text)

    def encode(self, text: str, max_len: int | None = None) -> List[int]:
        ids = [CLS_ID] + [self.token_id(t) for t in self.tokenize(text)] + [SEP_ID]
        if max_len is not None:
            ids = ids[:max_len]
        return ids

    def encode_pair(self, a: str, b: str, max_len: int | None = None) -> List[int]:
        ids = (
            [CLS_ID]
            + [self.token_id(t) for t in self.tokenize(a)]
            + [SEP_ID]
            + [self.token_id(t) for t in self.tokenize(b)]
            + [SEP_ID]
        )
        if max_len is not None:
            ids = ids[:max_len]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        # hashing is one-way; decode renders placeholder tokens (used only
        # by the random-weight chat model in offline tests)
        return " ".join(f"tok{i}" for i in ids if i >= _RESERVED)

    def count_tokens(self, text: str) -> int:
        return len(self.tokenize(text))


class WordPieceTokenizer:
    """Real WordPiece over a vocab file (reference: the HF tokenizer the
    reference loads for SentenceTransformer models, embedders.py:342).

    Greedy longest-match-first with `##` continuation pieces — the BERT
    algorithm — so ids match HuggingFace's BertTokenizer for ASCII text.
    Special ids come from the vocab ([PAD]/[CLS]/[SEP]/[UNK])."""

    def __init__(self, vocab, lowercase: bool = True):
        if isinstance(vocab, (str, bytes)):
            with open(vocab, encoding="utf-8") as f:
                tokens = [line.rstrip("\n") for line in f]
            vocab = {tok: i for i, tok in enumerate(tokens) if tok}
        self.vocab: dict = dict(vocab)
        self.lowercase = lowercase
        self.vocab_size = max(self.vocab.values()) + 1 if self.vocab else 0
        self.pad_id = self.vocab.get("[PAD]", 0)
        self.cls_id = self.vocab.get("[CLS]", 1)
        self.sep_id = self.vocab.get("[SEP]", 2)
        self.unk_id = self.vocab.get("[UNK]", 3)
        self._inv: dict | None = None

    def tokenize(self, text: str) -> List[str]:
        if self.lowercase:
            text = text.lower()
        pieces: List[str] = []
        for word in _WORD_RE.findall(text):
            pieces.extend(self._wordpiece(word))
        return pieces

    def _wordpiece(self, word: str, max_chars: int = 100) -> List[str]:
        if len(word) > max_chars:
            return ["[UNK]"]
        out: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return ["[UNK]"]
            out.append(piece)
            start = end
        return out

    def token_id(self, token: str) -> int:
        return self.vocab.get(token, self.unk_id)

    def encode(self, text: str, max_len: int | None = None) -> List[int]:
        ids = (
            [self.cls_id]
            + [self.token_id(t) for t in self.tokenize(text)]
            + [self.sep_id]
        )
        if max_len is not None and len(ids) > max_len:
            # HF truncation keeps [SEP] as the final token
            ids = ids[: max_len - 1] + [self.sep_id]
        return ids

    def encode_pair(self, a: str, b: str, max_len: int | None = None) -> List[int]:
        ids = (
            [self.cls_id]
            + [self.token_id(t) for t in self.tokenize(a)]
            + [self.sep_id]
            + [self.token_id(t) for t in self.tokenize(b)]
            + [self.sep_id]
        )
        if max_len is not None and len(ids) > max_len:
            ids = ids[: max_len - 1] + [self.sep_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        if self._inv is None:
            self._inv = {i: t for t, i in self.vocab.items()}
        specials = {self.pad_id, self.cls_id, self.sep_id}
        words: List[str] = []
        for i in ids:
            if i in specials:
                continue
            tok = self._inv.get(int(i), "[UNK]")
            if tok.startswith("##") and words:
                words[-1] += tok[2:]
            else:
                words.append(tok)
        return " ".join(words)

    def count_tokens(self, text: str) -> int:
        return len(self.tokenize(text))


def bucket_length(n: int, minimum: int = 16, maximum: int = 512) -> int:
    """Power-of-two buckets — the BATCH-dimension policy. Mesh sharding
    depends on it (power-of-two batches divide any power-of-two dp axis,
    minilm.py encode), and it bounds the compile cache to ~log2 shapes."""
    b = minimum
    while b < n and b < maximum:
        b *= 2
    return min(b, maximum)


def seq_bucket_length(n: int, minimum: int = 16, maximum: int = 512) -> int:
    """SEQUENCE-dimension buckets: powers of two up to 32, then multiples
    of 8. The finer high-end granularity matters on the MXU — bulk
    corpora sit just past a power of two (e.g. 51 tokens), and padding
    51 -> 64 instead of 51 -> 56 burns 14% of the FLOPs on pad tokens.
    The sequence axis is never mesh-sharded by the encoder, so the
    power-of-two divisibility constraint of `bucket_length` does not
    apply; shape count stays bounded by maximum/8."""
    if n <= minimum:
        return min(minimum, maximum)
    b = minimum
    while b < n and b < 32:
        b *= 2
    if b >= n:
        return min(b, maximum)
    return min(-(-n // 8) * 8, maximum)


def encode_batch(
    tokenizer: HashTokenizer,
    texts: Sequence[str],
    *,
    max_len: int = 512,
    pair_texts: Sequence[str] | None = None,
    batch_bucket: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (ids [B', L'], mask [B', L']) padded to bucketed shapes; the
    first len(texts) rows are the real batch. Single-text batches go through
    the C++ tokenizer when available (pathway_tpu/native/tokenizer.cpp)."""
    if (
        pair_texts is None
        and texts
        and isinstance(tokenizer, HashTokenizer)
        and tokenizer.lowercase
        and all(t.isascii() for t in texts)
    ):
        # the native path matches the python tokenizer exactly only for
        # lowercased ASCII input; anything else takes the python path so
        # ids never depend on whether a compiler was available
        native = _try_native(tokenizer, texts, max_len, batch_bucket)
        if native is not None:
            return native
    if pair_texts is not None:
        encoded = [
            tokenizer.encode_pair(a, b, max_len)
            for a, b in zip(texts, pair_texts)
        ]
    else:
        encoded = [tokenizer.encode(t, max_len) for t in texts]
    longest = max((len(e) for e in encoded), default=1)
    seq_len = seq_bucket_length(longest, maximum=max_len)
    batch = len(encoded)
    padded_batch = bucket_length(max(batch, 1), minimum=8, maximum=1 << 16) if batch_bucket else batch
    pad_id = getattr(tokenizer, "pad_id", PAD_ID)
    dtype = _wire_dtype(tokenizer)
    ids = np.full((padded_batch, seq_len), pad_id, dtype=dtype)
    mask = np.zeros((padded_batch, seq_len), dtype=dtype)
    for i, e in enumerate(encoded):
        e = e[:seq_len]
        ids[i, : len(e)] = e
        mask[i, : len(e)] = 1
    return ids, mask


PACK_MAX_SEGMENTS = 32


def pack_token_budget(default: int = 256) -> int:
    """Slab length for packed ragged batching (PATHWAY_PACK_TOKEN_BUDGET,
    read per call like PATHWAY_INGEST_CHUNK). 0 disables packing and the
    ingest path falls back to the classic one-doc-per-row bucketed
    encode."""
    raw = os.environ.get("PATHWAY_PACK_TOKEN_BUDGET", "")
    if not raw:
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        return default


def pack_batch(
    tokenizer,
    texts: Sequence[str],
    *,
    max_len: int = 512,
    token_budget: int = 256,
    max_segments: int = PACK_MAX_SEGMENTS,
    row_bucket: bool = True,
) -> Tuple[np.ndarray, np.ndarray, List[Tuple[int, int]]]:
    """Packed ragged batching: concatenate variable-length docs into
    fixed token-budget slabs with a segment-ids mask instead of padding
    each doc to the bucket max, so the MXU runs on real tokens.

    Returns (ids [R, L], seg [R, L], slots). seg holds 1..max_segments
    per document within a row (0 = padding); slots[d] = (row, seg - 1)
    locates document d's pooled vector in the encoder's [R, S, H] output.

    Packing is greedy first-fit in arrival order: deterministic, and the
    XLA shape set stays tiny because L is the fixed budget (raised to the
    sequence bucket of the longest doc only when one overflows it) and
    the row count buckets like a sequence axis — packed rows are never
    mesh-sharded, so the power-of-two batch contract does not apply.
    """
    encoded = [tokenizer.encode(t, max_len) for t in texts]
    longest = max((len(e) for e in encoded), default=1)
    slab = max(1, int(token_budget))
    if longest > slab:
        slab = seq_bucket_length(longest, maximum=max(max_len, longest))
    rows: List[List[List[int]]] = []
    used: List[int] = []
    slots: List[Tuple[int, int]] = []
    for e in encoded:
        need = len(e)
        row = -1
        for r in range(len(rows)):
            if used[r] + need <= slab and len(rows[r]) < max_segments:
                row = r
                break
        if row < 0:
            rows.append([])
            used.append(0)
            row = len(rows) - 1
        slots.append((row, len(rows[row])))
        rows[row].append(e)
        used[row] += need
    n_rows = max(len(rows), 1)
    padded_rows = (
        seq_bucket_length(n_rows, minimum=8, maximum=1 << 16)
        if row_bucket
        else n_rows
    )
    pad_id = getattr(tokenizer, "pad_id", PAD_ID)
    dtype = _wire_dtype(tokenizer)
    ids = np.full((padded_rows, slab), pad_id, dtype=dtype)
    seg = np.zeros((padded_rows, slab), dtype=dtype)
    for r, docs in enumerate(rows):
        at = 0
        for s, e in enumerate(docs):
            ids[r, at : at + len(e)] = e
            seg[r, at : at + len(e)] = s + 1
            at += len(e)
    return ids, seg, slots


def predict_pad_waste(
    lengths: Sequence[int], batch_size: int, *, max_len: int = 512
) -> float:
    """Predicted padding-waste fraction of the CLASSIC (unpacked) encode
    path for a UDF batch of `batch_size` docs drawn from the sampled
    token `lengths`: real tokens vs the bucketed [B', L'] slab that
    encode_batch would dispatch. Used by the PWT401 analyzer lint to flag
    embedder configs whose batch/bucket shape burns most of the MXU on
    pad tokens."""
    if not lengths or batch_size <= 0:
        return 0.0
    batch = [
        max(1, min(int(lengths[i % len(lengths)]), max_len))
        for i in range(batch_size)
    ]
    seq_len = seq_bucket_length(max(batch), maximum=max_len)
    padded_batch = bucket_length(batch_size, minimum=8, maximum=1 << 16)
    real = sum(batch)
    total = padded_batch * seq_len
    return 1.0 - (real / float(total)) if total else 0.0


def _wire_dtype(tokenizer):
    """THE wire-narrowing policy for token uploads (single source — the
    models upcast on device): int16/uint16 halves the host->device
    transfer of every token batch, the dominant upload on a tunneled
    chip; XLA gathers cast indices anyway. Falls back to int32 for
    vocabularies beyond 16-bit range. Masks share the ids dtype (narrow
    on the wire, and safe for in-jit integer sums at any seq length,
    which int8 would not be)."""
    nvocab = getattr(tokenizer, "vocab_size", None)
    if nvocab is None:
        nvocab = len(getattr(tokenizer, "vocab", ())) or (1 << 31)
    if nvocab < (1 << 15):
        return np.int16
    if nvocab < (1 << 16):
        return np.uint16
    return np.int32


def _try_native(tokenizer, texts, max_len, batch_bucket):
    from pathway_tpu import native

    lib = native.load()
    if lib is None:
        return None
    batch = len(texts)
    padded_batch = (
        bucket_length(max(batch, 1), minimum=8, maximum=1 << 16)
        if batch_bucket
        else batch
    )
    result = native.tokenize_batch_native(
        list(texts), tokenizer.vocab_size, max_len
    )
    if result is None:
        return None
    ids_full, mask_full = result
    longest = int(mask_full.sum(axis=1).max()) if batch else 1
    seq_len = seq_bucket_length(max(longest, 1), maximum=max_len)
    dtype = _wire_dtype(tokenizer)
    ids = np.full((padded_batch, seq_len), PAD_ID, dtype=dtype)
    mask = np.zeros((padded_batch, seq_len), dtype=dtype)
    ids[:batch] = ids_full[:, :seq_len]
    mask[:batch] = mask_full[:, :seq_len]
    return ids, mask


class FastTokenizer:
    """Adapter over HuggingFace `tokenizers` (tokenizer.json — the format
    Llama/Mistral checkpoints ship). Same interface as HashTokenizer /
    WordPieceTokenizer, so encode_batch and the models consume it
    unchanged."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer  # type: ignore

        self._tok = Tokenizer.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        self.lowercase = False
        self.pad_id = 0
        for cand in ("<pad>", "[PAD]", "<unk>", "<s>"):
            tid = self._tok.token_to_id(cand)
            if tid is not None:
                self.pad_id = tid
                break

    def tokenize(self, text: str) -> List[str]:
        return self._tok.encode(text).tokens

    def encode(self, text: str, max_len: int | None = None) -> List[int]:
        ids = self._tok.encode(text).ids
        if max_len is not None:
            ids = ids[:max_len]
        return ids

    def encode_pair(self, a: str, b: str, max_len: int | None = None) -> List[int]:
        ids = self._tok.encode(a, b).ids
        if max_len is not None:
            ids = ids[:max_len]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode([int(i) for i in ids], skip_special_tokens=True)

    def count_tokens(self, text: str) -> int:
        return len(self._tok.encode(text).ids)
