"""Long-context forward: the whole transformer under sequence parallelism.

Runs the full layer stack inside one `shard_map` over the mesh's `sp` axis:
activations stay sequence-sharded end to end ([B, L/sp, H] per device),
attention is exact ring attention (parallel/ring_attention.py) or Ulysses
all-to-all, and everything else (layernorm, QKV/MLP matmuls) is local
per-token work. Context length scales linearly with the number of chips —
a capability the reference does not have at all (SURVEY §5: it chunks long
documents in Python instead).

Params are replicated over sp (they're O(H^2); activations at long L are the
memory problem sequence parallelism solves). Combine with tp/dp axes by
nesting this shard_map in a pjit over the remaining axes.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from pathway_tpu.models.transformer import TransformerConfig, _layer_norm


def _local_forward(params, config: TransformerConfig, ids, mask,
                   *, axis_name: str, attn: str, use_flash):
    """Body run per-device inside shard_map. ids/mask: [B, C] local chunk."""
    import jax.numpy as jnp
    from jax import lax

    from pathway_tpu.parallel.ring_attention import (
        ring_attention,
        ulysses_attention,
    )

    compute_dtype = (
        jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    )
    b, c = ids.shape
    my = lax.axis_index(axis_name)
    # global positions of this chunk for the positional table
    pos = my * c + jnp.arange(c)
    x = params["embed"][ids] + params["pos_embed"][pos][None, :, :]
    x = x.astype(compute_dtype)

    heads, hd = config.heads, config.head_dim
    for layer in params["layers"]:
        y = _layer_norm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        qkv = (
            y @ layer["qkv"].astype(compute_dtype)
            + layer["qkv_b"].astype(compute_dtype)
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, c, heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, c, heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, c, heads, hd).transpose(0, 2, 1, 3)
        if attn == "ring":
            ctx = ring_attention(
                q, k, v, mask, axis_name=axis_name, causal=config.causal
            )
        else:
            ctx = ulysses_attention(
                q, k, v, mask, axis_name=axis_name, causal=config.causal,
                use_flash=use_flash,
            )
        ctx = ctx.astype(compute_dtype)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, c, config.hidden)
        x = x + (
            ctx @ layer["out"].astype(compute_dtype)
            + layer["out_b"].astype(compute_dtype)
        )
        y = _layer_norm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
        y = (
            y @ layer["up"].astype(compute_dtype)
            + layer["up_b"].astype(compute_dtype)
        )
        y = y * 0.5 * (1.0 + jnp.tanh(0.7978845608 * (y + 0.044715 * y**3)))
        x = x + (
            y @ layer["down"].astype(compute_dtype)
            + layer["down_b"].astype(compute_dtype)
        )

    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    if config.pooling == "none":
        return jnp.einsum(
            "blh,vh->blv", x.astype(jnp.float32), params["embed"]
        )
    # mean pooling needs the cross-chunk sums: two tiny psums
    m = mask[:, :, None].astype(x.dtype)
    local_sum = (x * m).sum(1)
    local_cnt = m.sum(1)
    pooled = lax.psum(local_sum, axis_name) / (
        lax.psum(local_cnt, axis_name) + 1e-9
    )
    pooled = pooled.astype(jnp.float32)
    return pooled / (jnp.linalg.norm(pooled, axis=-1, keepdims=True) + 1e-9)


def sequence_parallel_forward(params, config: TransformerConfig, ids, mask,
                              mesh, *, axis_name: str = "sp",
                              attn: str = "ring",
                              use_flash: Optional[bool] = None):
    """Jit-compile and run the transformer with sequences sharded over
    `axis_name` of `mesh`. ids, mask: [B, L] with L divisible by the axis
    size. Returns logits [B, L, V] (pooling='none') or pooled [B, H]."""
    import jax
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map  # jax >= 0.8
        _rep_kwargs = {"check_vma": False}
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map
        _rep_kwargs = {"check_rep": False}

    assert attn in ("ring", "ulysses"), attn
    l = ids.shape[1]
    sp = mesh.shape[axis_name]
    if l % sp != 0:
        raise ValueError(f"sequence length {l} not divisible by sp={sp}")

    body = functools.partial(
        _local_forward, config=config, axis_name=axis_name, attn=attn,
        use_flash=use_flash,
    )
    if config.pooling == "none":
        out_spec = P(None, axis_name, None)
    else:
        out_spec = P(None, None)
    fn = shard_map(
        lambda p, i, m: body(p, ids=i, mask=m),
        mesh=mesh,
        in_specs=(P(), P(None, axis_name), P(None, axis_name)),
        out_specs=out_spec,
        **_rep_kwargs,
    )
    return jax.jit(fn)(params, ids, mask)
