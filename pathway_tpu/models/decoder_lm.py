"""Decoder-only chat model on JAX/TPU.

TPU-native replacement for the reference's local HF pipeline
(reference: xpacks/llm/llms.py HFPipelineChat:456 — torch pipeline,
batch 32). Geometry for the Private-RAG target (Mistral-7B-class) is defined
in transformer.MISTRAL_7B; without pretrained weights (zero egress) the
default instance is a random-weight tiny decoder that exercises the exact
compute path (tokenize → bucketed batch → jit forward → greedy decode).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from pathway_tpu.models.tokenizer import HashTokenizer, encode_batch
from pathway_tpu.models.transformer import (
    MISTRAL_7B,
    TINY_DECODER,
    TransformerConfig,
    TransformerLM,
)

_model_cache: dict = {}


class ChatModel:
    def __init__(
        self,
        model: str = "tiny-decoder",
        *,
        config: TransformerConfig | None = None,
        seed: int = 2,
        max_len: int = 128,
    ):
        if config is None:
            config = MISTRAL_7B if "mistral" in model.lower() else TINY_DECODER
        self.name = model
        self.config = config
        self.max_len = min(max_len, config.max_len)
        self.tokenizer = HashTokenizer(vocab_size=config.vocab_size)
        self.lm = TransformerLM(config, seed=seed)

    @classmethod
    def cached(cls, model: str = "tiny-decoder", **kw) -> "ChatModel":
        key = (model, tuple(sorted(kw.items())))
        if key not in _model_cache:
            _model_cache[key] = cls(model, **kw)
        return _model_cache[key]

    def generate(
        self,
        prompts: Sequence[str],
        *,
        max_new_tokens: int = 16,
    ) -> List[str]:
        if not prompts:
            return []
        ids, mask = encode_batch(
            self.tokenizer, list(prompts), max_len=self.max_len
        )
        tokens = self.lm.generate(ids, mask, max_new_tokens=max_new_tokens)
        return [
            self.tokenizer.decode(row) for row in tokens[: len(prompts)]
        ]
