"""Decoder-only chat model on JAX/TPU.

TPU-native replacement for the reference's local HF pipeline
(reference: xpacks/llm/llms.py HFPipelineChat:456 — torch pipeline,
batch 32). Geometry for the Private-RAG target (Mistral-7B-class) is defined
in transformer.MISTRAL_7B; without pretrained weights (zero egress) the
default instance is a random-weight tiny decoder that exercises the exact
compute path (tokenize → bucketed batch → jit forward → greedy decode).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from pathway_tpu.models.decoder import (
    MISTRAL_7B_DECODER,
    TINY,
    DecoderConfig,
    generate_tokens,
    init_decoder_params,
)
from pathway_tpu.models.tokenizer import HashTokenizer

_model_cache: dict = {}


class ChatModel:
    """KV-cached decoder (models/decoder.py): prefill + lax.scan decode in
    one jit — no host round trip per token (the reference loops a torch
    pipeline on CPU/GPU, llms.py:456)."""

    def __init__(
        self,
        model: str = "tiny-decoder",
        *,
        config: DecoderConfig | None = None,
        seed: int = 2,
        max_len: int = 128,
    ):
        import os

        import jax

        params = None
        tokenizer = None
        from pathway_tpu.models import hf_loader

        if hf_loader.is_decoder_checkpoint(model):
            if config is not None:
                raise ValueError(
                    "pass either a checkpoint directory (its config.json "
                    "defines the architecture) or an explicit config=, "
                    "not both"
                )
            # real weights: a local Llama/Mistral-family checkpoint dir
            # (reference: llms.py HFPipelineChat:456 loads HF weights)
            config, params = hf_loader.load_hf_decoder(model)
            tok_json = os.path.join(model, "tokenizer.json")
            if os.path.exists(tok_json):
                from pathway_tpu.models.tokenizer import FastTokenizer

                tokenizer = FastTokenizer(tok_json)
        if config is None:
            config = MISTRAL_7B_DECODER if "mistral" in model.lower() else TINY
        self.name = model
        self.config = config
        self.max_len = min(max_len, config.max_len)
        self.tokenizer = tokenizer or HashTokenizer(
            vocab_size=config.vocab_size
        )
        if params is None:
            params = init_decoder_params(jax.random.PRNGKey(seed), config)
        self.params = params

    @classmethod
    def cached(cls, model: str = "tiny-decoder", **kw) -> "ChatModel":
        key = (model, tuple(sorted(kw.items())))
        if key not in _model_cache:
            _model_cache[key] = cls(model, **kw)
        return _model_cache[key]

    def generate(
        self,
        prompts: Sequence[str],
        *,
        max_new_tokens: int = 16,
        temperature: float = 0.0,
    ) -> List[str]:
        if not prompts:
            return []
        # Leave cache room for the new tokens; when a prompt overflows the
        # budget keep its most recent tokens — the tail is what conditions
        # the reply (the reference HF pipeline truncates the same end) —
        # so encode unbounded first, then keep the tail, left-aligned.
        budget = min(self.max_len, self.config.max_len - max_new_tokens)
        if budget <= 0:
            raise ValueError(
                f"max_new_tokens ({max_new_tokens}) leaves no cache room "
                f"for any prompt token (model max_len "
                f"{self.config.max_len})"
            )
        encoded = [
            self.tokenizer.encode(t, None)[-budget:] for t in prompts
        ]
        longest = max(len(e) for e in encoded)
        ids = np.zeros((len(encoded), longest), dtype=np.int32)
        mask = np.zeros_like(ids)
        for r, e in enumerate(encoded):
            ids[r, : len(e)] = e
            mask[r, : len(e)] = 1
        tokens = generate_tokens(
            self.params, self.config, ids, mask,
            max_new_tokens=max_new_tokens, temperature=temperature,
        )
        return [
            self.tokenizer.decode(row) for row in tokens[: len(prompts)]
        ]
