"""HF-checkpoint → JAX-pytree loading for encoder models.

The reference's embedder is a real SentenceTransformer with downloaded
weights (reference: python/pathway/xpacks/llm/embedders.py:342-434) and its
chat model loads real HF checkpoints (llms.py:456). This module gives the
TPU build the same capability offline: point `SentenceTransformerEmbedder`
(or `SentenceEncoder`) at a local directory holding a BERT-family checkpoint
(`config.json` + `model.safetensors` / `pytorch_model.bin` / `weights.npz`
+ `vocab.txt`) and the tensors are remapped into the `TransformerConfig`
post-LN ("bert") layout of models/transformer.py. No network access is ever
attempted — loading is from the filesystem only.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import numpy as np


def is_checkpoint_dir(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, "config.json")
    )


def _read_tensors(path: str) -> Dict[str, np.ndarray]:
    """Read raw named tensors from whichever serialized form is present."""
    st = os.path.join(path, "model.safetensors")
    if os.path.exists(st):
        from safetensors.numpy import load_file

        return {k: np.asarray(v) for k, v in load_file(st).items()}
    npz = os.path.join(path, "weights.npz")
    if os.path.exists(npz):
        with np.load(npz) as data:
            return {k: np.asarray(data[k]) for k in data.files}
    bin_path = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(bin_path):
        import torch

        state = torch.load(bin_path, map_location="cpu", weights_only=True)
        return {k: v.numpy() for k, v in state.items()}
    raise FileNotFoundError(
        f"no model.safetensors / weights.npz / pytorch_model.bin in {path}"
    )


def _strip_prefix(tensors: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Drop the leading module name HF sometimes nests under (`bert.`,
    `roberta.`, `0.auto_model.` for sentence-transformers exports)."""
    for prefix in ("bert.", "roberta.", "0.auto_model.", "auto_model."):
        if any(k.startswith(prefix) for k in tensors):
            return {
                (k[len(prefix):] if k.startswith(prefix) else k): v
                for k, v in tensors.items()
            }
    return tensors


def load_hf_encoder(path: str, *, dtype: str = "bfloat16"):
    """Returns (TransformerConfig, params-pytree) for a BERT-family encoder
    checkpoint directory. Tensor-name mapping:

      embeddings.word_embeddings.weight          -> embed [V,H]
      embeddings.position_embeddings.weight      -> pos_embed [P,H]
      embeddings.token_type_embeddings.weight    -> type_embed [T,H]
      embeddings.LayerNorm.{weight,bias}         -> embed_ln
      encoder.layer.i.attention.self.{q,k,v}     -> qkv [H,3H] (transposed,
                                                    concatenated)
      encoder.layer.i.attention.output.dense     -> out [H,H]
      encoder.layer.i.attention.output.LayerNorm -> ln1 (post-attn)
      encoder.layer.i.intermediate.dense         -> up [H,M]
      encoder.layer.i.output.dense               -> down [M,H]
      encoder.layer.i.output.LayerNorm           -> ln2 (post-mlp)

    torch Linear stores weight as [out, in]; JAX matmuls here are x @ W, so
    every dense weight is transposed on load."""
    import jax.numpy as jnp

    from pathway_tpu.models.transformer import TransformerConfig

    with open(os.path.join(path, "config.json"), encoding="utf-8") as f:
        cfg = json.load(f)
    config = TransformerConfig(
        vocab_size=cfg["vocab_size"],
        hidden=cfg["hidden_size"],
        layers=cfg["num_hidden_layers"],
        heads=cfg["num_attention_heads"],
        mlp_dim=cfg["intermediate_size"],
        max_len=cfg.get("max_position_embeddings", 512),
        causal=False,
        pooling="mean",
        norm_style="post",
        dtype=dtype,
    )

    tensors = _strip_prefix(_read_tensors(path))

    def get(name: str) -> np.ndarray:
        if name not in tensors:
            raise KeyError(
                f"checkpoint {path} is missing tensor {name!r}; "
                f"has {sorted(tensors)[:8]}..."
            )
        return tensors[name]

    def dev(x: np.ndarray):
        return jnp.asarray(np.asarray(x, dtype=np.float32))

    params: Dict[str, Any] = {
        "embed": dev(get("embeddings.word_embeddings.weight")),
        "pos_embed": dev(get("embeddings.position_embeddings.weight")),
        "type_embed": dev(get("embeddings.token_type_embeddings.weight")),
        "embed_ln": {
            "scale": dev(get("embeddings.LayerNorm.weight")),
            "bias": dev(get("embeddings.LayerNorm.bias")),
        },
        # post-LN forward never reads ln_f; keep an identity so the pytree
        # structure stays compatible with optimizer/sharding rules
        "ln_f": {
            "scale": jnp.ones((config.hidden,)),
            "bias": jnp.zeros((config.hidden,)),
        },
        "layers": [],
    }
    for i in range(config.layers):
        p = f"encoder.layer.{i}."
        qw = get(p + "attention.self.query.weight").T
        kw = get(p + "attention.self.key.weight").T
        vw = get(p + "attention.self.value.weight").T
        qb = get(p + "attention.self.query.bias")
        kb = get(p + "attention.self.key.bias")
        vb = get(p + "attention.self.value.bias")
        params["layers"].append(
            {
                "qkv": dev(np.concatenate([qw, kw, vw], axis=1)),
                "qkv_b": dev(np.concatenate([qb, kb, vb])),
                "out": dev(get(p + "attention.output.dense.weight").T),
                "out_b": dev(get(p + "attention.output.dense.bias")),
                "ln1": {
                    "scale": dev(get(p + "attention.output.LayerNorm.weight")),
                    "bias": dev(get(p + "attention.output.LayerNorm.bias")),
                },
                "up": dev(get(p + "intermediate.dense.weight").T),
                "up_b": dev(get(p + "intermediate.dense.bias")),
                "down": dev(get(p + "output.dense.weight").T),
                "down_b": dev(get(p + "output.dense.bias")),
                "ln2": {
                    "scale": dev(get(p + "output.LayerNorm.weight")),
                    "bias": dev(get(p + "output.LayerNorm.bias")),
                },
            }
        )
    return config, params


def load_tokenizer(path: str, lowercase: bool | None = None):
    """WordPiece tokenizer from the checkpoint's vocab.txt (falls back to
    the hashing tokenizer if the file is absent)."""
    from pathway_tpu.models.tokenizer import HashTokenizer, WordPieceTokenizer

    vocab_path = os.path.join(path, "vocab.txt")
    if not os.path.exists(vocab_path):
        return None
    if lowercase is None:
        lowercase = True
        cfg_tok = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(cfg_tok):
            with open(cfg_tok, encoding="utf-8") as f:
                lowercase = bool(json.load(f).get("do_lower_case", True))
    return WordPieceTokenizer(vocab_path, lowercase=lowercase)


def is_decoder_checkpoint(path: str) -> bool:
    """config.json with a Llama/Mistral-family architecture."""
    cfg_path = os.path.join(path, "config.json")
    if not os.path.exists(cfg_path):
        return False
    with open(cfg_path, encoding="utf-8") as f:
        cfg = json.load(f)
    archs = cfg.get("architectures") or []
    model_type = cfg.get("model_type", "")
    return model_type in ("llama", "mistral", "mixtral") or any(
        "CausalLM" in a for a in archs
    )


def load_hf_decoder(path: str, *, dtype: str | None = None):
    """Llama/Mistral-family causal checkpoint -> (DecoderConfig, params)
    for models/decoder.py (reference: llms.py HFPipelineChat:456 loads HF
    weights via transformers; here the tensors remap directly).

    Name mapping (torch Linear weights transpose onto x @ W):
      model.embed_tokens.weight                 -> embed [V,H]
      model.norm.weight                         -> ln_f
      model.layers.i.input_layernorm.weight     -> ln1
      model.layers.i.post_attention_layernorm   -> ln2
      model.layers.i.self_attn.{q,k,v,o}_proj   -> wq/wk/wv/wo
      model.layers.i.mlp.{gate,up,down}_proj    -> gate/up/down
      lm_head.weight                            -> lm_head (untied head)
    """
    import jax.numpy as jnp

    from pathway_tpu.models.decoder import DecoderConfig

    with open(os.path.join(path, "config.json"), encoding="utf-8") as f:
        cfg = json.load(f)
    config = DecoderConfig(
        vocab_size=cfg["vocab_size"],
        hidden=cfg["hidden_size"],
        layers=cfg["num_hidden_layers"],
        q_heads=cfg["num_attention_heads"],
        kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
        mlp_dim=cfg["intermediate_size"],
        max_len=min(cfg.get("max_position_embeddings", 4096), 32768),
        rope_theta=float(cfg.get("rope_theta", 10000.0)),
        norm_eps=float(cfg.get("rms_norm_eps", 1e-5)),
        dtype=dtype or "bfloat16",
    )

    tensors = _read_tensors(path)

    def get(name: str) -> np.ndarray:
        if name not in tensors:
            raise KeyError(
                f"checkpoint {path} is missing tensor {name!r}; "
                f"has {sorted(tensors)[:8]}..."
            )
        return tensors[name]

    # matmul weights are stored at the compute dtype (bf16 halves HBM for
    # a 7B model and makes the forward's .astype a no-op); norms/embed
    # stay f32 (numerics + f32 logit projection)
    weight_dtype = (
        jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
    )

    def dev32(x: np.ndarray):
        return jnp.asarray(np.asarray(x, dtype=np.float32))

    def devw(x: np.ndarray):
        return jnp.asarray(
            np.asarray(x, dtype=np.float32), dtype=weight_dtype
        )

    params: Dict[str, Any] = {
        "embed": dev32(get("model.embed_tokens.weight")),
        "ln_f": dev32(get("model.norm.weight")),
        "layers": [],
    }
    if "lm_head.weight" in tensors:
        params["lm_head"] = dev32(tensors["lm_head.weight"])
    for i in range(config.layers):
        p = f"model.layers.{i}."
        params["layers"].append(
            {
                "ln1": dev32(get(p + "input_layernorm.weight")),
                "ln2": dev32(get(p + "post_attention_layernorm.weight")),
                "wq": devw(get(p + "self_attn.q_proj.weight").T),
                "wk": devw(get(p + "self_attn.k_proj.weight").T),
                "wv": devw(get(p + "self_attn.v_proj.weight").T),
                "wo": devw(get(p + "self_attn.o_proj.weight").T),
                "gate": devw(get(p + "mlp.gate_proj.weight").T),
                "up": devw(get(p + "mlp.up_proj.weight").T),
                "down": devw(get(p + "mlp.down_proj.weight").T),
            }
        )
    return config, params
