"""SentenceTransformer-class sentence encoder on JAX/TPU.

TPU-native replacement for the reference's torch SentenceTransformer path
(reference: xpacks/llm/embedders.py SentenceTransformerEmbedder:342 — sync
batched UDF, default batch 1024, CPU/GPU). Here batches are bucketed to
stable shapes, jit-compiled, bf16 on the MXU; with a ("dp","tp") mesh the
batch axis shards over dp.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from pathway_tpu.models.tokenizer import HashTokenizer, encode_batch
from pathway_tpu.models.transformer import (
    MINILM_L6,
    TransformerConfig,
    TransformerLM,
)

_model_cache: dict = {}


class SentenceEncoder:
    """encode(list[str]) -> np.ndarray [B, hidden] (L2-normalized)."""

    def __init__(
        self,
        model: str = "all-MiniLM-L6-v2",
        *,
        config: TransformerConfig | None = None,
        seed: int = 0,
        max_len: int = 256,
        mesh=None,
    ):
        self.name = model
        params = None
        tokenizer = None
        from pathway_tpu.models import hf_loader

        if hf_loader.is_checkpoint_dir(model):
            # real weights: local HF-checkpoint dir (safetensors/.bin/.npz
            # + vocab.txt). The random-weight hash-tokenizer path stays the
            # offline default (reference: embedders.py:342 downloads the
            # model; this environment has zero egress).
            config, params = hf_loader.load_hf_encoder(model)
            tokenizer = hf_loader.load_tokenizer(model)
        self.config = config or MINILM_L6
        self.max_len = min(max_len, self.config.max_len)
        self.tokenizer = tokenizer or HashTokenizer(
            vocab_size=self.config.vocab_size
        )
        self.lm = TransformerLM(self.config, params=params, seed=seed)
        if mesh is not None:
            axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
            n_dev = mesh.shape[axis]
            if n_dev & (n_dev - 1):
                raise ValueError(
                    f"SentenceEncoder mesh axis {axis!r} has {n_dev} "
                    "devices; a power of two is required (batches bucket "
                    "to powers of two and would never shard evenly)"
                )
        self.mesh = mesh

    @classmethod
    def cached(cls, model: str = "all-MiniLM-L6-v2", **kwargs) -> "SentenceEncoder":
        key = (model, tuple(sorted(kwargs.items())))
        if key not in _model_cache:
            _model_cache[key] = cls(model, **kwargs)
        return _model_cache[key]

    @property
    def dimension(self) -> int:
        return self.config.hidden

    def encode(self, texts: Sequence[str]) -> np.ndarray:
        if not texts:
            return np.zeros((0, self.config.hidden), dtype=np.float32)
        ids, mask = encode_batch(
            self.tokenizer, list(texts), max_len=self.max_len
        )
        if self.mesh is not None:
            # data-parallel dispatch: the (bucketed, power-of-two) batch
            # axis shards over the mesh's 'dp'/first axis — XLA splits the
            # encoder across devices with no code change (scaling-book
            # recipe: annotate shardings, let the compiler place the rest)
            import jax
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            axis = "dp" if "dp" in self.mesh.axis_names else self.mesh.axis_names[0]
            n_dev = self.mesh.shape[axis]
            if ids.shape[0] % n_dev == 0:
                sharding = NamedSharding(self.mesh, P(axis, None))
                ids = jax.device_put(ids, sharding)
                mask = jax.device_put(mask, sharding)
        pooled = self.lm(ids, mask)
        return np.asarray(pooled)[: len(texts)]

    def encode_one(self, text: str) -> np.ndarray:
        return self.encode([text])[0]
