"""SentenceTransformer-class sentence encoder on JAX/TPU.

TPU-native replacement for the reference's torch SentenceTransformer path
(reference: xpacks/llm/embedders.py SentenceTransformerEmbedder:342 — sync
batched UDF, default batch 1024, CPU/GPU). Here batches are bucketed to
stable shapes, jit-compiled, bf16 on the MXU; with a ("dp","tp") mesh the
batch axis shards over dp.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from pathway_tpu.models.tokenizer import (
    PACK_MAX_SEGMENTS,
    HashTokenizer,
    encode_batch,
    pack_batch,
    pack_token_budget,
)
from pathway_tpu.models.transformer import (
    MINILM_L6,
    TransformerConfig,
    TransformerLM,
)

_model_cache: dict = {}


class SentenceEncoder:
    """encode(list[str]) -> np.ndarray [B, hidden] (L2-normalized)."""

    def __init__(
        self,
        model: str = "all-MiniLM-L6-v2",
        *,
        config: TransformerConfig | None = None,
        seed: int = 0,
        max_len: int = 256,
        mesh=None,
    ):
        self.name = model
        params = None
        tokenizer = None
        from pathway_tpu.models import hf_loader

        if hf_loader.is_checkpoint_dir(model):
            # real weights: local HF-checkpoint dir (safetensors/.bin/.npz
            # + vocab.txt). The random-weight hash-tokenizer path stays the
            # offline default (reference: embedders.py:342 downloads the
            # model; this environment has zero egress).
            config, params = hf_loader.load_hf_encoder(model)
            tokenizer = hf_loader.load_tokenizer(model)
        self.config = config or MINILM_L6
        self.max_len = min(max_len, self.config.max_len)
        self.tokenizer = tokenizer or HashTokenizer(
            vocab_size=self.config.vocab_size
        )
        self.lm = TransformerLM(self.config, params=params, seed=seed)
        if mesh is not None:
            axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
            n_dev = mesh.shape[axis]
            if n_dev & (n_dev - 1):
                raise ValueError(
                    f"SentenceEncoder mesh axis {axis!r} has {n_dev} "
                    f"devices, which is not a power of two: encode_batch "
                    f"buckets every batch to a power of two (minimum 8), "
                    f"so a {n_dev}-way '{axis}' shard would never divide "
                    f"the batch axis evenly. Use a power-of-two device "
                    f"count on that axis, or drop the mesh and run the "
                    f"single-device async pipeline "
                    f"(PATHWAY_DEVICE_PIPELINE=1, the default)"
                )
        self.mesh = mesh

    @classmethod
    def cached(cls, model: str = "all-MiniLM-L6-v2", **kwargs) -> "SentenceEncoder":
        key = (model, tuple(sorted(kwargs.items())))
        if key not in _model_cache:
            _model_cache[key] = cls(model, **kwargs)
        return _model_cache[key]

    @property
    def dimension(self) -> int:
        return self.config.hidden

    def encode(self, texts: Sequence[str]) -> np.ndarray:
        return self.encode_await(self.encode_submit(texts))

    def encode_submit(self, texts: Sequence[str]):
        """Async half of encode(): tokenize and ENQUEUE the device encode,
        returning an opaque handle without forcing the result. JAX
        dispatch is asynchronous, so the caller can tokenize the next
        batch while this one executes; encode_await transfers the pooled
        vectors. encode() is exactly encode_await(encode_submit(...)), so
        the two paths cannot drift numerically."""
        if not texts:
            return None
        ids, mask = encode_batch(
            self.tokenizer, list(texts), max_len=self.max_len
        )
        if self.mesh is not None:
            # data-parallel dispatch: the (bucketed, power-of-two) batch
            # axis shards over the mesh's 'dp'/first axis — XLA splits the
            # encoder across devices with no code change (scaling-book
            # recipe: annotate shardings, let the compiler place the rest)
            import jax
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            axis = "dp" if "dp" in self.mesh.axis_names else self.mesh.axis_names[0]
            n_dev = self.mesh.shape[axis]
            if ids.shape[0] % n_dev == 0:
                sharding = NamedSharding(self.mesh, P(axis, None))
                ids = jax.device_put(ids, sharding)
                mask = jax.device_put(mask, sharding)
        return (self.lm(ids, mask), len(texts))

    def encode_await(self, handle) -> np.ndarray:
        """Force a handle from encode_submit: one host transfer of the
        pooled [B, hidden] block, trimmed to the real batch."""
        if handle is None:
            return np.zeros((0, self.config.hidden), dtype=np.float32)
        pooled, n = handle
        return np.asarray(pooled)[:n]

    def encode_packed(self, texts: Sequence[str]) -> np.ndarray:
        """Packed ragged encode for the ingest hot path: docs concatenate
        into token-budget slabs (tokenizer.pack_batch) so the MXU runs on
        real tokens instead of per-doc pad. Falls back to the classic
        bucketed `encode` when packing is disabled
        (PATHWAY_PACK_TOKEN_BUDGET=0) or a mesh is attached — the mesh
        path needs the power-of-two batch-axis contract that packed row
        counts do not honor."""
        budget = pack_token_budget()
        if budget <= 0 or self.mesh is not None or not texts:
            return self.encode(texts)
        ids, seg, slots = pack_batch(
            self.tokenizer,
            list(texts),
            max_len=self.max_len,
            token_budget=budget,
        )
        pooled = np.asarray(
            self.lm.encode_packed(ids, seg, PACK_MAX_SEGMENTS)
        )
        rows = np.fromiter((r for r, _ in slots), dtype=np.int64, count=len(slots))
        segs = np.fromiter((s for _, s in slots), dtype=np.int64, count=len(slots))
        return pooled[rows, segs]

    def encode_one(self, text: str) -> np.ndarray:
        return self.encode([text])[0]
