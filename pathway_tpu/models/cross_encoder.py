"""Cross-encoder (query, doc) scorer on JAX/TPU.

TPU-native replacement for the reference's sentence_transformers CrossEncoder
(reference: xpacks/llm/rerankers.py CrossEncoderReranker:163 — which scores
ONE pair per call; see SURVEY.md 'batching asymmetries'). Here the whole
candidate batch scores in a single MXU pass.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

from pathway_tpu.models.tokenizer import HashTokenizer, encode_batch
from pathway_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
)

CROSS_ENCODER_CFG = TransformerConfig(
    vocab_size=30522, hidden=384, layers=4, heads=12, mlp_dim=1536,
    pooling="cls",
)

_model_cache: dict = {}


class CrossEncoderModel:
    def __init__(
        self,
        model: str = "cross-encoder/ms-marco-MiniLM-L-6-v2",
        *,
        config: TransformerConfig | None = None,
        seed: int = 1,
        max_len: int = 256,
    ):
        import jax

        self.name = model
        self.config = config or CROSS_ENCODER_CFG
        self.max_len = min(max_len, self.config.max_len)
        self.tokenizer = HashTokenizer(vocab_size=self.config.vocab_size)
        self.lm = TransformerLM(self.config, seed=seed)
        key = jax.random.PRNGKey(seed + 1)
        self.head = (
            np.asarray(
                jax.random.normal(key, (self.config.hidden,), dtype=np.float32)
            )
            * 0.02
        )

    @classmethod
    def cached(cls, model: str = "cross-encoder/ms-marco-MiniLM-L-6-v2", **kw):
        key = (model, tuple(sorted(kw.items())))
        if key not in _model_cache:
            _model_cache[key] = cls(model, **kw)
        return _model_cache[key]

    def score(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        """Scores for (query, doc) pairs, one fused batch."""
        if not pairs:
            return np.zeros((0,), dtype=np.float32)
        queries = [q for q, _ in pairs]
        docs = [d for _, d in pairs]
        ids, mask = encode_batch(
            self.tokenizer, queries, pair_texts=docs, max_len=self.max_len
        )
        pooled = np.asarray(self.lm(ids, mask))[: len(pairs)]
        return pooled @ self.head
