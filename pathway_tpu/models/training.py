"""Sharded training step for the transformer stack.

The reference never trains models (Pathway is a streaming framework), but the
TPU-native data plane owns its models, so fine-tuning the embedder/reranker/
decoder in-framework is a first-class capability. The step is pjit-sharded:
batch over 'dp', parameters Megatron-style over 'tp'
(models/transformer.param_sharding_rules); XLA places the psums/all-gathers
on ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import numpy as np

from pathway_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    param_sharding_rules,
)


def loss_fn(params, config: TransformerConfig, ids, mask, labels):
    """Cross-entropy LM loss (causal) or masked-token loss (encoder)."""
    import jax.numpy as jnp

    logits = forward(params, config, ids, mask, return_hidden=True)
    logits = logits.astype(jnp.float32)
    logp = logits - jnp.log(
        jnp.sum(jnp.exp(logits - logits.max(-1, keepdims=True)), axis=-1,
                keepdims=True)
    ) - logits.max(-1, keepdims=True)
    one_hot = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    m = mask.astype(jnp.float32)
    return -(one_hot * m).sum() / (m.sum() + 1e-9)


def sgd_step(params, grads, lr: float):
    import jax

    return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)


def make_train_step(config: TransformerConfig, lr: float = 1e-3):
    import jax

    def step(params, ids, mask, labels):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, config, ids, mask, labels)
        )(params)
        return sgd_step(params, grads, lr), loss

    return step


def make_sharded_train_step(mesh, config: TransformerConfig, lr: float = 1e-3):
    """jit the train step with explicit shardings over the mesh: inputs
    batch-sharded on 'dp', params sharded per param_sharding_rules ('tp'),
    loss replicated. Returns (jitted_step, place_params, place_batch)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    rules = param_sharding_rules(config, mesh)
    param_shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        rules,
        is_leaf=lambda x: isinstance(x, P),
    )
    batch_sharding = NamedSharding(mesh, P("dp", None))
    replicated = NamedSharding(mesh, P())
    step = make_train_step(config, lr)
    jitted = jax.jit(
        step,
        in_shardings=(param_shardings, batch_sharding, batch_sharding,
                      batch_sharding),
        out_shardings=(param_shardings, replicated),
    )

    def place_params(params):
        return jax.device_put(params, param_shardings)

    def place_batch(*arrays):
        return tuple(jax.device_put(a, batch_sharding) for a in arrays)

    return jitted, place_params, place_batch
