"""JAX model zoo for the LLM xpack data plane: sentence encoder
(SentenceTransformer-class), cross-encoder reranker, decoder LM
(HFPipelineChat-class). All jit-compiled, bf16 on the MXU, shardable over a
jax.sharding.Mesh."""

from pathway_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    init_params,
    param_sharding_rules,
)

__all__ = [
    "TransformerConfig",
    "TransformerLM",
    "init_params",
    "param_sharding_rules",
]
