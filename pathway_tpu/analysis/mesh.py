"""Mesh specification for the PWT4xx mesh-compatibility lints.

A mesh spec names the device axes a run intends to shard over — the same
("dp", "tp") vocabulary as `models/minilm.SentenceEncoder(mesh=...)` and
the pjit/NamedSharding recipes.  The analyzer does not need real devices:
the PWT402-405 lints are shape/topology arguments over the recorded
graph, so `pathway-tpu analyze --mesh dp=4,tp=2` works on a laptop and
`pw.run(mesh=...)` fails fast before any worker starts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Mapping, Tuple


@dataclass(frozen=True)
class MeshSpec:
    """Ordered (axis name, device count) pairs, e.g. dp=4,tp=2."""

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def parse(cls, spec: Any) -> "MeshSpec":
        """Accept a MeshSpec, a "dp=4,tp=2" string, or a name->count
        mapping.  Raises ValueError on anything else — pw.run(mesh=...)
        must reject a bad spec before building anything."""
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, Mapping):
            items = list(spec.items())
        elif isinstance(spec, str):
            items = []
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                name, eq, count = part.partition("=")
                if not eq:
                    raise ValueError(
                        f"mesh axis {part!r} is not name=count "
                        "(expected e.g. 'dp=4,tp=2')"
                    )
                items.append((name.strip(), count.strip()))
        else:
            raise ValueError(
                f"mesh spec must be a MeshSpec, 'dp=4,tp=2' string or "
                f"mapping, got {type(spec).__name__}"
            )
        axes = []
        for name, count in items:
            try:
                n = int(count)
            except (TypeError, ValueError):
                raise ValueError(
                    f"mesh axis {name!r} has non-integer device count "
                    f"{count!r}"
                ) from None
            if not name or n < 1:
                raise ValueError(
                    f"mesh axis {name!r}={n} must have a name and a "
                    "positive device count"
                )
            axes.append((name, n))
        if not axes:
            raise ValueError("mesh spec names no axes")
        return cls(axes=tuple(axes))

    @property
    def dp(self) -> int:
        return self.axis("dp")

    @property
    def tp(self) -> int:
        return self.axis("tp")

    def axis(self, name: str) -> int:
        for axis, count in self.axes:
            if axis == name:
                return count
        return 1

    def devices(self) -> int:
        n = 1
        for _axis, count in self.axes:
            n *= count
        return n

    def describe(self) -> str:
        return ",".join(f"{name}={count}" for name, count in self.axes)

    def to_dict(self) -> Dict[str, int]:
        return dict(self.axes)
