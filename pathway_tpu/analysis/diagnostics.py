"""Diagnostic model for the build-time graph analyzer.

Every finding carries a stable code (PWT1xx correctness, PWT2xx
state/robustness, PWT3xx performance), a severity, a human message, and
a location: the user stack frame that built the operator when
`internals/trace.py` found one, otherwise the operator id + graph path —
synthetic/stdlib-built operators still produce findings, they just point
at the graph instead of a user line.

The JSON form (`AnalysisResult.to_dict`/`from_dict`) round-trips exactly
so CI tooling can consume `pathway-tpu analyze --json` output.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class Severity(enum.IntEnum):
    INFO = 10
    WARNING = 20
    ERROR = 30

    def __str__(self) -> str:
        return self.name.lower()

    @classmethod
    def parse(cls, name: str) -> "Severity":
        return cls[name.upper()]


# code -> (default severity, short title).  Codes are append-only: once
# published they keep their meaning, tooling may match on them.
CODES: Dict[str, tuple] = {
    # PWT1xx — correctness
    "PWT101": (Severity.WARNING, "lossy numeric cast"),
    "PWT102": (Severity.ERROR, "comparison between incompatible dtypes"),
    "PWT103": (Severity.WARNING, "arithmetic on optional operand"),
    "PWT110": (Severity.WARNING, "dead subgraph never reaches a sink"),
    "PWT111": (Severity.INFO, "unused column"),
    # PWT2xx — state growth / robustness
    "PWT201": (Severity.WARNING, "temporal operator without behavior"),
    "PWT202": (Severity.WARNING, "groupby key of unbounded cardinality"),
    "PWT203": (Severity.WARNING, "iterate without iteration_limit"),
    # PWT3xx — performance
    "PWT301": (Severity.INFO, "join falls back to the classic path"),
    "PWT302": (Severity.WARNING, "unroutable routing dtype on exchange"),
    "PWT303": (Severity.INFO, "reduce falls back to the classic path"),
    "PWT304": (Severity.INFO, "flatten vector path disabled"),
    "PWT305": (Severity.WARNING, "non-deterministic UDF feeds stateful operator"),
    "PWT306": (Severity.WARNING, "async/blocking UDF on exchange-crossing path"),
    "PWT399": (Severity.ERROR, "analyzer prediction disagrees with built plan"),
    # PWT4xx — accelerator utilization / mesh compatibility
    "PWT401": (Severity.WARNING, "embedder batch shape wastes MXU on padding"),
    "PWT402": (Severity.ERROR, "embedding shape incompatible with mesh axes"),
    "PWT403": (Severity.WARNING, "reducer is not shardable across the mesh"),
    "PWT404": (Severity.WARNING, "exchange sharding disagrees with mesh axes"),
    "PWT405": (Severity.WARNING, "single-worker-pinned source on a mesh"),
    # PWT5xx — fusion planning
    "PWT501": (Severity.INFO, "fusable select/filter chain found"),
    "PWT502": (Severity.INFO, "fusion chain broken by non-fusable operator"),
    "PWT503": (Severity.INFO, "fusion chain broken by fan-out"),
    "PWT504": (Severity.INFO, "UDF barrier blocks chain fusion"),
    "PWT599": (Severity.ERROR, "fusion plan disagrees with built nodes"),
    # PWT6xx — memory / capacity planning
    "PWT601": (Severity.INFO, "predicted device-memory footprint"),
    "PWT602": (Severity.WARNING, "external index without capacity info"),
    "PWT603": (Severity.ERROR, "predicted footprint exceeds device memory"),
    "PWT604": (Severity.WARNING, "predicted HBM headroom below threshold"),
    "PWT605": (Severity.INFO, "encoder params replicated per dp replica"),
    "PWT699": (Severity.ERROR, "capacity plan disagrees with live accounting"),
    # PWT7xx — serving tier (internals/serving.py)
    "PWT701": (Severity.WARNING, "serving enabled over a non-batchable index"),
    "PWT702": (Severity.WARNING, "serving batch window exceeds the SLO target"),
    # PWT8xx — cost attribution (internals/costledger.py)
    "PWT801": (Severity.WARNING, "tenant rate limits armed without query tracing"),
    "PWT802": (Severity.INFO, "cost ledger without a device-capacity entry"),
    # PWT9xx — determinism & replay safety (analysis/purity.py)
    "PWT901": (Severity.WARNING, "UDF reads a nondeterminism source"),
    "PWT902": (Severity.WARNING, "unordered set/dict iteration feeds UDF output"),
    "PWT903": (Severity.WARNING, "replay-unsafe side effect in UDF"),
    "PWT904": (Severity.WARNING, "UDF closure captures unpicklable state"),
    "PWT905": (Severity.WARNING, "UDF mutates its input rows"),
    "PWT999": (Severity.ERROR, "determinism contract disagrees with purity analysis"),
    # PWT10xx — provenance / lineage coverage (analysis/provenance.py)
    "PWT1001": (Severity.WARNING, "lineage-opaque operator on an anchored path"),
    "PWT1099": (Severity.ERROR, "explain required but graph contains an opaque node"),
}

# PWT family prefix -> (family name, owning pass) — the `analyze
# --list-codes` table and the doc-sync guard derive from this instead of
# hand-maintained doc tables.
FAMILIES: Dict[str, tuple] = {
    "PWT1": ("correctness", "dtype_pass / dead_pass"),
    "PWT2": ("state growth", "state_pass"),
    "PWT3": ("performance", "columnar_pass / udf_pass / verify_against_plan"),
    "PWT4": ("mesh compatibility", "mesh_pass / embedder_pass"),
    "PWT5": ("fusion planning", "fusion_pass / verify_fusion"),
    "PWT6": ("capacity planning", "capacity_pass / verify_capacity"),
    "PWT7": ("serving", "serving_pass"),
    "PWT8": ("cost attribution", "cost_pass"),
    "PWT9": ("determinism", "purity_pass / verify_purity"),
    "PWT10": ("provenance", "provenance_pass"),
}

# JSON schema version for analyze --json payloads and the golden matrix.
# Bump when the payload shape changes (v2: schema_version stamp itself,
# deterministic finding order, the "fusion" plan section; v3: the
# "capacity" plan section; v4: the "purity" verdict section).
SCHEMA_VERSION = 4


def _trace_to_dict(trace: Any) -> Optional[Dict[str, Any]]:
    if trace is None:
        return None
    if isinstance(trace, dict):
        # already converted — passes that emit several findings for one
        # operator convert once and share the dict (read-only by
        # convention; Diagnostic.to_dict copies on serialization)
        return trace
    return {
        "file": trace.file,
        "line": trace.line,
        "function": trace.function,
        "line_text": trace.line_text,
    }


@dataclass
class Diagnostic:
    code: str
    message: str
    severity: Severity
    # user frame, as a plain dict (file/line/function/line_text); None for
    # synthetic operators with no user frame
    trace: Optional[Dict[str, Any]] = None
    # always-present fallback location: "kind#op_id" (+ graph path) — the
    # finding is never dropped just because the trace is missing
    operator: Optional[str] = None
    details: Dict[str, Any] = field(default_factory=dict)

    def location(self) -> str:
        if self.trace is not None:
            loc = f"{self.trace['file']}:{self.trace['line']}"
            if self.trace.get("line_text"):
                return f"{loc}: {self.trace['line_text']}"
            return loc
        if self.operator:
            return f"<{self.operator}>"
        return "<unknown>"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "code": self.code,
            "severity": str(self.severity),
            "message": self.message,
            "trace": dict(self.trace) if self.trace is not None else None,
            "operator": self.operator,
            "details": dict(self.details),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Diagnostic":
        return cls(
            code=d["code"],
            message=d["message"],
            severity=Severity.parse(d["severity"]),
            trace=dict(d["trace"]) if d.get("trace") is not None else None,
            operator=d.get("operator"),
            details=dict(d.get("details", {})),
        )


def make_diag(
    code: str,
    message: str,
    *,
    trace: Any = None,
    operator: Optional[str] = None,
    severity: Optional[Severity] = None,
    **details: Any,
) -> Diagnostic:
    default_sev, _title = CODES[code]
    return Diagnostic(
        code=code,
        message=message,
        severity=severity if severity is not None else default_sev,
        trace=_trace_to_dict(trace),
        operator=operator,
        details=details,
    )


def _finding_sort_key(f: Diagnostic) -> tuple:
    """Deterministic order regardless of pass/thread scheduling: (code,
    trace location, operator, message).  Applied before every render and
    serialization so golden-matrix comparisons cannot flake."""
    trace = f.trace or {}
    return (
        f.code,
        trace.get("file") or "",
        trace.get("line") or 0,
        f.operator or "",
        f.message,
    )


@dataclass
class AnalysisResult:
    findings: List[Diagnostic] = field(default_factory=list)
    # columnar-eligibility predictions, one per join/reduce/flatten op:
    # {"op", "op_id", "predicted": "columnar"|"classic", "reasons": [...],
    #  "trace": {...}|None}
    predictions: List[Dict[str, Any]] = field(default_factory=list)
    # FusionPlan section, attached by fusion_pass.  Holds either the
    # serialized dict or the live FusionPlan object (serialized lazily
    # on first read — the common pw.run path never reads it)
    _fusion: Any = field(default=None, repr=False)
    # capacity-plan section (analysis/capacity.py): predicted per-index /
    # per-device byte breakdown; None when the graph has no external index
    capacity: Optional[Dict[str, Any]] = None
    # purity-verdict section (analysis/purity.py): callable name ->
    # {"verdict": "deterministic"|"impure"|"unknown", "codes": [...]};
    # None when the graph has no UDF call sites
    purity: Optional[Dict[str, Any]] = None

    @property
    def fusion(self) -> Optional[Dict[str, Any]]:
        src = self._fusion
        if src is not None and not isinstance(src, dict):
            src = self._fusion = src.to_dict()
        return src

    @fusion.setter
    def fusion(self, value: Any) -> None:
        self._fusion = value

    def add(self, diag: Diagnostic) -> None:
        self.findings.append(diag)

    def sorted_findings(self) -> List[Diagnostic]:
        return sorted(self.findings, key=_finding_sort_key)

    def max_severity(self) -> Optional[Severity]:
        if not self.findings:
            return None
        return max(f.severity for f in self.findings)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[str(f.severity)] = out.get(str(f.severity), 0) + 1
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema_version": SCHEMA_VERSION,
            "findings": [f.to_dict() for f in self.sorted_findings()],
            "predictions": [dict(p) for p in self.predictions],
            "fusion": dict(self.fusion) if self.fusion is not None else None,
            "capacity": (
                dict(self.capacity) if self.capacity is not None else None
            ),
            "purity": (
                dict(self.purity) if self.purity is not None else None
            ),
            "summary": self.counts(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AnalysisResult":
        fusion = d.get("fusion")
        capacity = d.get("capacity")
        purity = d.get("purity")
        return cls(
            findings=[Diagnostic.from_dict(f) for f in d.get("findings", [])],
            predictions=[dict(p) for p in d.get("predictions", [])],
            _fusion=dict(fusion) if fusion is not None else None,
            capacity=dict(capacity) if capacity is not None else None,
            purity=dict(purity) if purity is not None else None,
        )

    def render_text(self) -> str:
        lines: List[str] = []
        order = sorted(
            self.sorted_findings(), key=lambda f: (-int(f.severity), f.code)
        )
        for f in order:
            _sev, title = CODES.get(f.code, (Severity.INFO, ""))
            lines.append(f"{f.severity}: {f.code} [{title}]")
            lines.append(f"  {f.message}")
            lines.append(f"  at {f.location()}")
            for key, value in sorted(f.details.items()):
                lines.append(f"  {key}: {value}")
        counts = self.counts()
        if counts:
            summary = ", ".join(
                f"{counts[k]} {k}" for k in ("error", "warning", "info")
                if k in counts
            )
            lines.append(f"{len(self.findings)} finding(s): {summary}")
        else:
            lines.append("no findings")
        return "\n".join(lines)
