"""PWT10xx — record-level lineage coverage (internals/provenance.py).

The provenance tracker reconstructs a row's backward lineage from the
edges the hooked operators record (sources, joins, groupbys, flatten,
fused chains, external indexes) plus the key-preserving operators it
can walk through for free (select/filter/exchange never change keys).
Some operators are neither: they derive output keys the tracker has no
hook for, so a backward BFS that reaches them dead-ends with no path
to a source offset.  That is knowable at BUILD time:

  * PWT1001 — a lineage-opaque operator sits on an anchored path while
    the tracker is armed: `explain` trees that cross it will terminate
    early ("source / untracked") instead of reaching connector offsets.
  * PWT1099 — the job declared that explain MUST work end to end
    (`PATHWAY_PROVENANCE_REQUIRE=1`) but the graph contains an opaque
    operator, so the declaration is unmeetable by construction.  ERROR:
    strict mode aborts the run (the PWT399/599/699/999 parity-gate
    pattern).

The pass only runs when the tracker is armed (`PATHWAY_PROVENANCE=1`):
an unarmed job records no lineage, so opacity costs nothing.
"""

from __future__ import annotations

import os
from typing import Any

from pathway_tpu.analysis.diagnostics import AnalysisResult, make_diag

# Operators whose output keys are derived with no lineage hook: the
# tracker cannot map an output row of these back to its input rows.
# Key-preserving kinds (select/filter/copy/concat/...) are deliberately
# absent — the BFS walks through them without needing an edge — and the
# hooked kinds (join/reduce/flatten/external_index) record their own.
OPAQUE_KINDS = {
    "reindex",      # re-keys rows by an arbitrary expression
    "ix",           # output keyed by another table's indexer column
    "deduplicate",  # instance-derived keys, acc-dependent emission
    "iterate",      # nested subgraph; inner edges are not recorded
}


def provenance_pass(view: Any, result: AnalysisResult) -> None:
    """PWT1001 per anchored lineage-opaque operator; PWT1099 when
    PATHWAY_PROVENANCE_REQUIRE=1 promises end-to-end explain anyway."""
    from pathway_tpu.internals import provenance

    if not provenance.ACTIVE:
        return
    opaque = []
    for kind in sorted(OPAQUE_KINDS):
        opaque.extend(view.anchored_by_kind.get(kind, ()))
    if not opaque:
        return
    for table, op in opaque:
        result.add(make_diag(
            "PWT1001",
            f"`{op.kind}` derives its output keys without a lineage "
            "hook: the provenance tracker records no edge here, so an "
            "`explain` of any downstream row stops at this operator "
            "instead of reaching source-connector offsets; restructure "
            "with a hooked operator (join/groupby/flatten) or accept "
            "the truncated tree",
            trace=getattr(table, "_trace", None),
            operator=view.op_label(table),
            kind=op.kind,
        ))
    if os.environ.get("PATHWAY_PROVENANCE_REQUIRE") == "1":
        table, op = opaque[0]
        result.add(make_diag(
            "PWT1099",
            "PATHWAY_PROVENANCE_REQUIRE=1 declares that every output "
            f"row must explain back to a source offset, but {len(opaque)} "
            "lineage-opaque operator(s) sit on anchored paths (see "
            "PWT1001) — the declaration is unmeetable by construction",
            trace=getattr(table, "_trace", None),
            operator=view.op_label(table),
            opaque_count=len(opaque),
            kinds=sorted({o.kind for _t, o in opaque}),
        ))
