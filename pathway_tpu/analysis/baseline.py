"""Diagnostic baselines: adopt strict analysis on an existing graph.

`pathway-tpu analyze --baseline findings.json` (and
`pw.run(analysis_baseline=...)`) snapshots the current findings on the
first run, then suppresses exact matches on later runs — `--fail-on` and
strict mode only see NEW findings.  The baseline file is the reviewable
artifact: full finding dicts under a schema_version stamp, so a
teammate can read exactly what was grandfathered in.

A finding matches the baseline when (code, message, location) agree;
location is the user trace file:line when present, else the operator
label.  Message text participates on purpose — a finding whose numbers
changed (e.g. predicted pad waste) is news, not noise.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Set, Tuple

from pathway_tpu.analysis.diagnostics import (
    SCHEMA_VERSION,
    AnalysisResult,
    Diagnostic,
)


def finding_key(f: Diagnostic) -> Tuple[str, str, str]:
    trace = f.trace or {}
    if trace.get("file"):
        loc = f"{trace['file']}:{trace.get('line')}"
    else:
        loc = f.operator or ""
    return (f.code, f.message, loc)


def write_baseline(path: str, result: AnalysisResult) -> int:
    payload = {
        "schema_version": SCHEMA_VERSION,
        "findings": [f.to_dict() for f in result.sorted_findings()],
    }
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(result.findings)


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    with open(path) as fh:
        payload = json.load(fh)
    return {
        finding_key(Diagnostic.from_dict(d))
        for d in payload.get("findings", ())
    }


def apply_baseline(result: AnalysisResult, path: str) -> Dict[str, Any]:
    """Mutate `result` to only hold findings NOT in the baseline at
    `path`; create the baseline from the current findings when the file
    does not exist yet.  Returns a summary dict for reports/JSON."""
    if not os.path.exists(path):
        count = write_baseline(path, result)
        result.findings = []
        return {"file": path, "created": True, "suppressed": count}
    known = load_baseline(path)
    kept = [f for f in result.findings if finding_key(f) not in known]
    suppressed = len(result.findings) - len(kept)
    result.findings = kept
    return {"file": path, "created": False, "suppressed": suppressed}
