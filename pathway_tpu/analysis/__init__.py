"""Build-time static analysis of the dataflow graph.

`analyze()` walks the recorded parse graph (see
`internals/parse_graph.OpSpec`) and returns an `AnalysisResult` of
structured diagnostics — stable PWT codes, user stack frames, rendered
expressions — plus per-node columnar-eligibility predictions.

Three surfaces consume it:
  * `pathway-tpu analyze script.py` (cli.py) — text/JSON, --fail-on for CI
  * `pw.run(analysis="strict"|"warn"|"off")` (internals/runner.py)
  * the `/status` observability endpoint (internals/monitoring.py)
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

from pathway_tpu.analysis.diagnostics import (
    CODES,
    FAMILIES,
    SCHEMA_VERSION,
    AnalysisResult,
    Diagnostic,
    Severity,
    make_diag,
)
from pathway_tpu.analysis.capacity import capacity_pass, verify_capacity
from pathway_tpu.analysis.cost import cost_pass
from pathway_tpu.analysis.fusion import FusionChain, FusionPlan, plan_fusion
from pathway_tpu.analysis.graph import GraphView
from pathway_tpu.analysis.mesh import MeshSpec
from pathway_tpu.analysis.purity import (
    classify_callable,
    purity_pass,
    verify_purity,
)
from pathway_tpu.analysis.provenance import provenance_pass
from pathway_tpu.analysis.serving import serving_pass
from pathway_tpu.analysis.passes import (
    columnar_pass,
    dead_pass,
    dtype_pass,
    embedder_pass,
    fusion_pass,
    mesh_pass,
    state_pass,
    udf_pass,
    verify_against_plan,
    verify_fusion,
)


class AnalysisError(RuntimeError):
    """Raised by pw.run(analysis="strict") when the analyzer finds
    warning-or-worse diagnostics."""

    def __init__(self, result: AnalysisResult):
        self.result = result
        super().__init__(
            "static analysis failed:\n" + result.render_text()
        )


def _worker_count() -> int:
    from pathway_tpu.internals.config import pathway_config

    threads = getattr(pathway_config, "threads", 1) or 1
    processes = getattr(pathway_config, "processes", 1) or 1
    return max(threads, 1) * max(processes, 1)


def analyze(
    graph: Any = None,
    *,
    extra_tables: Iterable[Any] = (),
    workers: Optional[int] = None,
    mesh: Any = None,
    slo: Optional[float] = None,
) -> AnalysisResult:
    """Run every pass over `graph` (default: the global parse graph).

    `extra_tables` anchors tables that are not registered as sinks (e.g.
    run_tables captures); `workers` overrides the configured worker
    count for the exchange-related lints; `mesh` (a MeshSpec,
    "dp=4,tp=2" string or mapping) additionally runs the PWT4xx
    mesh-compatibility pass against that device topology; `slo` is the
    declared p99 target in milliseconds (pw.run(slo=)), consumed by the
    PWT70x serving lints (PATHWAY_SLO_P99_MS is the fallback)."""
    if graph is None:
        from pathway_tpu.internals.parse_graph import G as graph
    if workers is None:
        workers = _worker_count()
    if mesh is not None:
        mesh = MeshSpec.parse(mesh)
    view = GraphView(graph, extra_tables=extra_tables)
    result = AnalysisResult()
    dtype_pass(view, result)
    state_pass(view, result)
    columnar_pass(view, result, workers=workers)
    dead_pass(view, result)
    udf_pass(view, result, workers=workers)
    purity_pass(view, result, workers=workers)
    embedder_pass(view, result, workers=workers)
    fusion_pass(view, result)
    mesh_pass(view, result, mesh=mesh, workers=workers)
    capacity_pass(view, result, mesh=mesh, workers=workers)
    serving_pass(view, result, slo=slo)
    cost_pass(view, result)
    provenance_pass(view, result)
    return result


__all__ = [
    "AnalysisError",
    "AnalysisResult",
    "CODES",
    "Diagnostic",
    "FAMILIES",
    "FusionChain",
    "FusionPlan",
    "GraphView",
    "MeshSpec",
    "SCHEMA_VERSION",
    "Severity",
    "analyze",
    "capacity_pass",
    "classify_callable",
    "cost_pass",
    "make_diag",
    "plan_fusion",
    "provenance_pass",
    "purity_pass",
    "serving_pass",
    "verify_against_plan",
    "verify_capacity",
    "verify_fusion",
    "verify_purity",
]
