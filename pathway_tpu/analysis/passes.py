"""The analyzer passes.

Each pass takes the `GraphView` and appends findings / predictions to an
`AnalysisResult`.  Passes only report what they can prove from recorded
ops, markers and schemas — anything uninferable stays silent (a lint
that guesses is worse than no lint).

The columnar-eligibility pass does not re-implement the runtime gates:
joins expose `_columnar_reasons()` next to `_join_keys_hashable()`,
reduce records the gate outcome (`use_vector` + reasons) on its OpSpec
from the very variable the build closure captures, and flatten asks
`vector_flatten_supported()`.  Prediction and selection share one source
of truth, which is what lets `verify_against_plan` treat a mismatch as
an internal error (PWT399) rather than an expected drift.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from pathway_tpu.analysis.diagnostics import AnalysisResult, make_diag
from pathway_tpu.analysis.graph import GraphView, infer, op_exprs, walk_expr
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ApplyExpression,
    BinaryOpExpression,
    CastExpression,
    ColumnReference,
    IdReference,
)
from pathway_tpu.internals.expression_printer import print_expression

_ARITH_OPS = {"+", "-", "*", "/", "//", "%", "**"}
_COMPARE_OPS = {"==", "!=", "<", "<=", ">", ">="}
# dtypes whose values ref_scalar can always hash — the exchange layer
# routes by that hash; anything else risks the unroutable-to-worker-0
# fallback (engine/exchange.py _Route.codes)
_ROUTABLE_CORES = (
    dt.STR, dt.INT, dt.FLOAT, dt.BOOL, dt.BYTES, dt.POINTER,
    dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC, dt.DURATION, dt.NONE,
)
# op kinds that accumulate state keyed on their input rows: a
# non-deterministic UDF upstream of one of these makes retractions
# recompute a *different* value, so deletions stop cancelling insertions
STATEFUL_KINDS = {
    "reduce", "join", "semijoin", "deduplicate", "sort", "iterate",
    "clocked", "stream_to_table", "merge_streams", "gradual_broadcast",
    "ix", "reindex",
}
# kinds whose engine nodes sit behind an exchange on multi-worker runs
_EXCHANGE_KINDS = {"reduce", "join", "semijoin", "deduplicate", "sort"}


def _trace_or_none(table: Any):
    return getattr(table, "_trace", None)


def _core(d: Optional[dt.DType]) -> Optional[dt.DType]:
    if d is None:
        return None
    if isinstance(d, dt.Optionalized):
        d = dt.unoptionalize(d)
    return d


# ---------------------------------------------------------------------------
# Pass 1 — dtype / coercion checks (PWT101, PWT102, PWT103)
# ---------------------------------------------------------------------------

_NUMERIC = (dt.INT, dt.FLOAT, dt.BOOL)


def _comparable(a: dt.DType, b: dt.DType) -> bool:
    if a == b:
        return True
    if a in _NUMERIC and b in _NUMERIC:
        return True
    # naive/utc datetimes, durations etc. must match exactly; ANY-family
    # and container dtypes are handled by the caller (skipped)
    return False


def dtype_pass(view: GraphView, result: AnalysisResult) -> None:
    for table, op in view.ops():
        if op.synthetic:
            continue
        seen_nodes: Set[int] = set()
        for expr in op_exprs(op):
            for node in walk_expr(expr):
                if id(node) in seen_nodes:
                    continue  # shared subexpressions report once
                seen_nodes.add(id(node))
                trace = _trace_or_none(table)
                operator = view.op_label(table)
                if isinstance(node, CastExpression):
                    inner = _core(infer(node._expr))
                    target = _core(node._target)
                    if inner is dt.FLOAT and target is dt.INT:
                        result.add(make_diag(
                            "PWT101",
                            "cast from float to int truncates: "
                            f"{print_expression(node)}",
                            trace=trace, operator=operator,
                            expression=print_expression(node),
                        ))
                elif isinstance(node, BinaryOpExpression):
                    lhs = infer(node._left)
                    rhs = infer(node._right)
                    lc, rc = _core(lhs), _core(rhs)
                    if lc is None or rc is None:
                        continue
                    simple = (
                        lc in _ROUTABLE_CORES and rc in _ROUTABLE_CORES
                    )
                    if (
                        node._op in _COMPARE_OPS
                        and simple
                        and not _comparable(lc, rc)
                    ):
                        result.add(make_diag(
                            "PWT102",
                            f"comparison {print_expression(node)} mixes "
                            f"incompatible dtypes {lhs} and {rhs}",
                            trace=trace, operator=operator,
                            expression=print_expression(node),
                            left_dtype=str(lhs), right_dtype=str(rhs),
                        ))
                    elif node._op in _ARITH_OPS and (
                        isinstance(lhs, dt.Optionalized)
                        or isinstance(rhs, dt.Optionalized)
                    ):
                        if lc in _NUMERIC and rc in _NUMERIC:
                            result.add(make_diag(
                                "PWT103",
                                "arithmetic on optional operand "
                                f"{print_expression(node)} silently "
                                "propagates None",
                                trace=trace, operator=operator,
                                expression=print_expression(node),
                            ))


# ---------------------------------------------------------------------------
# Pass 2 — state growth (PWT201, PWT202, PWT203)
# ---------------------------------------------------------------------------

# temporal entry points that accept behavior=; window_join has no such
# knob, so flagging it would be unsatisfiable noise
_BEHAVIORAL_TEMPORAL = {"windowby", "interval_join", "asof_join"}


def state_pass(view: GraphView, result: AnalysisResult) -> None:
    for marker in view.markers:
        if (
            marker.kind in _BEHAVIORAL_TEMPORAL
            and not marker.info.get("has_behavior")
        ):
            result.add(make_diag(
                "PWT201",
                f"{marker.kind} without behavior= keeps every row "
                "forever; pass pw.temporal.common_behavior(...) to bound "
                "state",
                trace=marker.trace, operator=marker.kind,
                temporal_op=marker.kind,
            ))
    for table, op in view.ops():
        if op.kind == "reduce" and not op.synthetic:
            for g in op.exprs.get("grouping", ()):
                gd = infer(g)
                core = _core(gd)
                if core is dt.FLOAT or core is dt.ANY:
                    result.add(make_diag(
                        "PWT202",
                        f"groupby key {print_expression(g)} has "
                        f"unbounded-cardinality dtype {gd}: every "
                        "distinct value becomes a group held in state",
                        trace=_trace_or_none(table),
                        operator=view.op_label(table),
                        key=print_expression(g), dtype=str(gd),
                    ))
        elif op.kind == "iterate" and op.info.get("iteration_limit") is None:
            result.add(make_diag(
                "PWT203",
                "iterate without iteration_limit= may never converge on "
                "adversarial input; bound it or document why the "
                "fixpoint is guaranteed",
                trace=_trace_or_none(table),
                operator=view.op_label(table),
            ))


# ---------------------------------------------------------------------------
# Pass 3 — columnar eligibility + predictions (PWT301..PWT304)
# ---------------------------------------------------------------------------

def _routable(d: Optional[dt.DType]) -> bool:
    """Can ref_scalar hash every value of this dtype?  Containers of
    routable dtypes hash fine; Json / ANY / arrays may not."""
    core = _core(d)
    if core is None:
        return False
    if core in _ROUTABLE_CORES:
        return True
    if isinstance(core, dt.TupleDType):
        return all(_routable(a) for a in core.args)
    if isinstance(core, dt.ListDType):
        return _routable(core.arg)
    return False


def _prediction(
    view: GraphView,
    table: Any,
    op_kind: str,
    op_id: int,
    reasons: List[str],
) -> Dict[str, Any]:
    from pathway_tpu.analysis.diagnostics import _trace_to_dict

    return {
        "op": op_kind,
        "op_id": op_id,
        "predicted": "classic" if reasons else "columnar",
        "reasons": list(reasons),
        "trace": _trace_to_dict(_trace_or_none(table)),
        "operator": view.op_label(table),
        "anchored": view.is_anchored(table),
    }


def columnar_pass(
    view: GraphView, result: AnalysisResult, *, workers: int = 1
) -> None:
    from pathway_tpu.engine.vector_flatten import vector_flatten_supported

    seen_joins: Set[int] = set()
    for table, op in view.ops():
        trace = _trace_or_none(table)
        operator = view.op_label(table)
        if op.kind == "join":
            from pathway_tpu.internals.joins import JoinResult

            jr = op.info.get("join_result")
            if jr is None or id(jr) in seen_joins:
                continue  # several selects on one JoinResult share a node
            seen_joins.add(id(jr))
            # temporal subclasses (interval/asof) build their own node
            # kinds — the vector-join gate does not apply to them
            if type(jr) is JoinResult:
                reasons = jr._columnar_reasons()
                result.predictions.append(
                    _prediction(view, table, "join", op.op_id, reasons)
                )
                if reasons:
                    result.add(make_diag(
                        "PWT301",
                        "join cannot take the columnar path: "
                        + "; ".join(reasons),
                        trace=trace, operator=operator, reasons=reasons,
                    ))
            if workers > 1:
                for key in (
                    list(op.exprs.get("on_left", ()))
                    + list(op.exprs.get("on_right", ()))
                ):
                    if not _routable(infer(key)):
                        result.add(make_diag(
                            "PWT302",
                            f"join key {print_expression(key)} has "
                            f"dtype {infer(key)} the exchange layer "
                            "cannot hash: rows pile up on worker 0 "
                            "(pathway_exchange_unroutable_rows)",
                            trace=trace, operator=operator,
                            key=print_expression(key),
                        ))
        elif op.kind == "reduce":
            reasons = list(op.info.get("vector_reasons", ()))
            result.predictions.append(
                _prediction(view, table, "reduce", op.op_id, reasons)
            )
            if reasons and not op.synthetic:
                result.add(make_diag(
                    "PWT303",
                    "reduce cannot take the columnar path: "
                    + "; ".join(reasons),
                    trace=trace, operator=operator, reasons=reasons,
                ))
            if workers > 1 and not op.synthetic:
                for g in op.exprs.get("grouping", ()):
                    if not _routable(infer(g)):
                        result.add(make_diag(
                            "PWT302",
                            f"groupby key {print_expression(g)} has "
                            f"dtype {infer(g)} the exchange layer "
                            "cannot hash: rows pile up on worker 0 "
                            "(pathway_exchange_unroutable_rows)",
                            trace=trace, operator=operator,
                            key=print_expression(g),
                        ))
        elif op.kind == "flatten":
            reasons = (
                []
                if vector_flatten_supported()
                else ["vector flatten disabled by configuration"]
            )
            result.predictions.append(
                _prediction(view, table, "flatten", op.op_id, reasons)
            )
            if reasons:
                result.add(make_diag(
                    "PWT304",
                    "flatten runs the classic row-wise path: "
                    + "; ".join(reasons),
                    trace=trace, operator=operator, reasons=reasons,
                ))


# ---------------------------------------------------------------------------
# Pass 4 — dead subgraphs and unused columns (PWT110, PWT111)
# ---------------------------------------------------------------------------

def dead_pass(view: GraphView, result: AnalysisResult) -> None:
    if not view.sink_tables:
        return  # nothing is anchored; "everything is dead" is not useful
    for table, op in view.ops():
        if op.synthetic or view.is_anchored(table):
            continue
        # report only subgraph leaves (no consumers): the table the user
        # computed and dropped, not every op that fed it
        if view.consumers.get(id(table)):
            continue
        result.add(make_diag(
            "PWT110",
            f"result of {op.kind} is never written to a sink: the "
            "subgraph computes rows nobody reads",
            trace=_trace_or_none(table),
            operator=view.op_label(table),
        ))

    # backward column liveness over the anchored region
    live: Dict[int, Set[str]] = {
        id(t): set(t.column_names()) for t in view.sink_tables
    }
    by_id = {id(t): t for t in view.anchored}
    work = list(view.sink_tables)

    def mark(tbl: Any, col: str) -> None:
        s = live.setdefault(id(tbl), set())
        if col not in s:
            s.add(col)
            work.append(tbl)

    def mark_refs(expr: Any) -> None:
        for node in walk_expr(expr):
            if isinstance(node, ColumnReference) and not isinstance(
                node, IdReference
            ):
                mark(node._table, node._name)

    processed: Set[tuple] = set()
    while work:
        t = work.pop()
        op = getattr(t, "_op", None)
        if op is None:
            continue
        out_live = frozenset(live.get(id(t), ()))
        key = (id(t), out_live)
        if key in processed:
            continue
        processed.add(key)
        if op.kind == "select":
            for name in out_live:
                expr = op.exprs.get("cols", {}).get(name)
                if expr is not None:
                    mark_refs(expr)
        elif op.kind == "filter":
            (inp,) = op.inputs
            for name in out_live:
                mark(inp, name)
            mark_refs(op.exprs.get("expr"))
        else:
            # conservative: the op may read anything from its inputs
            for inp in op.inputs:
                for name in inp.column_names():
                    mark(inp, name)
            for expr in op_exprs(op):
                mark_refs(expr)

    for t in view.anchored:
        op = getattr(t, "_op", None)
        if op is None or op.kind != "select" or op.synthetic:
            continue
        if not view.consumers.get(id(t)):
            continue  # sink-written tables keep every column
        unused = sorted(set(t.column_names()) - live.get(id(t), set()))
        for name in unused:
            result.add(make_diag(
                "PWT111",
                f"column {name!r} is computed but never read "
                "downstream",
                trace=_trace_or_none(t),
                operator=view.op_label(t),
                column=name,
            ))


# ---------------------------------------------------------------------------
# Pass 5 — UDF hazards (PWT305, PWT306)
# ---------------------------------------------------------------------------

def udf_pass(
    view: GraphView, result: AnalysisResult, *, workers: int = 1
) -> None:
    for table, op in view.ops():
        if op.synthetic:
            continue
        stateful_here = op.kind in STATEFUL_KINDS
        reaches_stateful = stateful_here or view.reaches_kind(
            table, STATEFUL_KINDS
        )
        crosses_exchange = workers > 1 and (
            op.kind in _EXCHANGE_KINDS
            or view.reaches_kind(table, _EXCHANGE_KINDS)
        )
        seen: Set[int] = set()
        for expr in op_exprs(op):
            for node in walk_expr(expr):
                if not isinstance(node, ApplyExpression):
                    continue
                if id(node) in seen:
                    continue
                seen.add(id(node))
                fname = getattr(node._fun, "__name__", "<udf>")
                if not node._deterministic and reaches_stateful:
                    result.add(make_diag(
                        "PWT305",
                        f"UDF {fname!r} is not marked deterministic but "
                        "feeds a stateful operator: retractions recompute "
                        "it and may not cancel the original insertion "
                        "(mark it @pw.udf(deterministic=True) if it is)",
                        trace=_trace_or_none(table),
                        operator=view.op_label(table),
                        udf=fname,
                    ))
                if node._is_async and crosses_exchange:
                    result.add(make_diag(
                        "PWT306",
                        f"async UDF {fname!r} sits on an exchange-"
                        "crossing path: its completion times differ per "
                        "worker, so downstream keyed state sees "
                        "interleavings that are hard to reproduce",
                        trace=_trace_or_none(table),
                        operator=view.op_label(table),
                        udf=fname,
                    ))


# ---------------------------------------------------------------------------
# Pass 6 — embedder batch-shape waste (PWT401)
# ---------------------------------------------------------------------------

# Deterministic stand-in for typical short-document corpora (final token
# counts per doc, CLS/SEP included — roughly what bench.py's synthetic
# ingest feeds the embedder). The lint is a shape argument, not a data
# argument: any distribution with mean/max in this range predicts the
# same verdict, and determinism keeps the golden matrix stable.
_SAMPLE_TOKEN_LENGTHS = (18, 24, 30, 34, 38, 42, 48, 56)
_PAD_WASTE_THRESHOLD = 0.5


def embedder_pass(
    view: GraphView, result: AnalysisResult, *, workers: int = 1
) -> None:
    """PWT401: embedder configs whose max_batch_size / bucket shape force
    most MXU cycles onto pad tokens. Embedder UDFs carry a `_pw_embedder`
    marker dict (xpacks/llm/embedders.py) with the shape facts, so the
    pass never builds a model."""
    from pathway_tpu.models.tokenizer import predict_pad_waste

    for table, op in view.ops():
        if op.synthetic:
            continue
        seen: Set[int] = set()
        for expr in op_exprs(op):
            for node in walk_expr(expr):
                if not isinstance(node, ApplyExpression):
                    continue
                if id(node) in seen:
                    continue
                seen.add(id(node))
                marker = getattr(node._fun, "_pw_embedder", None)
                if not isinstance(marker, dict):
                    continue
                batch = int(marker.get("max_batch_size") or 0)
                max_len = int(marker.get("max_len") or 512)
                if batch <= 0:
                    continue
                waste = predict_pad_waste(
                    _SAMPLE_TOKEN_LENGTHS, batch, max_len=max_len
                )
                if waste <= _PAD_WASTE_THRESHOLD:
                    continue
                fname = getattr(node._fun, "__name__", "<udf>")
                result.add(make_diag(
                    "PWT401",
                    f"embedder {fname!r} with max_batch_size={batch} "
                    f"predicts {round(100 * waste)}% padding waste on "
                    "sampled input lengths: the batch buckets to a power "
                    "of two (minimum 8) and every doc pads to the bucket "
                    "max, so most MXU cycles process pad tokens; raise "
                    "max_batch_size or keep packed ragged batching on "
                    "(PATHWAY_PACK_TOKEN_BUDGET > 0 with the default "
                    "PATHWAY_DEVICE_PIPELINE=1)",
                    trace=_trace_or_none(table),
                    operator=view.op_label(table),
                    udf=fname,
                    predicted_waste=round(waste, 3),
                    max_batch_size=batch,
                ))


# ---------------------------------------------------------------------------
# Plan verification (PWT399)
# ---------------------------------------------------------------------------

# engine node class name -> (op kind, selected path)
_NODE_PATHS = {
    "VectorJoinNode": ("join", "columnar"),
    "JoinNode": ("join", "classic"),
    "VectorReduceNode": ("reduce", "columnar"),
    "ReduceNode": ("reduce", "classic"),
    "VectorFlattenNode": ("flatten", "columnar"),
    "FlattenNode": ("flatten", "classic"),
}


def verify_against_plan(engine: Any, result: AnalysisResult) -> None:
    """Compare the analyzer's anchored columnar predictions against the
    node classes the build actually instantiated.  Counts (not per-node
    identity) — parse-level ops and engine nodes have no shared id, but
    every anchored join/reduce/flatten op builds exactly one node, so the
    histograms must agree."""
    predicted: Dict[tuple, int] = {}
    for p in result.predictions:
        if not p.get("anchored"):
            continue
        key = (p["op"], p["predicted"])
        predicted[key] = predicted.get(key, 0) + 1
    actual: Dict[tuple, int] = {}
    for node in getattr(engine, "nodes", ()):
        hit = _NODE_PATHS.get(type(node).__name__)
        if hit is not None:
            actual[hit] = actual.get(hit, 0) + 1
    for key in sorted(set(predicted) | set(actual)):
        if predicted.get(key, 0) != actual.get(key, 0):
            op_kind, path = key
            result.add(make_diag(
                "PWT399",
                f"analyzer predicted {predicted.get(key, 0)} {path} "
                f"{op_kind} node(s) but the built plan has "
                f"{actual.get(key, 0)} — the static gate and the build "
                "gate have drifted; please report this",
                operator=f"{op_kind}/{path}",
                predicted=predicted.get(key, 0),
                actual=actual.get(key, 0),
            ))
