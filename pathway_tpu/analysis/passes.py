"""The analyzer passes.

Each pass takes the `GraphView` and appends findings / predictions to an
`AnalysisResult`.  Passes only report what they can prove from recorded
ops, markers and schemas — anything uninferable stays silent (a lint
that guesses is worse than no lint).

The columnar-eligibility pass does not re-implement the runtime gates:
joins expose `_columnar_reasons()` next to `_join_keys_hashable()`,
reduce records the gate outcome (`use_vector` + reasons) on its OpSpec
from the very variable the build closure captures, and flatten asks
`vector_flatten_supported()`.  Prediction and selection share one source
of truth, which is what lets `verify_against_plan` treat a mismatch as
an internal error (PWT399) rather than an expected drift.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set

from pathway_tpu.analysis.diagnostics import AnalysisResult, make_diag
from pathway_tpu.analysis.graph import GraphView, infer, op_exprs, walk_expr
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    BinaryOpExpression,
    CastExpression,
    ColumnReference,
    IdReference,
)
from pathway_tpu.internals.expression_printer import print_expression

_ARITH_OPS = {"+", "-", "*", "/", "//", "%", "**"}
_COMPARE_OPS = {"==", "!=", "<", "<=", ">", ">="}
# dtypes whose values ref_scalar can always hash — the exchange layer
# routes by that hash; anything else risks the unroutable-to-worker-0
# fallback (engine/exchange.py _Route.codes)
_ROUTABLE_CORES = (
    dt.STR, dt.INT, dt.FLOAT, dt.BOOL, dt.BYTES, dt.POINTER,
    dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC, dt.DURATION, dt.NONE,
)
# op kinds that accumulate state keyed on their input rows: a
# non-deterministic UDF upstream of one of these makes retractions
# recompute a *different* value, so deletions stop cancelling insertions
STATEFUL_KINDS = {
    "reduce", "join", "semijoin", "deduplicate", "sort", "iterate",
    "clocked", "stream_to_table", "merge_streams", "gradual_broadcast",
    "ix", "reindex",
}
# kinds whose engine nodes sit behind an exchange on multi-worker runs
_EXCHANGE_KINDS = {"reduce", "join", "semijoin", "deduplicate", "sort"}


def _trace_or_none(table: Any):
    return getattr(table, "_trace", None)


def _core(d: Optional[dt.DType]) -> Optional[dt.DType]:
    if d is None:
        return None
    if isinstance(d, dt.Optionalized):
        d = dt.unoptionalize(d)
    return d


# ---------------------------------------------------------------------------
# Pass 1 — dtype / coercion checks (PWT101, PWT102, PWT103)
# ---------------------------------------------------------------------------

_NUMERIC = (dt.INT, dt.FLOAT, dt.BOOL)


def _comparable(a: dt.DType, b: dt.DType) -> bool:
    if a == b:
        return True
    if a in _NUMERIC and b in _NUMERIC:
        return True
    # naive/utc datetimes, durations etc. must match exactly; ANY-family
    # and container dtypes are handled by the caller (skipped)
    return False


def dtype_pass(view: GraphView, result: AnalysisResult) -> None:
    for table, op in view.ops():
        if op.synthetic:
            continue
        seen_nodes: Set[int] = set()
        for expr in op_exprs(op):
            for node in walk_expr(expr):
                if id(node) in seen_nodes:
                    continue  # shared subexpressions report once
                seen_nodes.add(id(node))
                trace = _trace_or_none(table)
                operator = view.op_label(table)
                if isinstance(node, CastExpression):
                    inner = _core(infer(node._expr))
                    target = _core(node._target)
                    if inner is dt.FLOAT and target is dt.INT:
                        result.add(make_diag(
                            "PWT101",
                            "cast from float to int truncates: "
                            f"{print_expression(node)}",
                            trace=trace, operator=operator,
                            expression=print_expression(node),
                        ))
                elif isinstance(node, BinaryOpExpression):
                    lhs = infer(node._left)
                    rhs = infer(node._right)
                    lc, rc = _core(lhs), _core(rhs)
                    if lc is None or rc is None:
                        continue
                    simple = (
                        lc in _ROUTABLE_CORES and rc in _ROUTABLE_CORES
                    )
                    if (
                        node._op in _COMPARE_OPS
                        and simple
                        and not _comparable(lc, rc)
                    ):
                        result.add(make_diag(
                            "PWT102",
                            f"comparison {print_expression(node)} mixes "
                            f"incompatible dtypes {lhs} and {rhs}",
                            trace=trace, operator=operator,
                            expression=print_expression(node),
                            left_dtype=str(lhs), right_dtype=str(rhs),
                        ))
                    elif node._op in _ARITH_OPS and (
                        isinstance(lhs, dt.Optionalized)
                        or isinstance(rhs, dt.Optionalized)
                    ):
                        if lc in _NUMERIC and rc in _NUMERIC:
                            result.add(make_diag(
                                "PWT103",
                                "arithmetic on optional operand "
                                f"{print_expression(node)} silently "
                                "propagates None",
                                trace=trace, operator=operator,
                                expression=print_expression(node),
                            ))


# ---------------------------------------------------------------------------
# Pass 2 — state growth (PWT201, PWT202, PWT203)
# ---------------------------------------------------------------------------

# temporal entry points that accept behavior=; window_join has no such
# knob, so flagging it would be unsatisfiable noise
_BEHAVIORAL_TEMPORAL = {"windowby", "interval_join", "asof_join"}


def state_pass(view: GraphView, result: AnalysisResult) -> None:
    for marker in view.markers:
        if (
            marker.kind in _BEHAVIORAL_TEMPORAL
            and not marker.info.get("has_behavior")
        ):
            result.add(make_diag(
                "PWT201",
                f"{marker.kind} without behavior= keeps every row "
                "forever; pass pw.temporal.common_behavior(...) to bound "
                "state",
                trace=marker.trace, operator=marker.kind,
                temporal_op=marker.kind,
            ))
    for table, op in view.ops():
        if op.kind == "reduce" and not op.synthetic:
            for g in op.exprs.get("grouping", ()):
                gd = infer(g)
                core = _core(gd)
                if core is dt.FLOAT or core is dt.ANY:
                    result.add(make_diag(
                        "PWT202",
                        f"groupby key {print_expression(g)} has "
                        f"unbounded-cardinality dtype {gd}: every "
                        "distinct value becomes a group held in state",
                        trace=_trace_or_none(table),
                        operator=view.op_label(table),
                        key=print_expression(g), dtype=str(gd),
                    ))
        elif op.kind == "iterate" and op.info.get("iteration_limit") is None:
            result.add(make_diag(
                "PWT203",
                "iterate without iteration_limit= may never converge on "
                "adversarial input; bound it or document why the "
                "fixpoint is guaranteed",
                trace=_trace_or_none(table),
                operator=view.op_label(table),
            ))


# ---------------------------------------------------------------------------
# Pass 3 — columnar eligibility + predictions (PWT301..PWT304)
# ---------------------------------------------------------------------------

def _routable(d: Optional[dt.DType]) -> bool:
    """Can ref_scalar hash every value of this dtype?  Containers of
    routable dtypes hash fine; Json / ANY / arrays may not."""
    core = _core(d)
    if core is None:
        return False
    if core in _ROUTABLE_CORES:
        return True
    if isinstance(core, dt.TupleDType):
        return all(_routable(a) for a in core.args)
    if isinstance(core, dt.ListDType):
        return _routable(core.arg)
    return False


def _prediction(
    view: GraphView,
    table: Any,
    op_kind: str,
    op_id: int,
    reasons: List[str],
) -> Dict[str, Any]:
    from pathway_tpu.analysis.diagnostics import _trace_to_dict

    return {
        "op": op_kind,
        "op_id": op_id,
        "predicted": "classic" if reasons else "columnar",
        "reasons": list(reasons),
        "trace": _trace_to_dict(_trace_or_none(table)),
        "operator": view.op_label(table),
        "anchored": view.is_anchored(table),
    }


def columnar_pass(
    view: GraphView, result: AnalysisResult, *, workers: int = 1
) -> None:
    from pathway_tpu.engine.vector_flatten import vector_flatten_supported

    seen_joins: Set[int] = set()
    for table, op in view.ops():
        trace = _trace_or_none(table)
        operator = view.op_label(table)
        if op.kind == "join":
            from pathway_tpu.internals.joins import JoinResult

            jr = op.info.get("join_result")
            if jr is None or id(jr) in seen_joins:
                continue  # several selects on one JoinResult share a node
            seen_joins.add(id(jr))
            # temporal subclasses (interval/asof) build their own node
            # kinds — the vector-join gate does not apply to them
            if type(jr) is JoinResult:
                reasons = jr._columnar_reasons()
                result.predictions.append(
                    _prediction(view, table, "join", op.op_id, reasons)
                )
                if reasons:
                    result.add(make_diag(
                        "PWT301",
                        "join cannot take the columnar path: "
                        + "; ".join(reasons),
                        trace=trace, operator=operator, reasons=reasons,
                    ))
            if workers > 1:
                for key in (
                    list(op.exprs.get("on_left", ()))
                    + list(op.exprs.get("on_right", ()))
                ):
                    if not _routable(infer(key)):
                        result.add(make_diag(
                            "PWT302",
                            f"join key {print_expression(key)} has "
                            f"dtype {infer(key)} the exchange layer "
                            "cannot hash: rows pile up on worker 0 "
                            "(pathway_exchange_unroutable_rows)",
                            trace=trace, operator=operator,
                            key=print_expression(key),
                        ))
        elif op.kind == "reduce":
            reasons = list(op.info.get("vector_reasons", ()))
            result.predictions.append(
                _prediction(view, table, "reduce", op.op_id, reasons)
            )
            if reasons and not op.synthetic:
                result.add(make_diag(
                    "PWT303",
                    "reduce cannot take the columnar path: "
                    + "; ".join(reasons),
                    trace=trace, operator=operator, reasons=reasons,
                ))
            if workers > 1 and not op.synthetic:
                for g in op.exprs.get("grouping", ()):
                    if not _routable(infer(g)):
                        result.add(make_diag(
                            "PWT302",
                            f"groupby key {print_expression(g)} has "
                            f"dtype {infer(g)} the exchange layer "
                            "cannot hash: rows pile up on worker 0 "
                            "(pathway_exchange_unroutable_rows)",
                            trace=trace, operator=operator,
                            key=print_expression(g),
                        ))
        elif op.kind == "flatten":
            reasons = (
                []
                if vector_flatten_supported()
                else ["vector flatten disabled by configuration"]
            )
            result.predictions.append(
                _prediction(view, table, "flatten", op.op_id, reasons)
            )
            if reasons:
                result.add(make_diag(
                    "PWT304",
                    "flatten runs the classic row-wise path: "
                    + "; ".join(reasons),
                    trace=trace, operator=operator, reasons=reasons,
                ))


# ---------------------------------------------------------------------------
# Pass 4 — dead subgraphs and unused columns (PWT110, PWT111)
# ---------------------------------------------------------------------------

def dead_pass(view: GraphView, result: AnalysisResult) -> None:
    if not view.sink_tables:
        return  # nothing is anchored; "everything is dead" is not useful
    for table, op in view.ops():
        if op.synthetic or view.is_anchored(table):
            continue
        # report only subgraph leaves (no consumers): the table the user
        # computed and dropped, not every op that fed it
        if view.consumers.get(id(table)):
            continue
        result.add(make_diag(
            "PWT110",
            f"result of {op.kind} is never written to a sink: the "
            "subgraph computes rows nobody reads",
            trace=_trace_or_none(table),
            operator=view.op_label(table),
        ))

    # backward column liveness over the anchored region
    live: Dict[int, Set[str]] = {
        id(t): set(t.column_names()) for t in view.sink_tables
    }
    by_id = {id(t): t for t in view.anchored}
    work = list(view.sink_tables)

    def mark(tbl: Any, col: str) -> None:
        s = live.setdefault(id(tbl), set())
        if col not in s:
            s.add(col)
            work.append(tbl)

    def mark_refs(expr: Any) -> None:
        for node in walk_expr(expr):
            if isinstance(node, ColumnReference) and not isinstance(
                node, IdReference
            ):
                mark(node._table, node._name)

    processed: Set[tuple] = set()
    while work:
        t = work.pop()
        op = getattr(t, "_op", None)
        if op is None:
            continue
        out_live = frozenset(live.get(id(t), ()))
        key = (id(t), out_live)
        if key in processed:
            continue
        processed.add(key)
        if op.kind == "select":
            for name in out_live:
                expr = op.exprs.get("cols", {}).get(name)
                if expr is not None:
                    mark_refs(expr)
        elif op.kind == "filter":
            (inp,) = op.inputs
            for name in out_live:
                mark(inp, name)
            mark_refs(op.exprs.get("expr"))
        else:
            # conservative: the op may read anything from its inputs
            for inp in op.inputs:
                for name in inp.column_names():
                    mark(inp, name)
            for expr in op_exprs(op):
                mark_refs(expr)

    for t in view.anchored:
        op = getattr(t, "_op", None)
        if op is None or op.kind != "select" or op.synthetic:
            continue
        if not view.consumers.get(id(t)):
            continue  # sink-written tables keep every column
        unused = sorted(set(t.column_names()) - live.get(id(t), set()))
        for name in unused:
            result.add(make_diag(
                "PWT111",
                f"column {name!r} is computed but never read "
                "downstream",
                trace=_trace_or_none(t),
                operator=view.op_label(t),
                column=name,
            ))


# ---------------------------------------------------------------------------
# Pass 5 — UDF hazards (PWT305, PWT306)
# ---------------------------------------------------------------------------

def udf_pass(
    view: GraphView, result: AnalysisResult, *, workers: int = 1
) -> None:
    for table, op, sites in view.apply_sites():
        if op.synthetic:
            continue
        stateful_here = op.kind in STATEFUL_KINDS
        reaches_stateful = stateful_here or view.reaches_kind(
            table, STATEFUL_KINDS
        )
        crosses_exchange = workers > 1 and (
            op.kind in _EXCHANGE_KINDS
            or view.reaches_kind(table, _EXCHANGE_KINDS)
        )
        for node in sites:
            fname = getattr(node._fun, "__name__", "<udf>")
            if not node._deterministic and reaches_stateful:
                result.add(make_diag(
                    "PWT305",
                    f"UDF {fname!r} is not marked deterministic but "
                    "feeds a stateful operator: retractions recompute "
                    "it and may not cancel the original insertion "
                    "(mark it @pw.udf(deterministic=True) if it is)",
                    trace=_trace_or_none(table),
                    operator=view.op_label(table),
                    udf=fname,
                ))
            if node._is_async and crosses_exchange:
                result.add(make_diag(
                    "PWT306",
                    f"async UDF {fname!r} sits on an exchange-"
                    "crossing path: its completion times differ per "
                    "worker, so downstream keyed state sees "
                    "interleavings that are hard to reproduce",
                    trace=_trace_or_none(table),
                    operator=view.op_label(table),
                    udf=fname,
                ))


# ---------------------------------------------------------------------------
# Pass 6 — embedder batch-shape waste (PWT401)
# ---------------------------------------------------------------------------

# Deterministic stand-in for typical short-document corpora (final token
# counts per doc, CLS/SEP included — roughly what bench.py's synthetic
# ingest feeds the embedder). The lint is a shape argument, not a data
# argument: any distribution with mean/max in this range predicts the
# same verdict, and determinism keeps the golden matrix stable.
_SAMPLE_TOKEN_LENGTHS = (18, 24, 30, 34, 38, 42, 48, 56)
_PAD_WASTE_THRESHOLD = 0.5


def embedder_pass(
    view: GraphView, result: AnalysisResult, *, workers: int = 1
) -> None:
    """PWT401: embedder configs whose max_batch_size / bucket shape force
    most MXU cycles onto pad tokens. Embedder UDFs carry a `_pw_embedder`
    marker dict (xpacks/llm/embedders.py) with the shape facts, so the
    pass never builds a model."""
    from pathway_tpu.models.tokenizer import predict_pad_waste

    for table, op, sites in view.apply_sites():
        if op.synthetic:
            continue
        for node in sites:
            marker = getattr(node._fun, "_pw_embedder", None)
            if not isinstance(marker, dict):
                continue
            batch = int(marker.get("max_batch_size") or 0)
            max_len = int(marker.get("max_len") or 512)
            if batch <= 0:
                continue
            waste = predict_pad_waste(
                _SAMPLE_TOKEN_LENGTHS, batch, max_len=max_len
            )
            if waste <= _PAD_WASTE_THRESHOLD:
                continue
            fname = getattr(node._fun, "__name__", "<udf>")
            result.add(make_diag(
                "PWT401",
                f"embedder {fname!r} with max_batch_size={batch} "
                f"predicts {round(100 * waste)}% padding waste on "
                "sampled input lengths: the batch buckets to a power "
                "of two (minimum 8) and every doc pads to the bucket "
                "max, so most MXU cycles process pad tokens; raise "
                "max_batch_size or keep packed ragged batching on "
                "(PATHWAY_PACK_TOKEN_BUDGET > 0 with the default "
                "PATHWAY_DEVICE_PIPELINE=1)",
                trace=_trace_or_none(table),
                operator=view.op_label(table),
                udf=fname,
                predicted_waste=round(waste, 3),
                max_batch_size=batch,
            ))


# ---------------------------------------------------------------------------
# Plan verification (PWT399)
# ---------------------------------------------------------------------------

# engine node class name -> (op kind, selected path)
_NODE_PATHS = {
    "VectorJoinNode": ("join", "columnar"),
    "JoinNode": ("join", "classic"),
    "VectorReduceNode": ("reduce", "columnar"),
    "ReduceNode": ("reduce", "classic"),
    "VectorFlattenNode": ("flatten", "columnar"),
    "FlattenNode": ("flatten", "classic"),
}


def verify_against_plan(engine: Any, result: AnalysisResult) -> None:
    """Compare the analyzer's anchored columnar predictions against the
    node classes the build actually instantiated.  Counts (not per-node
    identity) — parse-level ops and engine nodes have no shared id, but
    every anchored join/reduce/flatten op builds exactly one node, so the
    histograms must agree."""
    predicted: Dict[tuple, int] = {}
    for p in result.predictions:
        if not p.get("anchored"):
            continue
        key = (p["op"], p["predicted"])
        predicted[key] = predicted.get(key, 0) + 1
    actual: Dict[tuple, int] = {}
    for node in getattr(engine, "nodes", ()):
        hit = _NODE_PATHS.get(type(node).__name__)
        if hit is not None:
            actual[hit] = actual.get(hit, 0) + 1
    for key in sorted(set(predicted) | set(actual)):
        if predicted.get(key, 0) != actual.get(key, 0):
            op_kind, path = key
            result.add(make_diag(
                "PWT399",
                f"analyzer predicted {predicted.get(key, 0)} {path} "
                f"{op_kind} node(s) but the built plan has "
                f"{actual.get(key, 0)} — the static gate and the build "
                "gate have drifted; please report this",
                operator=f"{op_kind}/{path}",
                predicted=predicted.get(key, 0),
                actual=actual.get(key, 0),
            ))


# ---------------------------------------------------------------------------
# Pass 7 — chain-level fusion planning (PWT501..PWT504)
# ---------------------------------------------------------------------------

def fusion_pass(view: GraphView, result: AnalysisResult) -> None:
    """Plan maximal fusable select/filter chains and attach the
    serialized FusionPlan to the result (analysis/fusion.py holds the
    walk; the build step runs the same planner, which is what makes the
    PWT599 cross-check meaningful).  Chain findings are informational:
    PWT501 says a chain will build as one fused node, PWT502/503 say why
    it stops where it does, PWT504 marks the ops a UDF keeps out."""
    from pathway_tpu.analysis.diagnostics import _trace_to_dict
    from pathway_tpu.analysis.fusion import plan_fusion

    plan = plan_fusion(view)
    result.fusion = plan  # serialized lazily on first read
    for chain in plan.chains:
        tail = chain.tables[-1]
        trace = _trace_to_dict(_trace_or_none(tail))
        operator = view.op_label(tail)
        shape = " -> ".join(chain.kinds)
        result.add(make_diag(
            "PWT501",
            f"fusable chain of {len(chain)} row-wise ops ({shape}) "
            "collapses into one fused interpreter node: no intermediate "
            "materialization or per-stage consolidation",
            trace=trace, operator=operator,
            chain=chain.chain_id(), length=len(chain),
            kinds=list(chain.kinds),
        ))
        if chain.break_reason == "kind":
            result.add(make_diag(
                "PWT502",
                f"fusion chain ({shape}) stops at a non-fusable "
                f"{chain.break_info} consumer: that operator keeps keyed "
                "state and must see materialized rows",
                trace=trace, operator=operator,
                chain=chain.chain_id(), consumer=str(chain.break_info),
            ))
        elif chain.break_reason == "fanout":
            result.add(make_diag(
                "PWT503",
                f"fusion chain ({shape}) stops at fan-out: "
                f"{chain.break_info} consumers read the chain tail, so "
                "its rows must materialize once instead of being "
                "recomputed per consumer",
                trace=trace, operator=operator,
                chain=chain.chain_id(), consumers=chain.break_info,
            ))
    for table, name, why in plan.barrier_sites:
        op = table._op
        result.add(make_diag(
            "PWT504",
            f"{why} UDF {name!r} keeps this {op.kind} out of any fused "
            "chain: its outputs must materialize per stage so "
            "retractions can cancel the original insertions",
            trace=_trace_or_none(table),
            operator=view.op_label(table),
            udf=name, why=why,
        ))


# ---------------------------------------------------------------------------
# Pass 8 — mesh compatibility (PWT402..PWT405)
# ---------------------------------------------------------------------------

# reducers whose merge depends on arrival order across shards: sharding
# the groupby over dp devices makes their output depend on the shard
# interleaving (internals/reducers.py sorts entries per worker, but a
# cross-shard merge has no shared (time, seq) order)
_ORDER_SENSITIVE_REDUCERS = {"tuple", "earliest", "latest"}


def mesh_pass(
    view: GraphView, result: AnalysisResult, *, mesh, workers: int = 1
) -> None:
    """Lint graphs that cannot shard onto the proposed device mesh.

    Runs only when a mesh spec is given (pw.run(mesh=...) or
    `analyze --mesh dp=4,tp=2`).  Everything here is provable from the
    recorded graph + the spec: no devices are touched."""
    if mesh is None:
        return
    dp, tp = mesh.dp, mesh.tp

    # PWT402 — embedder output shapes vs the proposed axes.  Embedder
    # UDFs carry a `_pw_embedder` marker (xpacks/llm/embedders.py) with
    # the model's dimension; minilm's encode path additionally buckets
    # the batch axis to a power of two, so a non-pow2 dp count never
    # divides the batch evenly (models/minilm.py raises at build time —
    # this is the fail-fast twin of that check).
    for table, op, sites in view.apply_sites():
        if not view.is_anchored(table):
            continue
        for node in sites:
            marker = getattr(node._fun, "_pw_embedder", None)
            if not isinstance(marker, dict):
                continue
            fname = getattr(node._fun, "__name__", "<udf>")
            trace = _trace_or_none(table)
            operator = view.op_label(table)
            dim = int(marker.get("dimension") or 0)
            if tp > 1 and dim and dim % tp:
                result.add(make_diag(
                    "PWT402",
                    f"embedder {fname!r} produces {dim}-dim vectors, "
                    f"which a tp={tp} axis cannot shard evenly "
                    f"({dim} % {tp} != 0): pick a tp that divides "
                    "the hidden dimension",
                    trace=trace, operator=operator,
                    udf=fname, dimension=dim, tp=tp,
                ))
            if dp > 1 and dp & (dp - 1):
                # wording mirrors models/minilm.py SentenceEncoder's
                # build-time ValueError (this lint is its fail-fast twin)
                result.add(make_diag(
                    "PWT402",
                    f"embedder {fname!r}: encode_batch buckets every "
                    f"batch to a power of two (minimum 8), so a "
                    f"dp={dp} axis would never divide the batch axis "
                    "evenly. Use a power-of-two dp device count, or "
                    "drop the mesh and run the single-device async "
                    "pipeline (PATHWAY_DEVICE_PIPELINE=1, the "
                    "default); models/minilm.py enforces the same "
                    "rule at encoder build time",
                    trace=trace, operator=operator,
                    udf=fname, dp=dp,
                ))

    # PWT403 — order-sensitive / opaque custom reducers under a sharded
    # groupby: per-shard partials have no shared order to merge by
    if dp > 1:
        for table, op in view.anchored_by_kind.get("reduce", ()):
            if op.synthetic:
                continue
            for rexpr in op.exprs.get("reducers", ()):
                red = getattr(rexpr, "_reducer", None)
                rname = getattr(red, "name", None)
                if not rname:
                    continue
                if rname in _ORDER_SENSITIVE_REDUCERS:
                    detail = (
                        "its result depends on cross-shard arrival order"
                    )
                elif rname.startswith(("udf_", "stateful_")):
                    detail = (
                        "custom accumulators carry no mergeable partial "
                        "state across shards"
                    )
                else:
                    continue
                result.add(make_diag(
                    "PWT403",
                    f"reducer {rname!r} cannot shard over dp={dp}: "
                    + detail
                    + "; keep the groupby on one shard or use an "
                    "associative built-in",
                    trace=_trace_or_none(table),
                    operator=view.op_label(table),
                    reducer=rname, dp=dp,
                ))

    # PWT404 — exchange shard codes vs device axes: the exchange layer
    # routes by ref_scalar hash over `workers` (engine/value.py
    # SHARD_BITS), so when the worker count does not tile the dp axis,
    # rows land on devices that do not own the corresponding model shard
    if dp > 1 and workers % dp != 0:
        n_exchange = sum(
            len(view.anchored_by_kind.get(k, ()))
            for k in sorted(_EXCHANGE_KINDS)
        )
        if n_exchange:
            result.add(make_diag(
                "PWT404",
                f"{n_exchange} exchange-crossing op(s) route rows over "
                f"{workers} worker(s), which does not tile the dp={dp} "
                "device axis: shard codes and device placement disagree, "
                "so every mismatched row pays a cross-device hop; run "
                "with workers as a multiple of dp",
                operator="exchange/mesh",
                exchange_ops=n_exchange, workers=workers, dp=dp,
            ))

    # PWT405 — single-worker-pinned sources starve a multi-device mesh:
    # exclusive connectors (pw.io.python.read) ingest on one worker only.
    # parse_graph.pending_sources sees descriptors before build-time
    # registration; only sink-anchored ones matter (dead sources are
    # PWT110's business).
    if mesh.devices() > 1:
        # same union as parse_graph.pending_sources, but over the view's
        # already-collected descriptor tables (no weakref re-walk):
        # registered sources first, then connector tables' descriptors
        tables_by_source: Dict[int, Any] = {}
        pending: List[Any] = list(view.graph.sources)
        seen_src: Set[int] = {id(s) for s in pending}
        for live, t in view.live_source_tables:
            tables_by_source[id(live)] = t
            if id(live) not in seen_src:
                seen_src.add(id(live))
                pending.append(live)
        for live in pending:
            if not getattr(live, "exclusive", False):
                continue
            table = tables_by_source.get(id(live))
            if table is None or not view.is_anchored(table):
                continue
            sname = getattr(live, "name", None) or type(live).__name__
            result.add(make_diag(
                "PWT405",
                f"source {sname!r} is pinned to a single worker but the "
                f"mesh has {mesh.devices()} devices ({mesh.describe()}): "
                "ingest serializes on one device while the rest idle; "
                "use a partitioned connector or shard the input upstream",
                trace=_trace_or_none(table),
                operator=view.op_label(table),
                source=sname, devices=mesh.devices(),
            ))


# ---------------------------------------------------------------------------
# Fusion plan verification (PWT599)
# ---------------------------------------------------------------------------

def verify_fusion(engine: Any, result: AnalysisResult) -> None:
    """Compare the FusionPlan the build consumed (engine.fusion_plan,
    installed by internals/runner.py before any node was built) against
    the fused nodes it actually instantiated (engine.fused_chains).
    Chains are identified by their op_id tuples, so a dropped chain, a
    phantom fused node, or a stage-count mismatch each become a hard
    PWT599 — the fusion twin of PWT399."""
    plan = getattr(engine, "fusion_plan", None)
    if not plan or not plan.get("enabled"):
        return  # fusion off at build time: nothing was promised
    planned: Dict[tuple, Dict[str, Any]] = {
        tuple(c["op_ids"]): c for c in plan.get("chains", ())
    }
    built: Dict[tuple, Any] = {
        tuple(getattr(n, "op_ids", ())): n
        for n in getattr(engine, "fused_chains", ())
    }
    for key in sorted(set(planned) | set(built)):
        c = planned.get(key)
        node = built.get(key)
        if c is not None and node is None:
            result.add(make_diag(
                "PWT599",
                f"planned fused chain of {c['length']} ops "
                f"({' -> '.join(c['kinds'])}) was not built as a fused "
                "node — the fusion planner and the build have drifted; "
                "please report this",
                operator=f"fused_chain#{c['id']}",
                chain=c["id"], planned=c["length"], built=0,
            ))
        elif c is None and node is not None:
            result.add(make_diag(
                "PWT599",
                f"a fused node over {len(node.op_ids)} ops was built "
                "without a matching planned chain — the fusion planner "
                "and the build have drifted; please report this",
                operator="fused_chain#" + "-".join(
                    str(i) for i in node.op_ids
                ),
                planned=0, built=len(node.op_ids),
            ))
        elif len(node.stages) != c["length"]:
            result.add(make_diag(
                "PWT599",
                f"fused chain {c['id']} was planned with {c['length']} "
                f"stages but built with {len(node.stages)} — the fusion "
                "planner and the build have drifted; please report this",
                operator=f"fused_chain#{c['id']}",
                chain=c["id"], planned=c["length"],
                built=len(node.stages),
            ))
