"""Chain-level fusion planning (PWT5xx substrate).

The analyzer's columnar pass predicts per-node implementation choices;
this module plans across nodes: maximal linear chains of row-wise
select/filter ops that can collapse into ONE fused interpreter node
(engine/operators.py FusedChainNode) — one `process()` entry per batch,
no intermediate materialization or per-stage consolidation.

The plan is a contract, not a suggestion.  `internals/runner.py` installs
the same plan on the RunContext before building sinks, the build step
consumes it (RunContext.node builds a chain tail as one fused node), and
`passes.verify_fusion` (PWT599) cross-checks the plan the build claimed
against the fused nodes it actually instantiated — mirroring the
PWT399 discipline for columnar twins.

A chain member must be provably safe to defer behind a single emit:
  * kind is select or filter with exactly one input table (foreign-table
    selects read other universes and need the multi-input RowwiseNode
    state machine);
  * every expression is synchronous and deterministic — an async or
    non-deterministic UDF is a barrier (PWT504): its per-stage outputs
    must be materialized so retractions can cancel insertions.
Interior members additionally need exactly one anchored consumer (the
next member) and must not be sink-anchored themselves: a sink table has
to materialize its own node for the sink to attach to, so it can only
ever be a chain tail.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from pathway_tpu.analysis.graph import GraphView

_FUSABLE_KINDS = {"select", "filter"}


def fusion_enabled() -> bool:
    """Fusion is on by default; PATHWAY_DISABLE_FUSION=1 restores the
    classic one-node-per-op build (A/B lever for benchmarks and tests)."""
    return os.environ.get("PATHWAY_DISABLE_FUSION", "0").lower() not in (
        "1", "true", "yes",
    )


def udf_barrier(apply_sites: Iterable[Any]) -> Optional[Tuple[str, str]]:
    """(udf name, why) for the first fusion-blocking UDF among the op's
    ApplyExpression sites (GraphView.apply_index — a select/filter's
    payload is exactly its stage expressions), or None when every
    expression is fusable."""
    for node in apply_sites:
        name = getattr(node._fun, "__name__", "<udf>")
        if node._is_async:
            return name, "async"
        if not node._deterministic:
            return name, "non-deterministic"
    return None


@dataclass
class FusionChain:
    """One maximal fusable run of select/filter ops, head to tail.

    `tables` holds strong refs (the plan must outlive the build), and
    `skipped` is the build-side off switch: a skipped chain stays in the
    serialized plan (the claim) but builds classically — which is exactly
    the drift PWT599 exists to catch (tests force it via
    PATHWAY_FUSION_FORCE_SKIP)."""

    tables: List[Any]
    op_ids: Tuple[int, ...]
    kinds: Tuple[str, ...]
    break_reason: str  # "end" | "sink" | "fanout" | "kind" | "udf"
    break_info: Any = None
    skipped: bool = False

    def __len__(self) -> int:
        return len(self.tables)

    @property
    def tail(self) -> Any:
        return self.tables[-1]

    def chain_id(self) -> str:
        return "-".join(str(i) for i in self.op_ids)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.chain_id(),
            "op_ids": list(self.op_ids),
            "kinds": list(self.kinds),
            "length": len(self.tables),
            "break": {
                "reason": self.break_reason,
                "info": (
                    None if self.break_info is None else str(self.break_info)
                ),
            },
        }


@dataclass
class FusionPlan:
    chains: List[FusionChain] = field(default_factory=list)
    # every anchored select/filter op blocked by a UDF: (table, name, why)
    barrier_sites: List[Tuple[Any, str, str]] = field(default_factory=list)
    enabled: bool = True

    def by_tail(self) -> Dict[int, FusionChain]:
        return {id(c.tables[-1]): c for c in self.chains}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "chains": [c.to_dict() for c in self.chains],
            "barriers": [
                {"udf": name, "why": why}
                for _t, name, why in self.barrier_sites
            ],
        }


def plan_fusion(view: GraphView) -> FusionPlan:
    """Walk the anchored op graph and compute maximal fusable chains.

    Deterministic over a given graph build: the same function runs on the
    analyzer side (fusion_pass) and the build side (runner install), so
    the two plans cannot disagree about the same parse graph."""
    # fusable-member status over the anchored single-input select/filters
    # (every chain decision — head, input, next link — looks at anchored
    # tables only: a chain member's input is anchored by construction and
    # anchored_consumers never yields an unanchored next link).  Barriers
    # come from the shared UDF-site index: only apply-bearing ops can
    # carry one, so the full scan just classifies kinds.
    fusable: set = set()
    for kind in _FUSABLE_KINDS:
        for t, op in view.anchored_by_kind.get(kind, ()):
            if len(op.inputs) == 1:
                fusable.add(id(t))
    barrier: Dict[int, Tuple[str, str]] = {}
    for t, op, sites in view.apply_sites():
        if id(t) in fusable:
            b = udf_barrier(sites)
            if b is not None:
                barrier[id(t)] = b

    cons = view.anchored_consumers()
    sinkish = view.sink_ids

    def is_member(t: Any) -> bool:
        return id(t) in fusable and id(t) not in barrier

    def extendable(t: Any) -> bool:
        """Can a chain continue PAST t (t becomes interior)?"""
        return id(t) not in sinkish and len(cons.get(id(t), ())) == 1

    plan = FusionPlan(enabled=fusion_enabled())
    for t, op in view.ops(anchored_only=True):
        tid = id(t)
        if tid in barrier:
            name, why = barrier[tid]
            plan.barrier_sites.append((t, name, why))
            continue
        if tid not in fusable:
            continue
        inp = op.inputs[0]
        if is_member(inp) and extendable(inp):
            continue  # t is interior/tail of the chain started upstream
        members = [t]
        cur = t
        break_reason, break_info = "end", None
        while True:
            if not extendable(cur):
                consumers = cons.get(id(cur), ())
                if id(cur) in sinkish:
                    break_reason = "sink" if consumers else "end"
                elif len(consumers) > 1:
                    break_reason, break_info = "fanout", len(consumers)
                break
            (nxt,) = cons[id(cur)]
            nid = id(nxt)
            if nid not in fusable:
                break_reason = "kind"
                nxt_op = getattr(nxt, "_op", None)
                break_info = nxt_op.kind if nxt_op is not None else "sink"
                break
            if nid in barrier:
                break_reason, break_info = "udf", barrier[nid]
                break
            members.append(nxt)
            cur = nxt
        if len(members) < 2:
            continue  # a single op fuses with nothing; build it classically
        plan.chains.append(FusionChain(
            tables=members,
            op_ids=tuple(m._op.op_id for m in members),
            kinds=tuple(m._op.kind for m in members),
            break_reason=break_reason,
            break_info=break_info,
        ))
    plan.chains.sort(key=lambda c: c.op_ids)
    return plan


def plan_for_build(graph: Any, extra_tables: Iterable[Any] = ()):
    """Build-side entry point (internals/runner.py): plan over the current
    parse graph, honoring the disable/force-skip env levers.  Returns None
    when fusion is globally disabled — the runner then leaves the context
    untouched and every op builds its classic node."""
    if not fusion_enabled():
        return None
    plan = plan_fusion(GraphView(graph, extra_tables=extra_tables))
    force = os.environ.get("PATHWAY_FUSION_FORCE_SKIP", "")
    if force:
        # drift injection for the PWT599 negative tests: the plan still
        # claims these chains (to_dict is unchanged) but the build drops
        # them, so the verifier must notice
        if force.strip().lower() == "all":
            for c in plan.chains:
                c.skipped = True
        else:
            wanted = {s.strip() for s in force.split(",") if s.strip()}
            for c in plan.chains:
                if str(c.op_ids[-1]) in wanted or c.chain_id() in wanted:
                    c.skipped = True
    return plan
