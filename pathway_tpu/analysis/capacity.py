"""PWT6xx — capacity planning: predicted device-memory footprint.

The ROADMAP's tiered-index work (10M+ docs beyond HBM) starts with
knowing, at BUILD time, whether a graph's device-resident state fits the
chip.  This pass predicts the footprint of every anchored external index
from the recorded OpSpec graph + MeshSpec — no devices touched — using
exactly the allocation rules the runtime applies:

  * index slab: `ops/knn.DeviceKnnIndex` buckets capacity to the next
    power of two of max(reserved_space, 2*dp) and allocates
    ``capacity * (4*dimensions + 1)`` bytes (float32 rows + bool valid),
    sharded over dp;
  * encoder params: `internals/costmodel.encoder_param_count` — the
    analytic twin of models/transformer.init_params — at float32,
    tp-sharded within a replica but replicated per dp replica (PWT605);
  * pipeline in-flight slabs: window(2) x token-budget packed arrays
    (informational only — transient, excluded from the parity gate).

Predictions are judged against `memtrack.hbm_capacity_bytes()` — the
same resolution order the live forecaster uses (PATHWAY_ASSUME_HBM_BYTES
-> jax bytes_limit -> costmodel table), so the analyzer and the runtime
can never disagree about how big the chip is.

`verify_capacity` is the PWT699 parity gate mirroring PWT399/PWT599:
after the engine builds (live DeviceKnnIndex + encoder params registered
in internals/memtrack.py), the predicted component bytes must match the
live accounting within CAPACITY_PARITY_TOLERANCE — drift means the
predictor and the allocator have diverged, which would silently invalidate
every capacity plan.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pathway_tpu.analysis.diagnostics import AnalysisResult, make_diag

# Relative drift beyond which PWT699 fires (both components are exact
# formulas today; the tolerance absorbs future dtype/layout tweaks).
CAPACITY_PARITY_TOLERANCE = 0.10

# Device-pipeline in-flight window (internals/device_pipeline.py keeps
# two slabs resident: one executing, one queued).
_INFLIGHT_WINDOW = 2


def _trace_or_none(table: Any):
    return getattr(table, "_trace", None)


def _mib(b: float) -> str:
    return f"{b / 2**20:.1f} MiB"


def predict_index_bytes(
    dimensions: int, reserved_space: int, dp: int = 1
) -> Dict[str, int]:
    """The DeviceKnnIndex allocation, predicted: bucketed capacity rows
    and the float32-buffer + bool-valid byte count."""
    from pathway_tpu.ops.knn import _next_bucket

    min_cap = 8
    if dp > 1:
        min_cap = max(min_cap, 2 * dp)
    rows = _next_bucket(max(int(reserved_space), min_cap))
    return {"rows": rows, "bytes": rows * (4 * int(dimensions) + 1)}


def _pipeline_inflight_bytes() -> int:
    """Transient packed-slab bytes while the async pipeline runs: the
    in-flight window x (ids + seg) int32 arrays at the token budget."""
    from pathway_tpu.models.tokenizer import pack_token_budget

    budget = pack_token_budget()
    if budget <= 0:
        return 0
    return _INFLIGHT_WINDOW * budget * 2 * 4


def capacity_pass(
    view: Any, result: AnalysisResult, *, mesh=None, workers: int = 1
) -> None:
    """PWT601..PWT605 over the anchored external-index ops (recorded by
    stdlib/indexing/data_index.DataIndex).  Attaches the full byte
    breakdown as ``result.capacity`` so /status, the CLI JSON, and
    verify_capacity all read one structure."""
    indexes = view.anchored_by_kind.get("external_index", ())
    if not indexes:
        return
    from pathway_tpu.internals import costmodel, memtrack

    dp = mesh.dp if mesh is not None else 1
    tp = mesh.tp if mesh is not None else 1
    cap = memtrack.hbm_capacity_bytes()
    inflight = _pipeline_inflight_bytes()
    rows_out: List[Dict[str, Any]] = []
    per_device_total = float(inflight)

    for table, op in indexes:
        info = op.info
        label = view.op_label(table)
        trace = _trace_or_none(table)
        dim = int(info.get("dimensions") or 0)
        if dim <= 0:
            result.add(make_diag(
                "PWT602",
                f"external index {info.get('index') or 'factory'!s} "
                "exposes no embedding dimension, so its device-memory "
                "footprint cannot be predicted: pass dimensions= (and "
                "reserved_space=) to the index factory so the capacity "
                "plan covers it",
                trace=trace, operator=label,
                index=str(info.get("index") or ""),
            ))
            rows_out.append({
                "op_id": op.op_id,
                "index": str(info.get("index") or ""),
                "dimensions": None,
                "index_bytes": None,
                "param_bytes": None,
            })
            continue
        reserved = int(info.get("reserved_space") or 512)
        pred = predict_index_bytes(dim, reserved, dp)
        enc = info.get("encoder")
        param_bytes = 0
        if isinstance(enc, dict):
            param_bytes = 4 * costmodel.encoder_param_count(
                vocab_size=int(enc.get("vocab_size", 30522)),
                hidden=int(enc.get("hidden", dim)),
                layers=int(enc.get("layers", 6)),
                mlp_dim=int(enc.get("mlp_dim", 4 * dim)),
                max_len=int(enc.get("max_len", 512)),
            )
        # placement: index rows shard over dp; matmul params shard over
        # tp within a replica and replicate across dp replicas
        per_device = pred["bytes"] / dp + param_bytes / tp
        per_replica = pred["bytes"] / dp + param_bytes
        per_device_total += per_device
        rows_out.append({
            "op_id": op.op_id,
            "index": str(info.get("index") or ""),
            "dimensions": dim,
            "reserved_space": reserved,
            "predicted_rows": pred["rows"],
            "index_bytes": pred["bytes"],
            "param_bytes": param_bytes,
            "per_device_bytes": per_device,
            "per_replica_bytes": per_replica,
        })
        result.add(make_diag(
            "PWT601",
            f"external index predicts {_mib(pred['bytes'])} of index "
            f"slab ({pred['rows']} bucketed rows x {4 * dim + 1} bytes "
            f"at d={dim})"
            + (
                f" + {_mib(param_bytes)} of encoder params"
                if param_bytes else ""
            )
            + f"; per device that is {_mib(per_device)}"
            + (f" under dp={dp},tp={tp}" if mesh is not None else ""),
            trace=trace, operator=label,
            index_bytes=pred["bytes"], param_bytes=param_bytes,
            per_device_bytes=round(per_device),
            predicted_rows=pred["rows"], dimensions=dim,
        ))
        if dp > 1 and param_bytes:
            result.add(make_diag(
                "PWT605",
                f"encoder params ({_mib(param_bytes)}) replicate per dp "
                f"replica: dp={dp} holds {dp} copies "
                f"({_mib(dp * param_bytes)} across the mesh); budget "
                "them per replica, not once",
                trace=trace, operator=label,
                param_bytes=param_bytes, dp=dp,
            ))
        if cap is not None and per_device > cap:
            result.add(make_diag(
                "PWT603",
                f"predicted per-device footprint {_mib(per_device)} "
                f"exceeds device HBM capacity {_mib(cap)}: the index "
                "will OOM before reserved_space fills; shrink "
                "reserved_space, widen dp, or move to a tiered index",
                trace=trace, operator=label,
                per_device_bytes=round(per_device),
                hbm_capacity_bytes=round(cap),
            ))

    headroom = cap - per_device_total if cap is not None else None
    if (
        cap
        and headroom is not None
        and headroom > 0
        and 100.0 * headroom / cap < memtrack.HEADROOM_WARN_PCT
    ):
        result.add(make_diag(
            "PWT604",
            f"predicted per-device usage {_mib(per_device_total)} "
            f"leaves {_mib(headroom)} of {_mib(cap)} HBM "
            f"({100.0 * headroom / cap:.1f}% — below the "
            f"{memtrack.HEADROOM_WARN_PCT:g}% warning threshold): "
            "ingest growth or a compile-time doubling will tip this "
            "over; plan capacity now",
            operator="capacity/headroom",
            per_device_bytes=round(per_device_total),
            headroom_bytes=round(headroom),
        ))
    result.capacity = {
        "mesh": mesh.describe() if mesh is not None else None,
        "hbm_capacity_bytes": cap,
        "indexes": rows_out,
        "pipeline_inflight_bytes": inflight,
        "per_device_bytes": per_device_total,
        "headroom_bytes": headroom,
    }


def verify_capacity(engine: Any, result: AnalysisResult) -> None:
    """PWT699 — predicted-vs-live parity, the capacity twin of
    PWT399/PWT599.  Runs after the engine built its sinks (so every
    DeviceKnnIndex / encoder-param copy is registered live in
    internals/memtrack.py) and compares component byte sums.  Skips when
    memtrack is disabled, nothing was predicted, or the live entry count
    does not match the prediction count (another engine's registrations
    are still alive in this process — a sum comparison would be
    meaningless, and guessing is worse than silence)."""
    from pathway_tpu.internals import memtrack

    if not memtrack.ENABLED:
        return
    section = result.capacity if hasattr(result, "capacity") else None
    if not section:
        return
    predicted = [
        r for r in section["indexes"] if r.get("index_bytes")
    ]
    if not predicted:
        return
    tracker = memtrack.tracker()
    checks = [
        (
            "knn_index",
            sum(r["index_bytes"] for r in predicted),
            tracker.entries("knn_index"),
            len(predicted),
        ),
        (
            "encoder_params",
            sum(r.get("param_bytes") or 0 for r in predicted),
            tracker.entries("encoder_params"),
            len([r for r in predicted if r.get("param_bytes")]),
        ),
    ]
    for component, pred_bytes, live_entries, expected_n in checks:
        if not pred_bytes or len(live_entries) != expected_n:
            continue
        live_bytes = sum(e["nbytes"] for e in live_entries)
        if not live_bytes:
            continue
        drift = abs(pred_bytes - live_bytes) / live_bytes
        if drift > CAPACITY_PARITY_TOLERANCE:
            result.add(make_diag(
                "PWT699",
                f"capacity plan predicted {_mib(pred_bytes)} of "
                f"{component} but live accounting holds "
                f"{_mib(live_bytes)} ({100 * drift:.1f}% drift > "
                f"{100 * CAPACITY_PARITY_TOLERANCE:.0f}%) — the "
                "predictor and the allocator have diverged; please "
                "report this",
                operator=f"capacity/{component}",
                predicted_bytes=round(pred_bytes),
                live_bytes=round(live_bytes),
                drift_pct=round(100 * drift, 2),
            ))
