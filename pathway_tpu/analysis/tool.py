"""`pathway-tpu analyze` implementation: load a user script, intercept
pw.run, analyze the graph it built.

The script is executed for its graph-building side effects only —
`runner.run`/`run_all` are patched to record that they were called (and
with what) instead of starting the engine, so analysis stays cheap and
side-effect-free even for streaming jobs.  Exit codes: 0 clean, 1
findings at or above --fail-on, 2 the script itself failed to load.
"""

from __future__ import annotations

import json
import runpy
import sys
from typing import List, Optional

from pathway_tpu.analysis import AnalysisResult, Severity, analyze


def analyze_script(path: str, *, mesh=None) -> AnalysisResult:
    """Execute `path` with pw.run patched out, then analyze the graph it
    registered on the global parse graph."""
    from pathway_tpu.internals import runner
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    calls: List[dict] = []

    def _capture_run(**kwargs):
        calls.append(kwargs)

    real_run, real_run_all = runner.run, runner.run_all
    # patch both the module and the package re-export: scripts call
    # pw.run, which resolved at import time
    import pathway_tpu as pw

    pw_run, pw_run_all = pw.run, pw.run_all
    runner.run = _capture_run
    runner.run_all = _capture_run
    pw.run = _capture_run
    pw.run_all = _capture_run
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        runner.run, runner.run_all = real_run, real_run_all
        pw.run, pw.run_all = pw_run, pw_run_all
    return analyze(G, mesh=mesh)


def list_codes(*, as_json: bool = False) -> str:
    """`analyze --list-codes`: render the diagnostics registry — every
    PWT code with its default severity, title and owning pass family —
    from diagnostics.CODES/FAMILIES, so docs and users never
    hand-maintain the table."""
    from pathway_tpu.analysis.diagnostics import CODES, FAMILIES

    def family_of(code: str):
        # the family prefix is everything but the two code digits —
        # "PWT101" -> "PWT1", "PWT1001" -> "PWT10" (a fixed [:4] slice
        # would misfile the four-digit families under PWT1)
        return FAMILIES.get(code[:-2], ("", ""))

    if as_json:
        payload = {
            "codes": [
                {
                    "code": code,
                    "severity": str(sev),
                    "title": title,
                    "family": family_of(code)[0],
                    "pass": family_of(code)[1],
                }
                for code, (sev, title) in sorted(CODES.items())
            ],
            "families": {
                prefix: {"family": fam, "pass": owner}
                for prefix, (fam, owner) in sorted(FAMILIES.items())
            },
        }
        return json.dumps(payload, indent=2, sort_keys=True)
    lines: List[str] = []
    last_prefix = None
    for code, (sev, title) in sorted(CODES.items()):
        prefix = code[:-2]
        if prefix != last_prefix:
            fam, owner = family_of(code)
            lines.append(f"{prefix}xx — {fam} ({owner})")
            last_prefix = prefix
        lines.append(f"  {code}  {str(sev):7s}  {title}")
    lines.append(f"{len(CODES)} registered code(s)")
    return "\n".join(lines)


def main_analyze(args) -> int:
    """Entry point for the cli.py `analyze` subcommand."""
    if getattr(args, "list_codes", False):
        print(list_codes(as_json=bool(args.json)))
        return 0
    if not getattr(args, "script", None):
        print(
            "error: a script argument is required unless --list-codes "
            "is given",
            file=sys.stderr,
        )
        return 2
    mesh = getattr(args, "mesh", None)
    if mesh is not None:
        from pathway_tpu.analysis.mesh import MeshSpec

        try:
            mesh = MeshSpec.parse(mesh)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
    try:
        result = analyze_script(args.script, mesh=mesh)
    except SystemExit as exc:  # script called sys.exit()
        code = exc.code if isinstance(exc.code, int) else 1
        if code != 0:
            print(
                f"error: {args.script} exited with {code} during graph "
                "build",
                file=sys.stderr,
            )
            return 2
        from pathway_tpu.internals.parse_graph import G

        result = analyze(G, mesh=mesh)
    except Exception as exc:  # noqa: BLE001 — report, don't traceback
        print(f"error: failed to load {args.script}: {exc}", file=sys.stderr)
        return 2

    baseline_info = None
    if getattr(args, "baseline", None):
        from pathway_tpu.analysis.baseline import apply_baseline

        try:
            baseline_info = apply_baseline(result, args.baseline)
        except (OSError, ValueError, KeyError) as exc:
            print(
                f"error: unusable baseline {args.baseline}: {exc}",
                file=sys.stderr,
            )
            return 2
        if baseline_info["created"]:
            print(
                f"baseline written: {baseline_info['suppressed']} "
                f"finding(s) -> {args.baseline}",
                file=sys.stderr,
            )

    if args.json:
        payload = result.to_dict()
        if baseline_info is not None:
            payload["baseline"] = baseline_info
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        if baseline_info is not None and baseline_info["suppressed"]:
            print(
                f"baseline {args.baseline}: "
                f"{baseline_info['suppressed']} known finding(s) "
                "suppressed",
                file=sys.stderr,
            )
        print(result.render_text())

    threshold: Optional[Severity] = None
    if args.fail_on:
        threshold = Severity.parse(args.fail_on)
    worst = result.max_severity()
    if threshold is not None and worst is not None and worst >= threshold:
        return 1
    return 0
