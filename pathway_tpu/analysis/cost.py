"""PWT8xx — cost-attribution lints (internals/costledger.py).

The cost ledger's attribution quality depends on configuration that is
knowable at BUILD time:

  * PWT801 — the admission controller is armed with per-tenant rate
    limits (``PATHWAY_SERVE_TENANT_RATE`` > 0) while query tracing is
    disabled (``PATHWAY_QTRACE=0``).  The tenant resolved from
    ``X-Tenant`` dies at the token bucket: no span carries it into the
    batched dispatch, so every shed decision and every device-second a
    tenant spends is unattributable — the ledger charges the whole serve
    workload to the ``""`` bucket and per-tenant limits cannot be
    audited against per-tenant cost.
  * PWT802 — the cost ledger is enabled but the attached device has no
    peak-FLOPs entry in the chip table (internals/costmodel.py — CPU CI,
    new chip generations).  Attribution still works, but every derived
    efficiency gauge (``pathway_cost_efficiency_pct``) reports None;
    stated as a finding so the gap is visible instead of a silently
    absent metric.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.analysis.diagnostics import AnalysisResult, make_diag


def _trace_or_none(table: Any):
    return getattr(table, "_trace", None)


def cost_pass(view: Any, result: AnalysisResult) -> None:
    """PWT801/PWT802 over the anchored external-index ops — the ops the
    serve workload's device time flows through.  Runs only when a graph
    actually serves (an anchored external index exists)."""
    from pathway_tpu.internals import costledger, costmodel, qtrace, serving

    indexes = view.anchored_by_kind.get("external_index", ())
    if not indexes:
        return
    table, op = indexes[0]

    if (
        serving.ENABLED
        and serving.tenant_rate() > 0
        and not qtrace.ENABLED
    ):
        result.add(make_diag(
            "PWT801",
            "per-tenant admission rate limits are armed "
            f"(PATHWAY_SERVE_TENANT_RATE={serving.tenant_rate():g}/s) but "
            "query tracing is disabled (PATHWAY_QTRACE=0): the resolved "
            "X-Tenant dies at the token bucket instead of riding the "
            "query span into the batched dispatch, so shed decisions and "
            "per-tenant device cost are unattributable — the ledger "
            "charges all serve time to the \"\" tenant; re-enable "
            "PATHWAY_QTRACE or drop the tenant limits",
            trace=_trace_or_none(table),
            operator=view.op_label(table),
            tenant_rate_per_s=serving.tenant_rate(),
        ))

    if costledger.ENABLED and not costmodel.device_capacity_known():
        result.add(make_diag(
            "PWT802",
            "the cost ledger is enabled but the attached device "
            f"('{costmodel.device_name()}') has no peak-FLOPs entry in "
            "the chip table (internals/costmodel.py): attribution works, "
            "but every derived efficiency gauge "
            "(pathway_cost_efficiency_pct, MFU-style ratios) will report "
            "None; add the chip to DEVICE_PEAK_BF16_FLOPS or expect "
            "absent efficiency series",
            trace=_trace_or_none(table),
            operator=view.op_label(table),
            device=costmodel.device_name(),
        ))
