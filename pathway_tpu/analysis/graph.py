"""Graph access layer for the analyzer.

The parse graph records an `OpSpec` on every op-result table
(`internals/parse_graph.record_op`) — kind, input tables, expression
payload.  This module turns that flat record into the views the passes
need: the anchored set (tables reachable upstream from a sink), a
consumer index for downstream reachability, expression traversal, and a
best-effort dtype resolver.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional, Set, Tuple

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ApplyExpression,
    ColumnExpression,
    ColumnReference,
    IdReference,
)
from pathway_tpu.internals.type_interpreter import infer_dtype


def walk_expr(expr: Any) -> Iterator[ColumnExpression]:
    """Yield `expr` and every sub-expression, in pre-order.  Children are
    discovered structurally (any ColumnExpression attribute, or tuple /
    list / dict attribute containing one), matching how expression
    classes store operands."""
    if not isinstance(expr, ColumnExpression):
        return
    stack: List[ColumnExpression] = [expr]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ColumnReference):
            continue  # leaf: do not follow the _table backref
        for value in vars(node).values():
            if isinstance(value, ColumnExpression):
                stack.append(value)
            elif isinstance(value, (tuple, list)):
                for v in value:
                    if isinstance(v, ColumnExpression):
                        stack.append(v)
            elif isinstance(value, dict):
                for v in value.values():
                    if isinstance(v, ColumnExpression):
                        stack.append(v)


def op_exprs(op: Any) -> Iterator[ColumnExpression]:
    """Every expression the op's payload closes over, flattened."""
    for value in op.exprs.values():
        if isinstance(value, ColumnExpression):
            yield value
        elif isinstance(value, (tuple, list)):
            for v in value:
                if isinstance(v, ColumnExpression):
                    yield v
        elif isinstance(value, dict):
            for v in value.values():
                if isinstance(v, ColumnExpression):
                    yield v


def resolve_ref_dtype(ref: ColumnReference) -> dt.DType:
    if isinstance(ref, IdReference):
        return dt.POINTER
    return ref._table._schema[ref.name].dtype


def infer(expr: ColumnExpression) -> Optional[dt.DType]:
    """Best-effort dtype of an expression; None when inference fails
    (the analyzer then stays silent rather than guessing)."""
    try:
        return infer_dtype(expr, resolve_ref_dtype)
    except Exception:  # noqa: BLE001
        return None


class GraphView:
    """Immutable snapshot of the parse graph, indexed for analysis."""

    def __init__(self, graph: Any, extra_tables: Iterable[Any] = ()):
        self.graph = graph
        self.markers = list(graph.markers)
        self.sink_tables: List[Any] = []
        seen_sink: Set[int] = set()
        for spec in graph.sinks:
            for t in spec.tables:
                if id(t) not in seen_sink:
                    seen_sink.add(id(t))
                    self.sink_tables.append(t)
        for t in extra_tables:
            if id(t) not in seen_sink:
                seen_sink.add(id(t))
                self.sink_tables.append(t)

        # anchored: everything a sink transitively depends on
        self.anchored: List[Any] = []
        self._anchored_ids: Set[int] = set()
        stack = list(self.sink_tables)
        while stack:
            t = stack.pop()
            if id(t) in self._anchored_ids:
                continue
            self._anchored_ids.add(id(t))
            self.anchored.append(t)
            op = getattr(t, "_op", None)
            if op is not None:
                stack.extend(op.inputs)

        # every table the analyzer can see: anchored first (dead tables
        # may already be garbage-collected; live_tables catches the rest)
        self.tables: List[Any] = list(self.anchored)
        known = set(self._anchored_ids)
        for t in graph.live_tables():
            if id(t) not in known:
                known.add(id(t))
                self.tables.append(t)

        # one sweep over the visible tables builds every index the passes
        # share: the consumer map, the (table, op) pair lists behind
        # ops(), the anchored per-kind buckets, and the connector tables
        # carrying a live-source descriptor.  vars() sidesteps
        # Table.__getattr__'s column-lookup fallback.
        self.sink_ids: Set[int] = seen_sink
        self.consumers: Dict[int, List[Any]] = {}
        self.anchored_by_kind: Dict[str, List[Any]] = {}
        self.live_source_tables: List[Any] = []
        self._all_pairs: List[Any] = []
        self._anchored_pairs: List[Any] = []
        self._anchored_consumers: Dict[int, List[Any]] = {}
        anchored_ids = self._anchored_ids
        for t in self.tables:
            d = vars(t)
            live = d.get("_live_source")
            if live is not None:
                self.live_source_tables.append((live, t))
            op = d.get("_op")
            if op is None:
                continue
            self._all_pairs.append((t, op))
            anchored = id(t) in anchored_ids
            if anchored:
                self._anchored_pairs.append((t, op))
                self.anchored_by_kind.setdefault(op.kind, []).append((t, op))
            for inp in op.inputs:
                self.consumers.setdefault(id(inp), []).append(t)
                if anchored:
                    self._anchored_consumers.setdefault(
                        id(inp), []
                    ).append(t)

        self._apply_index: Optional[Dict[int, Tuple[Any, ...]]] = None
        self._apply_sites: Optional[List[Any]] = None
        self._label_cache: Dict[int, str] = {}

    def apply_index(self) -> Dict[int, Tuple[Any, ...]]:
        """table id -> deduped ApplyExpression nodes in that table's op
        payload, in expression-walk order.  Four passes scan for UDF call
        sites (udf_pass, embedder_pass, the fusion planner's barrier
        check and the mesh pass's embedder-marker scan); the graph is
        immutable under this view, so the expression walk happens once
        and everyone shares the result."""
        if self._apply_index is None:
            idx: Dict[int, Tuple[Any, ...]] = {}
            rows: List[Any] = []
            for t, op in self.ops():
                seen: Set[int] = set()
                sites: List[Any] = []
                for expr in op_exprs(op):
                    for node in walk_expr(expr):
                        if (
                            isinstance(node, ApplyExpression)
                            and id(node) not in seen
                        ):
                            seen.add(id(node))
                            sites.append(node)
                if sites:
                    idx[id(t)] = tuple(sites)
                    rows.append((t, op, tuple(sites)))
            self._apply_index = idx
            self._apply_sites = rows
        return self._apply_index

    def apply_sites(self) -> List[Any]:
        """(table, op, ApplyExpression sites) rows for every op that
        calls at least one UDF, in ops() order.  The UDF-centric passes
        iterate this short list instead of scanning every op."""
        if self._apply_sites is None:
            self.apply_index()
        return self._apply_sites

    def is_anchored(self, table: Any) -> bool:
        return id(table) in self._anchored_ids

    def anchored_consumers(self) -> Dict[int, List[Any]]:
        """Consumer index restricted to the anchored region (built in
        __init__).  Only anchored consumers are ever built, so only they
        pin a table's materialization — this is the index the fusion
        planner walks (a dead reader must not break an otherwise fusable
        chain)."""
        return self._anchored_consumers

    def ops(self, *, anchored_only: bool = False) -> Iterator[Any]:
        """(table, op) pairs, de-duplicated, anchored tables first
        (precomputed in __init__)."""
        return iter(self._anchored_pairs if anchored_only else self._all_pairs)

    def graph_path(self, table: Any, depth: int = 5) -> str:
        """Short upstream chain for trace-less findings:
        "select#7 <- join#3 <- source"."""
        parts: List[str] = []
        t = table
        while t is not None and len(parts) < depth:
            op = getattr(t, "_op", None)
            if op is None:
                parts.append("source")
                break
            parts.append(f"{op.kind}#{op.op_id}")
            t = op.inputs[0] if op.inputs else None
        else:
            if t is not None:
                parts.append("...")
        return " <- ".join(parts)

    def op_label(self, table: Any) -> str:
        """The trace-fallback operator label: kind#op_id plus path.
        Memoized — every pass labels the tables it reports on, and the
        upstream path never changes under this view."""
        label = self._label_cache.get(id(table))
        if label is None:
            op = vars(table).get("_op")
            if op is None:
                label = "source"
            else:
                path = self.graph_path(table)
                label = f"{op.kind}#{op.op_id} ({path})"
            self._label_cache[id(table)] = label
        return label

    def reaches_kind(self, table: Any, kinds: Set[str]) -> bool:
        """Does any transitive consumer of `table` run an op in `kinds`?"""
        stack = list(self.consumers.get(id(table), ()))
        seen: Set[int] = set()
        while stack:
            t = stack.pop()
            if id(t) in seen:
                continue
            seen.add(id(t))
            op = getattr(t, "_op", None)
            if op is not None and op.kind in kinds:
                return True
            stack.extend(self.consumers.get(id(t), ()))
        return False
