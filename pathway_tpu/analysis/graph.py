"""Graph access layer for the analyzer.

The parse graph records an `OpSpec` on every op-result table
(`internals/parse_graph.record_op`) — kind, input tables, expression
payload.  This module turns that flat record into the views the passes
need: the anchored set (tables reachable upstream from a sink), a
consumer index for downstream reachability, expression traversal, and a
best-effort dtype resolver.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional, Set

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    IdReference,
)
from pathway_tpu.internals.type_interpreter import infer_dtype


def walk_expr(expr: Any) -> Iterator[ColumnExpression]:
    """Yield `expr` and every sub-expression, in pre-order.  Children are
    discovered structurally (any ColumnExpression attribute, or tuple /
    list / dict attribute containing one), matching how expression
    classes store operands."""
    if not isinstance(expr, ColumnExpression):
        return
    stack: List[ColumnExpression] = [expr]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ColumnReference):
            continue  # leaf: do not follow the _table backref
        for value in vars(node).values():
            if isinstance(value, ColumnExpression):
                stack.append(value)
            elif isinstance(value, (tuple, list)):
                for v in value:
                    if isinstance(v, ColumnExpression):
                        stack.append(v)
            elif isinstance(value, dict):
                for v in value.values():
                    if isinstance(v, ColumnExpression):
                        stack.append(v)


def op_exprs(op: Any) -> Iterator[ColumnExpression]:
    """Every expression the op's payload closes over, flattened."""
    for value in op.exprs.values():
        if isinstance(value, ColumnExpression):
            yield value
        elif isinstance(value, (tuple, list)):
            for v in value:
                if isinstance(v, ColumnExpression):
                    yield v
        elif isinstance(value, dict):
            for v in value.values():
                if isinstance(v, ColumnExpression):
                    yield v


def resolve_ref_dtype(ref: ColumnReference) -> dt.DType:
    if isinstance(ref, IdReference):
        return dt.POINTER
    return ref._table._schema[ref.name].dtype


def infer(expr: ColumnExpression) -> Optional[dt.DType]:
    """Best-effort dtype of an expression; None when inference fails
    (the analyzer then stays silent rather than guessing)."""
    try:
        return infer_dtype(expr, resolve_ref_dtype)
    except Exception:  # noqa: BLE001
        return None


class GraphView:
    """Immutable snapshot of the parse graph, indexed for analysis."""

    def __init__(self, graph: Any, extra_tables: Iterable[Any] = ()):
        self.graph = graph
        self.markers = list(graph.markers)
        self.sink_tables: List[Any] = []
        seen_sink: Set[int] = set()
        for spec in graph.sinks:
            for t in spec.tables:
                if id(t) not in seen_sink:
                    seen_sink.add(id(t))
                    self.sink_tables.append(t)
        for t in extra_tables:
            if id(t) not in seen_sink:
                seen_sink.add(id(t))
                self.sink_tables.append(t)

        # anchored: everything a sink transitively depends on
        self.anchored: List[Any] = []
        self._anchored_ids: Set[int] = set()
        stack = list(self.sink_tables)
        while stack:
            t = stack.pop()
            if id(t) in self._anchored_ids:
                continue
            self._anchored_ids.add(id(t))
            self.anchored.append(t)
            op = getattr(t, "_op", None)
            if op is not None:
                stack.extend(op.inputs)

        # every table the analyzer can see: anchored first (dead tables
        # may already be garbage-collected; live_tables catches the rest)
        self.tables: List[Any] = list(self.anchored)
        known = set(self._anchored_ids)
        for t in graph.live_tables():
            if id(t) not in known:
                known.add(id(t))
                self.tables.append(t)

        # consumer index over the visible tables
        self.consumers: Dict[int, List[Any]] = {}
        for t in self.tables:
            op = getattr(t, "_op", None)
            if op is None:
                continue
            for inp in op.inputs:
                self.consumers.setdefault(id(inp), []).append(t)

    def is_anchored(self, table: Any) -> bool:
        return id(table) in self._anchored_ids

    def ops(self, *, anchored_only: bool = False) -> Iterator[Any]:
        """(table, op) pairs, de-duplicated, anchored tables first."""
        for t in (self.anchored if anchored_only else self.tables):
            op = getattr(t, "_op", None)
            if op is not None:
                yield t, op

    def graph_path(self, table: Any, depth: int = 5) -> str:
        """Short upstream chain for trace-less findings:
        "select#7 <- join#3 <- source"."""
        parts: List[str] = []
        t = table
        while t is not None and len(parts) < depth:
            op = getattr(t, "_op", None)
            if op is None:
                parts.append("source")
                break
            parts.append(f"{op.kind}#{op.op_id}")
            t = op.inputs[0] if op.inputs else None
        else:
            if t is not None:
                parts.append("...")
        return " <- ".join(parts)

    def op_label(self, table: Any) -> str:
        """The trace-fallback operator label: kind#op_id plus path."""
        op = getattr(table, "_op", None)
        if op is None:
            return "source"
        path = self.graph_path(table)
        return f"{op.kind}#{op.op_id} ({path})"

    def reaches_kind(self, table: Any, kinds: Set[str]) -> bool:
        """Does any transitive consumer of `table` run an op in `kinds`?"""
        stack = list(self.consumers.get(id(table), ()))
        seen: Set[int] = set()
        while stack:
            t = stack.pop()
            if id(t) in seen:
                continue
            seen.add(id(t))
            op = getattr(t, "_op", None)
            if op is not None and op.kind in kinds:
                return True
            stack.extend(self.consumers.get(id(t), ()))
        return False
