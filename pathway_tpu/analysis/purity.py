"""Deep UDF purity analysis — the PWT9xx determinism pass.

The engine's headline guarantees (exactly-once sinks, snapshot+replay
failover, fused chains, incremental retraction streams) all assume user
callables are deterministic, side-effect-free and picklable.  This pass
walks the *source* of every UDF reachable from an apply site or a
stateful custom reducer and classifies it:

  * ``deterministic`` — the AST was fully analyzed and nothing impure
    was found; the runtime sanitizer (internals/sanitizer.py) treats the
    callable as certified and the PWT999 parity gate asserts its replay
    hash never diverges.
  * ``impure`` — a concrete nondeterminism source or replay-unsafe side
    effect was found (PWT901/PWT903).
  * ``unknown`` — no source (builtins, C extensions) or only soft
    hazards (PWT902/PWT904/PWT905); the sanitizer still hashes it but
    the parity gate makes no promise.

Findings:
  PWT901  nondeterminism source (time/random/uuid/secrets/os.urandom,
          datetime.now, builtin id())
  PWT902  unordered set/dict iteration feeding the output
  PWT903  replay-unsafe side effect (file/network writes, global-state
          mutation) on a path that stateful operators recompute
  PWT904  closure captures unpicklable state — would disable the
          enclosing node's operator snapshot (build-time twin of the
          runtime "snapshot skips node" warn-once)
  PWT905  mutation of input rows — breaks FusedChainNode batch sharing
  PWT999  parity: a callable *declared* deterministic=True that the
          analysis proves impure (the static half of the contract the
          runtime replay hash enforces)
"""

from __future__ import annotations

import ast
import inspect
import pickle
import textwrap
import weakref
from typing import Any, Dict, List, Optional, Tuple

from pathway_tpu.analysis.diagnostics import AnalysisResult, make_diag
from pathway_tpu.analysis.graph import GraphView, op_exprs, walk_expr
from pathway_tpu.internals.expression import ReducerExpression

DETERMINISTIC = "deterministic"
IMPURE = "impure"
UNKNOWN = "unknown"

# module roots whose mere use marks a nondeterminism source (PWT901)
_NONDET_MODULES = {"random", "uuid", "secrets"}
# (module, attr) calls that are nondeterministic; bare module calls from
# `time` are fine to *measure* but not to feed output, so every call
# into these is flagged
_NONDET_CALLS = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("time", "perf_counter_ns"), ("time", "process_time"),
    ("os", "urandom"), ("os", "getpid"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("date", "today"),
}
_NONDET_BUILTINS = {"id"}

# module roots whose use from inside a UDF is a replay-unsafe side
# effect (PWT903): network and subprocess I/O
_SIDE_EFFECT_MODULES = {
    "socket", "requests", "urllib", "http", "subprocess", "smtplib",
}
# method names that mutate their receiver in place (PWT905 when the
# receiver is a parameter)
_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse",
}


class PurityReport:
    """Classification of one callable."""

    __slots__ = ("name", "verdict", "hazards", "declared_deterministic")

    def __init__(self, name: str):
        self.name = name
        self.verdict = UNKNOWN
        # list of (code, message) pairs, in source order
        self.hazards: List[Tuple[str, str]] = []
        self.declared_deterministic = False

    def codes(self) -> List[str]:
        seen: List[str] = []
        for code, _ in self.hazards:
            if code not in seen:
                seen.append(code)
        return seen

    def to_dict(self) -> Dict[str, Any]:
        return {"verdict": self.verdict, "codes": self.codes()}

    def copy(self) -> "PurityReport":
        dup = PurityReport(self.name)
        dup.verdict = self.verdict
        dup.hazards = list(self.hazards)
        dup.declared_deterministic = self.declared_deterministic
        return dup


def _unwrap(fun: Any) -> Any:
    """Follow decorator/UDF wrapping down to the user's own function."""
    seen = set()
    while id(fun) not in seen:
        seen.add(id(fun))
        for attr in ("__wrapped__", "func", "__func__"):
            inner = getattr(fun, attr, None)
            if callable(inner) and inner is not fun:
                fun = inner
                break
        else:
            return fun
    return fun


def _user_callables(fun: Any, depth: int = 3) -> List[Any]:
    """`fun` plus closure-captured callables defined outside pathway_tpu
    (stateful_single/stateful_many wrap the user's combiner in library
    closures; the user code is in the cells)."""
    out: List[Any] = []
    seen = set()
    stack = [(fun, 0)]
    while stack:
        f, d = stack.pop()
        f = _unwrap(f)
        if id(f) in seen or not callable(f):
            continue
        seen.add(id(f))
        module = getattr(f, "__module__", "") or ""
        if not module.startswith("pathway_tpu"):
            out.append(f)
        if d < depth:
            for cell in getattr(f, "__closure__", None) or ():
                try:
                    v = cell.cell_contents
                except ValueError:  # empty cell
                    continue
                if callable(v):
                    stack.append((v, d + 1))
    return out


def _param_names(tree: ast.AST) -> set:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            return {
                p.arg
                for p in (
                    list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                )
            } | ({a.vararg.arg} if a.vararg else set()) | (
                {a.kwarg.arg} if a.kwarg else set()
            )
    return set()


def _dotted_root(node: ast.AST) -> Optional[Tuple[str, str]]:
    """`mod.attr(...)` -> ("mod", "attr"); `mod.sub.attr` -> root+attr."""
    if not isinstance(node, ast.Attribute):
        return None
    attr = node.attr
    base = node.value
    while isinstance(base, ast.Attribute):
        base = base.value
    if isinstance(base, ast.Name):
        return (base.id, attr)
    return None


def _is_unordered_iterable(node: ast.AST) -> bool:
    """Set literals, set()/frozenset() calls, and dict .keys/.values/
    .items views — iteration order is not a replayable contract."""
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id in (
            "set", "frozenset"
        ):
            return True
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "keys", "values", "items"
        ):
            # sorted(d.items()) is handled by the caller (sorted() wraps)
            return True
    return False


class _HazardVisitor(ast.NodeVisitor):
    def __init__(self, params: set):
        self.params = params
        self.hazards: List[Tuple[str, str]] = []
        self._sorted_depth = 0

    def _add(self, code: str, message: str) -> None:
        self.hazards.append((code, message))

    # -- PWT901 / PWT903: calls -------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted_root(node.func)
        if dotted is not None:
            root, attr = dotted
            if root in _NONDET_MODULES:
                self._add("PWT901", f"calls {root}.{attr}()")
            elif (root, attr) in _NONDET_CALLS:
                self._add("PWT901", f"calls {root}.{attr}()")
            elif root in _SIDE_EFFECT_MODULES:
                self._add("PWT903", f"performs I/O via {root}.{attr}()")
            elif node.func.attr in _MUTATING_METHODS and isinstance(
                node.func.value, ast.Name
            ) and node.func.value.id in self.params:
                self._add(
                    "PWT905",
                    f"mutates input {node.func.value.id!r} via "
                    f".{node.func.attr}()",
                )
        elif isinstance(node.func, ast.Name):
            name = node.func.id
            if name in _NONDET_BUILTINS:
                self._add("PWT901", f"calls builtin {name}()")
            elif name == "open":
                mode = ""
                if len(node.args) > 1 and isinstance(
                    node.args[1], ast.Constant
                ):
                    mode = str(node.args[1].value)
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                        mode = str(kw.value.value)
                if any(c in mode for c in "wax+"):
                    self._add("PWT903", f"opens a file for writing "
                                        f"(mode {mode!r})")
            elif name == "sorted":
                # sorted(set(...)) restores a total order — suppress the
                # unordered-iteration lint inside the call
                self._sorted_depth += 1
                self.generic_visit(node)
                self._sorted_depth -= 1
                return
            elif name in ("list", "tuple"):
                self._flag_set_to_sequence(node)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "join":
            self._flag_set_to_sequence(node)
        self.generic_visit(node)

    # -- PWT902: unordered iteration --------------------------------------
    def _check_unordered(self, iter_node: ast.AST, context: str) -> None:
        if self._sorted_depth == 0 and _is_unordered_iterable(iter_node):
            self._add("PWT902", f"iterates an unordered collection "
                                f"in {context}")

    def visit_For(self, node: ast.For) -> None:
        self._check_unordered(node.iter, "a for loop")
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._check_unordered(node.iter, "a comprehension")
        self.generic_visit(node)

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        for gen in node.generators:
            self._check_unordered(gen.iter, "a generator expression")
        self.generic_visit(node)

    # str.join(set) / list(set) / tuple(set): set order leaks into a
    # sequence even without an explicit loop
    def _flag_set_to_sequence(self, node: ast.Call) -> None:
        for arg in node.args:
            if self._sorted_depth == 0 and _is_unordered_iterable(arg):
                self._add(
                    "PWT902",
                    "converts an unordered collection to a sequence",
                )

    # -- PWT903: global mutation ------------------------------------------
    def visit_Global(self, node: ast.Global) -> None:
        self._add(
            "PWT903",
            f"declares global {', '.join(node.names)} (state survives "
            "across rows and diverges on replay)",
        )
        self.generic_visit(node)

    # -- PWT905: parameter mutation ---------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._check_param_store(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_param_store(node.target)
        self.generic_visit(node)

    def _check_param_store(self, tgt: ast.AST) -> None:
        if isinstance(tgt, (ast.Subscript, ast.Attribute)):
            base = tgt.value
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if isinstance(base, ast.Name) and base.id in self.params:
                self._add(
                    "PWT905", f"assigns into input {base.id!r} in place"
                )


def _source_tree(fun: Any) -> Optional[ast.AST]:
    try:
        src = inspect.getsource(fun)
    except (OSError, TypeError):
        return None
    try:
        return ast.parse(textwrap.dedent(src))
    except SyntaxError:
        # a lambda mid-expression: retry on the bracketed expression
        try:
            return ast.parse("(" + textwrap.dedent(src).strip().rstrip(",")
                             + ")", mode="eval")
        except SyntaxError:
            return None


# source hazards are a property of the def site, not the closure
# instance: rebuilding a graph re-creates function objects but reuses
# their code objects, so keying on __code__ makes repeated analyze runs
# skip the getsource/parse/visit work (closure pickle probing stays
# per-call — it depends on live cell values)
_source_cache: Dict[Any, Tuple[bool, Tuple[Tuple[str, str], ...]]] = {}


def _source_hazards(fun: Any) -> Tuple[bool, Tuple[Tuple[str, str], ...]]:
    code = getattr(fun, "__code__", None)
    if code is not None:
        hit = _source_cache.get(code)
        if hit is not None:
            return hit
    tree = _source_tree(fun)
    if tree is None:
        res: Tuple[bool, Tuple[Tuple[str, str], ...]] = (False, ())
    else:
        visitor = _HazardVisitor(_param_names(tree))
        visitor.visit(tree)
        res = (True, tuple(visitor.hazards))
    if code is not None:
        _source_cache[code] = res
    return res


def _closure_pickle_hazards(fun: Any) -> List[Tuple[str, str]]:
    """PWT904: closure cells (and bound __self__) that do not pickle
    would skip the enclosing node's operator snapshot at runtime."""
    out: List[Tuple[str, str]] = []
    names = getattr(getattr(fun, "__code__", None), "co_freevars", ())
    cells = getattr(fun, "__closure__", None) or ()
    for name, cell in zip(names, cells):
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if callable(v):
            continue  # nested functions are analyzed, not pickled here
        try:
            pickle.dumps(v)
        except Exception as exc:  # noqa: BLE001 — the finding IS the point
            out.append((
                "PWT904",
                f"closure variable {name!r} ({type(v).__name__}) does not "
                f"pickle: {exc}",
            ))
    owner = getattr(fun, "__self__", None)
    if owner is not None:
        try:
            pickle.dumps(owner)
        except Exception as exc:  # noqa: BLE001
            out.append((
                "PWT904",
                f"bound instance ({type(owner).__name__}) does not "
                f"pickle: {exc}",
            ))
    return out


# classification is pure in the callable object (source + closure
# cells), and re-running the analyze gate over the same graph builders
# re-presents the same function objects — memoize per callable, weakly
# so dropped UDFs do not pin their closures.  Callers mutate the report
# (declared_deterministic), so hits hand out copies.
_classify_cache: "weakref.WeakKeyDictionary[Any, PurityReport]" = (
    weakref.WeakKeyDictionary()
)


def classify_callable(fun: Any) -> PurityReport:
    """Classify one callable (following UDF/decorator wrapping)."""
    try:
        cached = _classify_cache.get(fun)
    except TypeError:  # unhashable / non-weakrefable callable
        cached = None
    if cached is not None:
        return cached.copy()
    report = _classify_uncached(fun)
    try:
        _classify_cache[fun] = report.copy()
    except TypeError:
        pass
    return report


def _classify_uncached(fun: Any) -> PurityReport:
    def _name_of(f: Any) -> str:
        return getattr(f, "__qualname__", None) or getattr(
            f, "__name__", None
        ) or type(f).__name__

    targets = _user_callables(fun)
    # attribute to the user's own function, not a library wrapper it is
    # buried in (stateful reducers wrap the combiner in library closures)
    module = getattr(_unwrap(fun), "__module__", "") or ""
    named = targets[0] if targets and module.startswith("pathway_tpu") else fun
    report = PurityReport(_name_of(named))
    if not targets:
        return report  # pure-library callable: unknown, no hazards
    analyzed_any = False
    for target in targets:
        report.hazards.extend(_closure_pickle_hazards(target))
        analyzed, src_hazards = _source_hazards(target)
        if not analyzed:
            continue
        analyzed_any = True
        report.hazards.extend(src_hazards)
    hard = {c for c, _ in report.hazards if c in ("PWT901", "PWT903")}
    if hard:
        report.verdict = IMPURE
    elif analyzed_any and not report.hazards:
        report.verdict = DETERMINISTIC
    else:
        report.verdict = UNKNOWN
    return report


def _reducer_callables(op: Any):
    """Stateful custom reducers carry user combiners inside library
    closures (internals/reducers.py stateful_single/stateful_many)."""
    for expr in op_exprs(op):
        for node in walk_expr(expr):
            if isinstance(node, ReducerExpression):
                reducer = node._reducer
                if str(getattr(reducer, "name", "")).startswith("stateful"):
                    compute = getattr(reducer, "compute", None)
                    if callable(compute):
                        yield compute


# stateful operators recompute UDFs on retraction and have their state
# snapshotted — the kinds the replay-safety findings key on (kept in
# sync with passes.STATEFUL_KINDS via tests/test_analysis.py)
def _stateful_kinds() -> set:
    from pathway_tpu.analysis.passes import STATEFUL_KINDS

    return STATEFUL_KINDS


def purity_pass(
    view: GraphView, result: AnalysisResult, *, workers: int = 1
) -> None:
    """Pass 12 — classify every reachable user callable and attach the
    verdict map at result.purity (the sanitizer's certification input)."""
    stateful_kinds = _stateful_kinds()
    verdicts: Dict[str, Dict[str, Any]] = {}
    reports: List[Tuple[Any, Any, PurityReport, Any]] = []

    # the reaches-a-stateful-operator query walks the graph, and only
    # the (rare) PWT903 suppression decision consumes it — resolve it
    # lazily per table instead of paying the walk at every apply site
    _snap_memo: Dict[int, bool] = {}

    def _snap(table, op):
        key = id(table)
        if key not in _snap_memo:
            _snap_memo[key] = op.kind in stateful_kinds or (
                view.reaches_kind(table, stateful_kinds)
            )
        return _snap_memo[key]

    for table, op, sites in view.apply_sites():
        if op.synthetic:
            continue
        for node in sites:
            report = classify_callable(node._fun)
            report.declared_deterministic = bool(node._deterministic)
            reports.append((table, view, report, op))
            verdicts[report.name] = report.to_dict()
    for table, op in view.ops(anchored_only=True):
        if op.synthetic or op.kind not in stateful_kinds:
            continue
        for compute in _reducer_callables(op):
            report = classify_callable(compute)
            reports.append((table, view, report, None))
            verdicts[report.name] = report.to_dict()

    for table, v, report, site_op in reports:
        # site_op None marks a stateful reducer: always on snapshot path
        snapshot_path = True if site_op is None else None
        trace = getattr(table, "_trace", None)
        operator = v.op_label(table)
        emitted = set()
        for code, why in report.hazards:
            if code == "PWT903":
                if snapshot_path is None:
                    snapshot_path = _snap(table, site_op)
                if not snapshot_path:
                    # side effects only corrupt replay when retractions
                    # / snapshots re-run the callable
                    continue
            if (code, why) in emitted:
                continue
            emitted.add((code, why))
            noun = {
                "PWT901": "is nondeterministic",
                "PWT902": "has order-unstable output",
                "PWT903": "has replay-unsafe side effects",
                "PWT904": "would disable its node's operator snapshot",
                "PWT905": "breaks fused-chain batch sharing",
            }[code]
            result.add(make_diag(
                code,
                f"UDF {report.name!r} {noun}: {why}",
                trace=trace,
                operator=operator,
                udf=report.name,
                verdict=report.verdict,
            ))
        if report.declared_deterministic and report.verdict == IMPURE:
            result.add(make_diag(
                "PWT999",
                f"UDF {report.name!r} is declared deterministic=True but "
                "purity analysis proves it impure: "
                + "; ".join(w for c, w in report.hazards
                            if c in ("PWT901", "PWT903")),
                trace=trace,
                operator=operator,
                udf=report.name,
            ))
    if verdicts:
        result.purity = {k: verdicts[k] for k in sorted(verdicts)}


def certified_deterministic(result: AnalysisResult) -> List[str]:
    """Callable names the static pass certifies — the PWT999 runtime
    contract set the sanitizer's replay hash is checked against."""
    purity = result.purity or {}
    return sorted(
        name for name, v in purity.items()
        if v.get("verdict") == DETERMINISTIC
    )


def verify_purity(engine: Any, result: AnalysisResult) -> None:
    """PWT999 parity gate, runtime half.  Mirrors verify_against_plan /
    verify_fusion: after the engine builds (and, in-process, after any
    previous armed run), a callable certified deterministic must never
    have tripped the sanitizer's replay-divergence hash.  The certified
    set is handed to the sanitizer so a *live* divergence of a certified
    callable is attributed as a parity violation, not just a UDF bug."""
    certified = certified_deterministic(result)
    engine.purity_certified = certified
    from pathway_tpu.internals import sanitizer as _sanitizer

    if not _sanitizer.ACTIVE:
        return
    tracker = _sanitizer.tracker()
    tracker.certify(certified)
    for v in tracker.recent_violations():
        if v.get("kind") == "replay_hash" and v.get("udf") in certified:
            result.add(make_diag(
                "PWT999",
                f"UDF {v['udf']!r} is certified deterministic but its "
                "replay hash diverged at runtime "
                f"(worker {v.get('worker')}): static purity analysis "
                "and the dataflow disagree",
                operator="sanitizer",
                udf=v["udf"],
            ))
