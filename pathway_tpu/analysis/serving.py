"""PWT7xx — serving-tier lints (internals/serving.py).

The serving micro-batcher only pays off when a coalesced query batch
actually collapses into one fused device program, and its batch window
only makes sense when it is small against the latency budget.  Both are
knowable at BUILD time:

  * PWT701 — serving is enabled but an anchored external index has no
    encoder config: queries reach the index as raw vectors/text with no
    `FusedEmbedSearch` path, so a coalesced batch still costs a
    per-query host loop instead of one jit — the batcher adds latency
    (the window) without buying dispatch fusion.
  * PWT702 — the serving batch window (`PATHWAY_SERVE_BATCH_WINDOW_MS`)
    is larger than the declared p99 SLO target (`pw.run(slo=...)` /
    `PATHWAY_SLO_P99_MS`): every query waits up to the window before the
    engine even sees it, so the SLO is unmeetable by configuration.
"""

from __future__ import annotations

from typing import Any, Optional

from pathway_tpu.analysis.diagnostics import AnalysisResult, make_diag


def _trace_or_none(table: Any):
    return getattr(table, "_trace", None)


def serving_pass(
    view: Any, result: AnalysisResult, *, slo: Optional[float] = None
) -> None:
    """PWT701/PWT702 over the anchored external-index ops.  Runs only
    when the serving tier is enabled and armed (a non-zero batch window);
    `slo` is the p99 target in milliseconds threaded from pw.run(slo=)
    with PATHWAY_SLO_P99_MS as the CLI-path fallback."""
    import os

    from pathway_tpu.internals import serving

    if not serving.ENABLED:
        return
    indexes = view.anchored_by_kind.get("external_index", ())
    if not indexes:
        return
    window_ms = serving.batch_window_ms()
    if window_ms <= 0:
        return

    for table, op in indexes:
        enc = op.info.get("encoder")
        if not isinstance(enc, dict):
            result.add(make_diag(
                "PWT701",
                "serving micro-batching is enabled but this external "
                "index has no encoder config, so a coalesced query batch "
                "cannot run as one fused embed+search program (ops/knn."
                "FusedEmbedSearch) — the batch window adds up to "
                f"{window_ms:g} ms of queueing without buying dispatch "
                "fusion; use an embedder-backed index factory or set "
                "PATHWAY_SERVE_BATCH_WINDOW_MS=0 for this job",
                trace=_trace_or_none(table),
                operator=view.op_label(table),
                batch_window_ms=window_ms,
                index=str(op.info.get("index") or ""),
            ))

    if slo is None:
        env_slo = os.environ.get("PATHWAY_SLO_P99_MS")
        if env_slo:
            try:
                slo = float(env_slo)
            except ValueError:
                slo = None
    if slo is not None and window_ms > float(slo):
        table, op = indexes[0]
        result.add(make_diag(
            "PWT702",
            f"serving batch window {window_ms:g} ms exceeds the declared "
            f"p99 SLO target {float(slo):g} ms: every query waits up to "
            "the full window before the engine sees it, so the target is "
            "unmeetable by configuration; shrink "
            "PATHWAY_SERVE_BATCH_WINDOW_MS well below the SLO (the "
            "size trigger PATHWAY_SERVE_MAX_BATCH still coalesces "
            "bursts)",
            trace=_trace_or_none(table),
            operator=view.op_label(table),
            batch_window_ms=window_ms,
            slo_p99_ms=float(slo),
        ))
